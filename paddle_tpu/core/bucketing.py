"""Gradient bucketing + cross-replica sharded weight update.

Reference parity: the role of imperative/reducer.cc's gradient Group
fusion (fuse_grad_size_in_MB coalescing before FusedAllReduce) and
DygraphShardingOptimizer's reduce-scatter/broadcast vocabulary — rebuilt
TPU-native per Xu et al., "Automatic Cross-Replica Sharding of Weight
Update in Data-Parallel Training" (arXiv:2004.13336):

  * gradients are coalesced into a small number of dtype-homogeneous
    1-D **buckets** (size-capped, zero-padded, with a stable
    param -> (bucket, offset) layout map);
  * each bucket is communicated with ONE `reduce_scatter` over the
    data-parallel mesh axes instead of one `psum` per parameter;
  * every rank owns a 1/dp **shard** of each bucket's parameters and
    optimizer moments (ZeRO-1/2 semantics), applies the optimizer update
    on its shard only, and `all_gather`s the updated parameters;
  * an opt-in compressed-collective mode (EQuARX, arXiv:2506.17615)
    sends the reduce-scatter payload compressed but ACCUMULATES in
    fp32 (all_to_all + local fp32 sum — the paper's accuracy note: the
    wire is compressed, the reduction is not). `comm_dtype='bfloat16'`
    is a plain cast; `comm_dtype='int8'` is BLOCK-SCALED: per-block
    abs-max fp32 scales ride beside the int8 payload on the wire
    (`quantize_blocks`), the param refresh all-gathers int8 shards +
    scales the same way, and the `ptpu_comm_*` gauges count the real
    wire bytes — payload, scales and padding reported separately
    (docs/performance.md#int8-wire).

Everything here is either host-side layout bookkeeping or pure
traced-code helpers used inside the engines' `shard_map` bodies; the
only state is the monitor gauges (`ptpu_comm_*`).

Communication/compute overlap (ISSUE 10, arXiv:2004.13336 §overlap +
arXiv:2112.01075 chunked collectives):

  * **layer-grouped buckets** — `layer_group_fn` keys buckets on the
    model-layer index parsed from the parameter name, so a bucket's
    gradients are complete as soon as its layers' backward finishes
    and its reduce-scatter is schedulable under the remaining
    backward compute (one dtype-global blob serializes everything
    behind the full backward);
  * **chunked collectives** — `reduce_scatter`/`all_gather` accept a
    `chunk` element cap (`PTPU_COMM_CHUNK`) that decomposes an
    oversized bucket's collective into schedulable pieces along the
    shard dimension; piece results concatenate to the EXACT unchunked
    shard/gather layout (bit-identical for uncompressed wires);
  * **overlap telemetry** — `publish_overlap_gauges` models per-step
    exposed vs hidden comm seconds (`ptpu_comm_overlap_*`), emits one
    profiler span per group, and `comm_snapshot()['comm_overlap']`
    is the JSON view the bench/dryrun records carry.
"""
import math
import re

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

# int8 symmetric range: scale = blockmax / 127, values clipped to ±127
INT8_BIN = 127.0
# per-block scale granularity for the int8 wire (elements); must
# divide the per-rank shard length, so the effective block per bucket
# is the largest divisor of shard_len <= this (env PTPU_COMM_BLOCK)
DEFAULT_COMM_BLOCK = 256
# scales travel as fp32 beside the int8 payload
SCALE_ITEMSIZE = 4
# deferred-gather prefetch window: how many param groups may be
# in flight (gathered but not yet consumed) ahead of first use
DEFAULT_PREFETCH_DEPTH = 2
# modeled per-rank interconnect bandwidth for the exposed/hidden comm
# model (v5e ICI-class, one direction) — a MODEL constant like the
# byte gauges, not a measurement
MODELED_ICI_BYTES_PER_S = 4.5e10


def resolve_comm_config(comm_dtype=None, bucket_mb=None):
    """Gradient-communication knobs, resolved kwarg -> env -> fleet
    strategy -> default (strategy.comm_dtype / fuse_grad_size_in_MB)."""
    import os
    strategy = None
    try:
        from ..distributed.fleet import fleet as _fleet
        strategy = _fleet._user_defined_strategy
    except Exception:
        strategy = None
    if comm_dtype is None:
        comm_dtype = os.environ.get('PTPU_COMM_DTYPE') or None
    if comm_dtype is None and strategy is not None:
        comm_dtype = strategy.comm_dtype
    if comm_dtype is not None:
        comm_dtype = jnp.dtype(comm_dtype)
    if bucket_mb is None:
        bucket_mb = float(os.environ.get('PTPU_BUCKET_MB', 0) or 0) or None
    if bucket_mb is None and strategy is not None:
        bucket_mb = float(strategy.fuse_grad_size_in_MB)
    if bucket_mb is None:
        bucket_mb = 32.0
    return comm_dtype, int(bucket_mb * 1024 * 1024)


def resolve_comm_block(block=None):
    """Block-scale granularity for the int8 wire, kwarg -> env ->
    default."""
    import os
    if block is None:
        block = int(os.environ.get('PTPU_COMM_BLOCK', 0) or 0) or None
    if block is None:
        block = DEFAULT_COMM_BLOCK
    return max(int(block), 1)


def resolve_overlap_config(overlap=None, prefetch=None, chunk=None):
    """Communication-overlap knobs, resolved kwarg -> env -> fleet
    strategy -> default:

      overlap  : bool — layer-grouped buckets + eager reduce-scatter +
                 deferred/prefetched param all-gather
                 (`PTPU_COMM_OVERLAP` / sharding_configs['comm_overlap']
                 / engine kwarg `comm_overlap`);
      prefetch : int — deferred-gather prefetch depth, groups in
                 flight ahead of first use (`PTPU_COMM_PREFETCH` /
                 sharding_configs['comm_overlap_prefetch'] /
                 engine kwarg `prefetch_depth`);
      chunk    : int — max full-bucket elements per collective
                 (`PTPU_COMM_CHUNK` / sharding_configs['comm_chunk'] /
                 engine kwarg `comm_chunk`; 0 = unchunked).
    """
    import os
    sc = {}
    try:
        from ..distributed.fleet import fleet as _fleet
        strategy = _fleet._user_defined_strategy
        if strategy is not None:
            sc = strategy.sharding_configs or {}
    except Exception:
        sc = {}
    if overlap is None:
        v = os.environ.get('PTPU_COMM_OVERLAP')
        if v is not None and v != '':
            overlap = v.lower() in ('1', 'true', 'yes')
    if overlap is None:
        overlap = sc.get('comm_overlap', False)
    # a PRESENT env var wins over the strategy even when its value is
    # falsy — PTPU_COMM_CHUNK=0 must be able to switch chunking off
    if prefetch is None:
        v = os.environ.get('PTPU_COMM_PREFETCH')
        if v is not None and v != '':
            prefetch = int(v)
    if prefetch is None:
        prefetch = sc.get('comm_overlap_prefetch')
    if prefetch is None:
        prefetch = DEFAULT_PREFETCH_DEPTH
    if chunk is None:
        v = os.environ.get('PTPU_COMM_CHUNK')
        if v is not None and v != '':
            chunk = int(v)
    if chunk is None:
        chunk = sc.get('comm_chunk')
    if chunk is None:
        chunk = 0
    return bool(overlap), max(int(prefetch), 1), max(int(chunk), 0)


# model-layer index: first numeric dotted path component of the
# parameter name ('gpt.decoder.layers.3.linear1.weight' -> 3)
_LAYER_IDX_RE = re.compile(r'(?:^|\.)(\d+)(?:\.|$)')


def layer_group_fn(name, shape=None, dtype=None):
    """Bucket grouping key for layer-grouped buckets: the FIRST numeric
    path component of the dotted parameter name (model layer / block
    order), 'stem' when the name carries none (embeddings, final
    norms, heads). Zero-padded so group keys sort in layer order."""
    m = _LAYER_IDX_RE.search(name)
    return f'layer{int(m.group(1)):05d}' if m else 'stem'


def ensure_overlap_xla_flags():
    """Best-effort XLA scheduling flags for comm/compute overlap: the
    latency-hiding scheduler + async collective fusion. XLA_FLAGS is
    read once at backend initialization and engine builds run after
    it, so for the flags to reach THIS process's compiler the
    launcher must export PTPU_COMM_OVERLAP=1 — core/flags.py honors
    that at first import, before any backend exists. This call (from
    an engine build) records the intent in the flags registry and
    updates the env for CHILD processes; explicit user settings (True
    or False) are respected and never overridden."""
    from . import flags as _flags
    want = {}
    for k in ('FLAGS_xla_latency_hiding_scheduler',
              'FLAGS_xla_async_collectives'):
        if _flags.flag(k) is None:
            want[k] = True
    if want:
        _flags.set_flags(want)


def block_len(n, want):
    """Largest divisor of `n` that is <= `want` — the effective scale
    block for a flat array of length n (blocks must tile the array and
    must not cross shard boundaries, so callers pass the SHARD
    length)."""
    b = min(int(want), int(n))
    while b > 1 and n % b:
        b -= 1
    return max(b, 1)


# ---------------------------------------------------------------------------
# layout
# ---------------------------------------------------------------------------
class Slot:
    """One parameter's place inside a bucket."""
    __slots__ = ('name', 'shape', 'dtype', 'bucket', 'offset', 'size')

    def __init__(self, name, shape, dtype, bucket, offset, size):
        self.name = name
        self.shape = tuple(shape)
        self.dtype = jnp.dtype(dtype)
        self.bucket = bucket
        self.offset = offset
        self.size = size

    def to_dict(self):
        return {'name': self.name, 'shape': list(self.shape),
                'dtype': str(self.dtype), 'bucket': self.bucket,
                'offset': self.offset, 'size': self.size}


class Bucket:
    __slots__ = ('index', 'dtype', 'group', 'slots', 'used', 'size')

    def __init__(self, index, dtype, group):
        self.index = index
        self.dtype = jnp.dtype(dtype)
        self.group = group
        self.slots = []
        self.used = 0      # elements occupied by real parameters
        self.size = 0      # padded length (set at finalize)

    @property
    def pad(self):
        return self.size - self.used

    def nbytes(self, dtype=None):
        return self.size * jnp.dtype(dtype or self.dtype).itemsize


class BucketLayout:
    """Stable param -> (bucket, offset) map over dtype-homogeneous,
    size-capped, padded 1-D buckets.

    Built from an ORDERED {name: (shape, dtype)} description of the
    LOCAL (per-rank) parameter arrays; the greedy fill preserves
    insertion order, opens a new bucket when the byte cap would be
    exceeded (a single parameter larger than the cap gets its own
    bucket), and pads every bucket to a multiple of `pad_to` so a
    1/pad_to shard is always an integral slice.
    """

    def __init__(self, buckets, slots, pad_to):
        self.buckets = buckets
        self.slots = slots
        self.pad_to = pad_to

    @classmethod
    def build(cls, named_shapes, bucket_bytes=32 * 1024 * 1024, pad_to=1,
              group_fn=None):
        """named_shapes: ordered {name: (shape, dtype)}."""
        pad_to = max(int(pad_to), 1)
        buckets, slots = [], {}
        open_by_key = {}
        for name, (shape, dtype) in named_shapes.items():
            dtype = jnp.dtype(dtype)
            group = group_fn(name, shape, dtype) if group_fn else None
            size = int(np.prod(shape)) if len(shape) else 1
            key = (group, str(dtype))
            b = open_by_key.get(key)
            if b is not None and \
                    (b.used + size) * dtype.itemsize > bucket_bytes \
                    and b.used > 0:
                b = None   # cap exceeded: close it
            if b is None:
                b = Bucket(len(buckets), dtype, group)
                buckets.append(b)
                open_by_key[key] = b
            slot = Slot(name, shape, dtype, b.index, b.used, size)
            b.slots.append(slot)
            slots[name] = slot
            b.used += size
        for b in buckets:
            b.size = int(math.ceil(b.used / pad_to) * pad_to)
        return cls(buckets, slots, pad_to)

    # -- flatten / unflatten (pure; usable under jit and on host) -----------
    def flatten(self, tree, cast=None):
        """{name: array} -> [one 1-D padded array per bucket]."""
        out = []
        for b in self.buckets:
            parts = [jnp.reshape(tree[s.name], (-1,)).astype(cast or b.dtype)
                     for s in b.slots]
            if b.pad:
                parts.append(jnp.zeros((b.pad,), cast or b.dtype))
            out.append(parts[0] if len(parts) == 1
                       else jnp.concatenate(parts))
        return out

    def unflatten(self, flats, cast_slots=False):
        """[per-bucket 1-D arrays] -> {name: array of slot shape}."""
        tree = {}
        for b, flat in zip(self.buckets, flats):
            for s in b.slots:
                a = lax.slice_in_dim(flat, s.offset, s.offset + s.size)
                if cast_slots:
                    a = a.astype(s.dtype)
                tree[s.name] = jnp.reshape(a, s.shape)
        return tree

    def names(self):
        return list(self.slots)

    def total_elements(self):
        return sum(s.size for s in self.slots.values())

    def total_padded(self):
        return sum(b.size for b in self.buckets)

    def nbytes(self, dtype=None):
        return sum(b.nbytes(dtype) for b in self.buckets)

    def describe(self):
        """JSON-ready layout map (the stable param->(bucket,offset)
        contract, round-trippable by tests/tools)."""
        return {
            'pad_to': self.pad_to,
            'buckets': [{'index': b.index, 'dtype': str(b.dtype),
                         'group': b.group if b.group is None
                         else str(b.group),
                         'used': b.used, 'size': b.size,
                         'slots': [s.to_dict() for s in b.slots]}
                        for b in self.buckets],
        }


# ---------------------------------------------------------------------------
# block-scaled int8 quantization (pure; used inside shard_map bodies)
# ---------------------------------------------------------------------------
def quantize_blocks(flat, block):
    """Symmetric abs-max int8 quantization of a 1-D array in blocks of
    `block` elements (must divide len(flat)). Returns (int8 [L],
    fp32 scales [L // block]); dequantized value = q * scale."""
    blk = flat.astype(jnp.float32).reshape(-1, block)
    amax = jnp.max(jnp.abs(blk), axis=1, keepdims=True)
    scale = jnp.maximum(amax, 1e-30) / INT8_BIN
    q = jnp.clip(jnp.round(blk / scale), -INT8_BIN, INT8_BIN) \
        .astype(jnp.int8)
    return q.reshape(-1), scale.reshape(-1)


def dequantize_blocks(q, scales, block):
    """Inverse of quantize_blocks (fp32 result)."""
    blk = q.reshape(-1, block).astype(jnp.float32)
    return (blk * scales.reshape(-1, 1)).reshape(-1)


def _is_int8(comm_dtype):
    return comm_dtype is not None and jnp.dtype(comm_dtype) == jnp.int8


# ---------------------------------------------------------------------------
# collectives over buckets (call inside shard_map bodies)
# ---------------------------------------------------------------------------
def axes_size(mesh, axes):
    n = 1
    for a in axes:
        n *= mesh.shape[a]
    return n


def shard_index(axes):
    """Combined shard index over `axes` (major-to-minor in the given
    order — matches `lax.psum_scatter` over the same axis tuple and a
    PartitionSpec placing `tuple(axes)` on dim 0)."""
    idx = jnp.asarray(0, jnp.int32)
    for a in axes:
        idx = idx * lax.psum(1, a) + lax.axis_index(a)
    return idx


def take_shard(flat, axes, n_shards):
    """Slice this rank's 1/n shard out of a (replicated) flat bucket."""
    shard_len = flat.shape[0] // n_shards
    return lax.dynamic_slice_in_dim(
        flat, shard_index(axes) * shard_len, shard_len, axis=0)


def _chunk_spans(shard_len, n_shards, chunk):
    """Split points for chunked collectives (arXiv:2112.01075): `chunk`
    caps the FULL-bucket elements per collective, so the piece width
    along the shard dimension is chunk // n_shards. Returns a list of
    (start, width) spans over [0, shard_len), or None when chunking is
    off / the bucket already fits one chunk."""
    if not chunk or n_shards < 1:
        return None
    w = max(int(chunk) // max(int(n_shards), 1), 1)
    if shard_len <= w:
        return None
    spans, s = [], 0
    while s < shard_len:
        spans.append((s, min(w, shard_len - s)))
        s += spans[-1][1]
    return spans


def reduce_scatter(flat, axes, n_shards, comm_dtype=None, mean=True,
                   block=None, chunk=None):
    """SUM-reduce a flat bucket over `axes` and keep this rank's 1/n
    shard. With `comm_dtype` narrower than fp32 the payload moves
    compressed but the reduction runs in fp32 (all_to_all + local fp32
    accumulate — EQuARX's compressed-wire / uncompressed-math split);
    otherwise a native `psum_scatter`. `comm_dtype='int8'` is
    block-scaled: per-block abs-max fp32 scales are computed on the
    flat bucket (block = largest divisor of the shard length <=
    `block`, default DEFAULT_COMM_BLOCK) and travel beside the int8
    payload in a second all_to_all. Returns an fp32 shard (the
    optimizer update math dtype) scaled to the mean when `mean`.

    `chunk` (elements, `PTPU_COMM_CHUNK`) decomposes an oversized
    bucket into multiple collectives over shard-dimension slices —
    schedulable pieces the latency-hiding scheduler can interleave
    with compute. Each element is still reduced across the same ranks
    in the same order, and pieces concatenate to the exact unchunked
    shard layout, so the uncompressed result is bit-identical."""
    axes = tuple(axes)
    spans = _chunk_spans(flat.shape[0] // n_shards, n_shards, chunk)
    if spans:
        view = flat.reshape(n_shards, -1)
        return jnp.concatenate([
            reduce_scatter(
                lax.slice_in_dim(view, s, s + w, axis=1).reshape(-1),
                axes, n_shards, comm_dtype=comm_dtype, mean=mean,
                block=block)
            for s, w in spans])
    if _is_int8(comm_dtype):
        shard_len = flat.shape[0] // n_shards
        b = block_len(shard_len, resolve_comm_block(block))
        q, scales = quantize_blocks(flat, b)
        q_ch = lax.all_to_all(q.reshape(n_shards, shard_len), axes,
                              split_axis=0, concat_axis=0)
        s_ch = lax.all_to_all(scales.reshape(n_shards, -1), axes,
                              split_axis=0, concat_axis=0)
        deq = q_ch.reshape(n_shards, -1, b).astype(jnp.float32) \
            * s_ch[:, :, None]
        shard = jnp.sum(deq.reshape(n_shards, shard_len), axis=0)
    elif comm_dtype is not None and \
            jnp.dtype(comm_dtype) != jnp.float32:
        if jnp.dtype(comm_dtype) != flat.dtype:
            flat = flat.astype(comm_dtype)
        # compress -> all_to_all (wire in comm_dtype) -> fp32 accumulate
        chunks = lax.all_to_all(flat.reshape(n_shards, -1), axes,
                                split_axis=0, concat_axis=0)
        shard = jnp.sum(chunks.astype(jnp.float32), axis=0)
    else:
        if comm_dtype is not None and jnp.dtype(comm_dtype) != flat.dtype:
            flat = flat.astype(comm_dtype)
        shard = lax.psum_scatter(flat, axes, scatter_dimension=0,
                                 tiled=True).astype(jnp.float32)
    if mean:
        shard = shard * (1.0 / n_shards)
    return shard


def all_gather(shard, axes, comm_dtype=None, block=None, chunk=None,
               n_shards=None):
    """Reassemble the full flat bucket from per-rank shards (reverse
    axis order of the matching reduce_scatter/take_shard). With
    `comm_dtype='int8'` the param refresh is scale-carrying: each rank
    quantizes its updated shard block-wise, int8 payload + fp32 scales
    all-gather together, and every rank dequantizes — all ranks see
    the SAME (quantized) params, and the sharded optimizer state keeps
    the fp32 master, so the rounding does not accumulate step over
    step. Result dtype follows the input shard.

    `chunk` + `n_shards` enable the chunked variant (mirror of
    reduce_scatter's): gather shard slices piecewise, then interleave
    the [n_shards, w] pieces back into the exact rank-major flat
    layout the unchunked gather produces."""
    axes = tuple(axes)
    if n_shards:
        spans = _chunk_spans(shard.shape[0], n_shards, chunk)
        if spans:
            pieces = [all_gather(
                lax.slice_in_dim(shard, s, s + w), axes,
                comm_dtype=comm_dtype, block=block)
                for s, w in spans]
            return jnp.concatenate(
                [p.reshape(n_shards, -1) for p in pieces],
                axis=1).reshape(-1)
    if not _is_int8(comm_dtype):
        for a in reversed(axes):
            shard = lax.all_gather(shard, a, axis=0, tiled=True)
        return shard
    dt = shard.dtype
    b = block_len(shard.shape[0], resolve_comm_block(block))
    q, scales = quantize_blocks(shard, b)
    for a in reversed(axes):
        q = lax.all_gather(q, a, axis=0, tiled=True)
        scales = lax.all_gather(scales, a, axis=0, tiled=True)
    return dequantize_blocks(q, scales, b).astype(dt)


def gather_groups(shards, axes, n_shards, comm_dtype=None, block=None,
                  chunk=None, prefetch=None):
    """Deferred/prefetched param all-gather over a list of 1-D bucket
    shards (call inside shard_map bodies): gathers groups IN ORDER,
    and with `prefetch` chains gather g behind gather g-prefetch via
    `optimization_barrier`, so at most `prefetch` full groups are in
    flight beyond the shards. The ONE home of the overlap gather
    contract — both engines' step-top materialization and their
    taps-mode re-gathers go through here."""
    out = []
    for gi, sh in enumerate(shards):
        if prefetch and gi >= prefetch:
            sh = lax.optimization_barrier((sh, out[gi - prefetch]))[0]
        out.append(all_gather(sh, axes, comm_dtype=comm_dtype,
                              block=block, chunk=chunk,
                              n_shards=n_shards))
    return out


# ---------------------------------------------------------------------------
# sharded weight update
# ---------------------------------------------------------------------------
def elementwise(optimizer):
    """True when the optimizer's update rule is strictly per-element, so
    applying it to a flattened shard is bit-equivalent to per-parameter
    application (Lamb/LARS/DGC use per-PARAMETER norms/quantiles and
    must keep the per-param path)."""
    return bool(getattr(optimizer, '_elementwise', False))


def init_bucket_state(optimizer, bucket, param_flat32, force_master=False):
    """Flat optimizer state for one bucket (host-side arrays).

    param_flat32: the bucket's initial parameter values, flattened to
    fp32 (numpy). Returns {state_key: np.ndarray}; adds the fp32
    'master' copy for low-precision buckets under multi_precision.
    `force_master` adds it for fp32 buckets too — required when the
    param all-gather wire is quantized (comm_dtype='int8'): the
    sharded master stays the exact trajectory and only the gathered
    working copy is rounded, so wire error never feeds back into the
    optimizer state. It therefore overrides multi_precision=False —
    without the master the int8-rounded params would BE the state and
    the invariant would silently break."""
    from .tensor import Tensor
    st = optimizer.init_state(Tensor(jnp.zeros((bucket.size,),
                                               jnp.float32)))
    st = {k: np.asarray(v) for k, v in st.items()}
    if force_master or (bucket.dtype != jnp.float32
                        and getattr(optimizer, '_multi_precision', True)):
        st['master'] = np.asarray(param_flat32, np.float32)
    return st


def grad_stats(flat):
    """One-pass (sum of squares, nonfinite count) of a flat gradient
    array — the two scalars the step needs before touching params
    (global-clip contribution + GradScaler found-inf). Routes to the
    fused Pallas kernel (ops/pallas/fused_optimizer.py) on TPU, one
    fused XLA reduction pair on the reference path. Both return fp32
    scalars; nonfinite gradients poison the sum exactly like
    jnp.sum(g*g) does."""
    from ..ops.pallas import fused_optimizer as FO
    if FO.use_fused_stats():
        return FO.grad_stats_pallas(flat)
    x = flat.astype(jnp.float32)
    return jnp.sum(x * x), jnp.sum((~jnp.isfinite(x))
                                   .astype(jnp.float32))


def shard_update(optimizer, p_shard, g32_shard, st, lr, prefactor=None,
                 found_inf=None):
    """One bucket-shard optimizer update with fp32-master handling —
    the flat twin of the engines' `_update_one` (same rule order:
    prefactor multiply, decay-into-grad, update in fp32, master
    ride-along). `p_shard` is the shard in PARAMETER dtype; returns
    (new_p_shard, new_state).

    `prefactor` (optional scalar) is the combined unscale x global-clip
    multiplier applied to the gradient first; `found_inf` (optional
    bool scalar) makes the whole update a no-op (params and every state
    entry keep their old values — the GradScaler skip). Both fold into
    the SAME pass on the fused route (ops/pallas/fused_optimizer.py,
    one Pallas kernel per bucket shard: unscale + clip + moments +
    param step + master cast in one read/write per operand); the
    reference path below applies them as the familiar XLA op chain."""
    from ..ops.pallas import fused_optimizer as FO
    if FO.use_fused_update(optimizer):
        return FO.fused_shard_update(optimizer, p_shard, g32_shard, st,
                                     lr, prefactor=prefactor,
                                     found_inf=found_inf)
    low = p_shard.dtype != jnp.float32
    st = dict(st)
    master = st.pop('master', None)
    p32 = master if master is not None else (
        p_shard.astype(jnp.float32) if low else p_shard)
    if prefactor is not None:
        g32_shard = g32_shard * prefactor
    wd = getattr(optimizer, '_weight_decay', None)
    if wd and optimizer._decay_into_grad():
        g32_shard = g32_shard + wd * p32
    new32, ns = optimizer.update(p32, g32_shard, st, lr)
    ns = dict(ns)
    if master is not None or (low and getattr(optimizer,
                                              '_multi_precision', True)):
        ns['master'] = new32
    new_p = new32.astype(p_shard.dtype)
    if found_inf is not None:
        old = dict(st)
        if master is not None:
            old['master'] = master
        new_p = jnp.where(found_inf, p_shard, new_p)
        ns = {k: (jnp.where(found_inf, old[k], v) if k in old else v)
              for k, v in ns.items()}
        if 'master' in ns and master is None:
            ns['master'] = jnp.where(found_inf, p32, ns['master'])
    return new_p, ns


def flat_functional_apply(optimizer, layout, params, grads, flat_states,
                          lr):
    """Whole-model bucketed update for the single-program path
    (jit.TrainStep): semantics of Optimizer.functional_apply — global
    grad clip, weight decay, per-param rule — but applied to the
    flattened buckets so the optimizer phase is a handful of fused
    kernels instead of one chain per parameter.

    flat_states: [per-bucket state dict]. Returns (new_params,
    new_flat_states)."""
    from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                           ClipGradByValue)
    clip = optimizer._grad_clip
    if isinstance(clip, ClipGradByNorm):
        # per-PARAM norms: clip before flattening
        cn = clip.clip_norm
        def _clip1(g):
            n = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
            return g * jnp.minimum(cn / jnp.maximum(n, 1e-12),
                                   1.0).astype(g.dtype)
        grads = {n: _clip1(g) for n, g in grads.items()}

    flat_grads = [g.astype(jnp.float32)
                  for g in layout.flatten(grads, cast=jnp.float32)]
    factor = None
    if isinstance(clip, ClipGradByGlobalNorm):
        # one fused stats pass per bucket (Pallas on TPU) feeds the
        # clip factor; the multiply itself fuses into the update pass
        sq = sum(grad_stats(g)[0] for g in flat_grads)
        gn = jnp.sqrt(sq)
        factor = clip.clip_norm / jnp.maximum(gn, clip.clip_norm)
    elif isinstance(clip, ClipGradByValue):
        flat_grads = [jnp.clip(g, clip.min, clip.max) for g in flat_grads]

    flat_params = layout.flatten(params)
    new_flats, new_states = [], []
    for b, pf, gf, st in zip(layout.buckets, flat_params, flat_grads,
                             flat_states):
        np_, ns = shard_update(optimizer, pf, gf, st, lr,
                               prefactor=factor)
        new_flats.append(np_)
        new_states.append(ns)
    new_params = {}
    for b, flat in zip(layout.buckets, new_flats):
        for s in b.slots:
            new_params[s.name] = jnp.reshape(
                lax.slice_in_dim(flat, s.offset, s.offset + s.size),
                s.shape)
    return new_params, new_states


# ---------------------------------------------------------------------------
# flat <-> per-param optimizer-state conversion (checkpoint contract)
# ---------------------------------------------------------------------------
def flat_states_to_named(layout, flat_states):
    """[per-bucket {key: host flat array}] -> {param: {key: array}} in
    the engines' per-parameter state_dict schema. Vector states slice
    per slot; scalar states (beta powers) replicate per param."""
    out = {}
    for b, st in zip(layout.buckets, flat_states):
        for s in b.slots:
            d = {}
            for k, v in st.items():
                v = np.asarray(v)
                if v.ndim >= 1 and v.shape[0] == b.size:
                    d[k] = v[s.offset:s.offset + s.size] \
                        .reshape(s.shape).copy()
                else:
                    d[k] = v.copy()
            out[s.name] = d
    return out


def named_states_to_flat(layout, named_states, template):
    """Inverse of flat_states_to_named. `template`: [per-bucket
    {key: host array}] giving each state's flat shape/dtype (used as
    the fallback for params missing from the checkpoint)."""
    out = []
    for b, tmpl in zip(layout.buckets, template):
        st = {k: np.array(v, copy=True) for k, v in tmpl.items()}
        for s in b.slots:
            src = named_states.get(s.name)
            if not src:
                continue
            for k, v in src.items():
                if k not in st:
                    continue
                v = np.asarray(v)
                if st[k].ndim >= 1 and st[k].shape[0] == b.size:
                    st[k][s.offset:s.offset + s.size] = \
                        v.reshape(-1).astype(st[k].dtype)
                else:
                    st[k] = v.astype(st[k].dtype)
        out.append(st)
    return out


# ---------------------------------------------------------------------------
# telemetry: ptpu_comm_* gauges
# ---------------------------------------------------------------------------
def _bucket_wire(b, n_shards, comm_dtype=None, block=None):
    """Per-bucket wire-byte split, the ONE home of the byte
    convention (wire_bytes totals and the overlap seconds model both
    read it): reduce_scatter moves gradients in `comm_dtype`
    (param/bucket dtype when None); all_gather moves updated params
    in their storage dtype; int8 mode moves int8 + fp32 block scales
    on both legs."""
    int8 = _is_int8(comm_dtype)
    rs_item = 1 if int8 else jnp.dtype(comm_dtype or b.dtype).itemsize
    ag_item = 1 if int8 else b.dtype.itemsize
    scale_bytes = 0
    if int8:
        eb = block_len(max(b.size // max(n_shards, 1), 1),
                       resolve_comm_block(block))
        scale_bytes = (b.size // eb) * SCALE_ITEMSIZE
    return {'reduce_scatter': {'payload': b.used * rs_item,
                               'scale': scale_bytes,
                               'pad': b.pad * rs_item},
            'all_gather': {'payload': b.used * ag_item,
                           'scale': scale_bytes,
                           'pad': b.pad * ag_item}}


def wire_bytes(layout, n_shards, comm_dtype=None, block=None):
    """Real per-rank wire bytes per step for a bucket layout, split
    into parameter payload vs overhead (the ISSUE-7 accounting audit):

      {'reduce_scatter'|'all_gather':
          {'payload': <real-parameter bytes on the wire>,
           'scale':   <block-scale sidecar bytes (int8 mode only)>,
           'pad':     <zero-padding bytes>,
           'total':   payload + scale + pad}}
    """
    out = {'reduce_scatter': {'payload': 0, 'scale': 0, 'pad': 0},
           'all_gather': {'payload': 0, 'scale': 0, 'pad': 0}}
    for b in layout.buckets:
        per = _bucket_wire(b, n_shards, comm_dtype, block)
        for op, parts in out.items():
            for k in parts:
                parts[k] += per[op][k]
    for op in out.values():
        op['total'] = op['payload'] + op['scale'] + op['pad']
    return out


def publish_comm_gauges(layout, engine, n_shards, comm_dtype=None,
                        enabled=True, block=None):
    """Publish the per-step communication model for a bucket layout.

    Byte convention (docs/performance.md): a ring allreduce moves
    2x the payload per rank (its reduce-scatter + all-gather
    decomposition); reduce_scatter and all_gather move 1x each. The
    baseline scheme is the per-parameter psum of fp32 gradients — the
    dtype the reduction math runs in, which is what the compressed mode
    preserves (EQuARX) — so `bucketed` vs `per_param_psum_fp32` is an
    equal-accuracy comparison. Wire bytes are REAL bytes: int8 mode
    counts the fp32 block-scale sidecars and the bucket zero-padding,
    reported separately from the parameter payload so the compression
    claim is auditable. Gauges are modeled at trace/build time (the
    compiled step replays the same collectives every step)."""
    from . import monitor as _m
    elems = layout.total_elements()
    padded = layout.total_padded()
    wires = wire_bytes(layout, n_shards, comm_dtype, block)
    rs_bytes = wires['reduce_scatter']['total']
    ag_bytes = wires['all_gather']['total']
    baseline = 2 * elems * 4    # per-param fp32 allreduce, 2x payload
    g = _m.gauge
    g('ptpu_comm_buckets', help='gradient buckets per step',
      labelnames=('engine',)).set(len(layout.buckets), engine=engine)
    g('ptpu_comm_bucket_pad_elements',
      help='zero-padding elements across buckets',
      labelnames=('engine',)).set(padded - elems, engine=engine)
    g('ptpu_comm_shards', help='weight-update shard count (dp degree)',
      labelnames=('engine',)).set(n_shards, engine=engine)
    g('ptpu_comm_bytes_per_step',
      help='modeled per-rank wire bytes per step, by collective '
           '(payload + block scales + padding)',
      labelnames=('engine', 'op')).set(rs_bytes, engine=engine,
                                       op='reduce_scatter')
    g('ptpu_comm_bytes_per_step',
      labelnames=('engine', 'op')).set(ag_bytes, engine=engine,
                                       op='all_gather')
    for op in ('reduce_scatter', 'all_gather'):
        g('ptpu_comm_payload_bytes_per_step',
          help='real-parameter bytes on the wire per rank per step '
               '(scales and padding excluded)',
          labelnames=('engine', 'op')).set(
              wires[op]['payload'], engine=engine, op=op)
        for kind in ('scale', 'pad'):
            g('ptpu_comm_overhead_bytes_per_step',
              help='non-payload wire bytes per rank per step: block '
                   'scales (int8 mode) and bucket zero-padding',
              labelnames=('engine', 'op', 'kind')).set(
                  wires[op][kind], engine=engine, op=op, kind=kind)
    # report the EFFECTIVE block (smallest across buckets), not the
    # requested one: block_len() shrinks to a divisor of the shard
    # length, and an honest gauge is what keeps the scale-overhead
    # numbers auditable (engine layouts pad to n_shards*8, so this
    # never collapses below 8)
    eff_block = 0
    if _is_int8(comm_dtype) and layout.buckets:
        want = resolve_comm_block(block)
        eff_block = min(
            block_len(max(b.size // max(n_shards, 1), 1), want)
            for b in layout.buckets)
    g('ptpu_comm_block_elements',
      help='int8 block-scale granularity in elements — smallest '
           'EFFECTIVE block across buckets (0 = not block-scaled)',
      labelnames=('engine',)).set(eff_block, engine=engine)
    g('ptpu_comm_modeled_bytes_per_step',
      help='modeled per-rank wire bytes per step, by scheme '
           '(allreduce counted 2x payload)',
      labelnames=('engine', 'scheme')).set(
          baseline, engine=engine, scheme='per_param_psum_fp32')
    g('ptpu_comm_modeled_bytes_per_step',
      labelnames=('engine', 'scheme')).set(
          rs_bytes + ag_bytes, engine=engine, scheme='bucketed')
    g('ptpu_comm_compressed_fraction',
      help='1 - reduce_scatter parameter payload / fp32 payload',
      labelnames=('engine',)).set(
          1.0 - wires['reduce_scatter']['payload'] / max(elems * 4, 1),
          engine=engine)
    g('ptpu_comm_enabled',
      help='1 when the bucketed rs/ag path is compiled into the step '
           '(0: modeled only — dp degree 1 or legacy path)',
      labelnames=('engine',)).set(1 if enabled else 0, engine=engine)
    _m.counter('ptpu_collective_calls_total',
               help='collective API invocations',
               labelnames=('op',)).inc(
                   2 * len(layout.buckets) if enabled else 0,
                   op='bucket_rs_ag')


def _bucket_wire_totals(b, n_shards, comm_dtype=None, block=None):
    """(reduce_scatter bytes, all_gather bytes) for ONE bucket —
    payload + block scales + padding, straight from _bucket_wire so
    the overlap seconds model can never drift from the byte gauges."""
    per = _bucket_wire(b, n_shards, comm_dtype, block)
    return (sum(per['reduce_scatter'].values()),
            sum(per['all_gather'].values()))


def overlap_seconds(layout, n_shards, comm_dtype=None, block=None,
                    enabled=True):
    """Trace-time exposed/hidden comm model for a bucket layout:
    (total_s, exposed_s, hidden_s) at MODELED_ICI_BYTES_PER_S.

    With overlap compiled in, a group's reduce-scatter hides under the
    backward of the layers still to come, and its next-step all-gather
    hides under the forward of the groups before it — EXCEPT group 0
    (layer order): its grads complete last (backward ends at layer 0),
    so its reduce-scatter has no compute left to hide under, and its
    params are the first the forward needs, so its gather is on the
    critical path. Exposed = group 0's rs+ag; hidden = the rest. With
    overlap off (or a single group) every byte is exposed."""
    per = [_bucket_wire_totals(b, n_shards, comm_dtype, block)
           for b in layout.buckets]
    total = sum(rs + ag for rs, ag in per) / MODELED_ICI_BYTES_PER_S
    if not enabled or len(per) <= 1:
        return total, total, 0.0
    exposed = sum(per[0]) / MODELED_ICI_BYTES_PER_S
    return total, exposed, total - exposed


def publish_overlap_gauges(layout, engine, n_shards, comm_dtype=None,
                           enabled=True, prefetch=None, chunk=0,
                           block=None):
    """Publish the ptpu_comm_overlap_* gauges for a bucket layout and
    emit one profiler span per group (modeled bytes/seconds ride as
    span args — the compiled step replays the same collectives every
    step, so the model is trace-time like the byte gauges)."""
    from . import monitor as _m
    from .. import profiler as _prof
    prefetch = int(prefetch or DEFAULT_PREFETCH_DEPTH)
    groups = len(layout.buckets)
    total_s, exposed_s, hidden_s = overlap_seconds(
        layout, n_shards, comm_dtype, block, enabled=enabled)
    g = _m.gauge
    g('ptpu_comm_overlap_enabled',
      help='1 when the overlapped (layer-grouped, deferred-gather) '
           'comm schedule is compiled into the step',
      labelnames=('engine',)).set(1 if enabled else 0, engine=engine)
    g('ptpu_comm_overlap_groups',
      help='layer-grouped gradient buckets per step',
      labelnames=('engine',)).set(groups, engine=engine)
    g('ptpu_comm_overlap_groups_in_flight',
      help='param groups gathered ahead of first use (prefetch window '
           'actually achievable with this layout)',
      labelnames=('engine',)).set(
          min(prefetch, groups) if enabled else 0, engine=engine)
    g('ptpu_comm_overlap_prefetch_depth',
      help='deferred-gather prefetch depth knob',
      labelnames=('engine',)).set(prefetch, engine=engine)
    g('ptpu_comm_overlap_chunk_elements',
      help='PTPU_COMM_CHUNK collective decomposition cap '
           '(0 = unchunked)',
      labelnames=('engine',)).set(int(chunk or 0), engine=engine)
    g('ptpu_comm_overlap_total_comm_seconds',
      help='modeled per-step collective seconds at the ICI model '
           'bandwidth',
      labelnames=('engine',)).set(total_s, engine=engine)
    g('ptpu_comm_overlap_exposed_comm_seconds',
      help='modeled comm seconds NOT hidden under compute (group 0 '
           'rs+ag when overlapped; everything when not)',
      labelnames=('engine',)).set(exposed_s, engine=engine)
    g('ptpu_comm_overlap_hidden_comm_seconds',
      help='modeled comm seconds hidden under backward/forward '
           'compute',
      labelnames=('engine',)).set(hidden_s, engine=engine)
    for b in layout.buckets:
        rs_b, ag_b = _bucket_wire_totals(b, n_shards, comm_dtype, block)
        with _prof.RecordEvent(
                f'comm::group{b.index}', event_type='comm',
                engine=engine, group=str(b.group), bucket=b.index,
                rs_bytes=rs_b, ag_bytes=ag_b,
                modeled_seconds=round(
                    (rs_b + ag_b) / MODELED_ICI_BYTES_PER_S, 9),
                hidden=bool(enabled and groups > 1 and b.index != 0)):
            pass


def comm_snapshot():
    """JSON-ready view of every ptpu_comm_* gauge (for
    StepTelemetry.snapshot / bench records / health_dump)."""
    from . import monitor as _m
    reg = _m.metrics()
    out = {}
    for name in ('ptpu_comm_buckets', 'ptpu_comm_bucket_pad_elements',
                 'ptpu_comm_shards', 'ptpu_comm_bytes_per_step',
                 'ptpu_comm_payload_bytes_per_step',
                 'ptpu_comm_overhead_bytes_per_step',
                 'ptpu_comm_block_elements',
                 'ptpu_comm_modeled_bytes_per_step',
                 'ptpu_comm_compressed_fraction', 'ptpu_comm_enabled',
                 'ptpu_comm_overlap_enabled', 'ptpu_comm_overlap_groups',
                 'ptpu_comm_overlap_groups_in_flight',
                 'ptpu_comm_overlap_prefetch_depth',
                 'ptpu_comm_overlap_chunk_elements',
                 'ptpu_comm_overlap_total_comm_seconds',
                 'ptpu_comm_overlap_exposed_comm_seconds',
                 'ptpu_comm_overlap_hidden_comm_seconds'):
        m = reg.get(name)
        if m is None:
            continue
        series = {}
        for key, child in m._series().items():
            label = ','.join(f'{ln}={lv}' for ln, lv
                             in zip(m.labelnames, key))
            series[label or '()'] = child.value()
        out[name] = series
    # derived headline: the acceptance number. This is a trace-time
    # MODEL either way; comm_bytes_drop_enabled says whether the rs/ag
    # path is actually compiled into the step (dp>1) or the engine only
    # modeled it (dp=1 / use_buckets=False) — consumers must not read a
    # modeled-only drop as realized wire savings.
    modeled = out.get('ptpu_comm_modeled_bytes_per_step') or {}
    enabled = out.get('ptpu_comm_enabled') or {}
    payload = out.get('ptpu_comm_payload_bytes_per_step') or {}
    overhead = out.get('ptpu_comm_overhead_bytes_per_step') or {}
    for eng in {k.split(',')[0].split('=', 1)[1]
                for k in modeled if k.startswith('engine=')}:
        base = modeled.get(f'engine={eng},scheme=per_param_psum_fp32')
        new = modeled.get(f'engine={eng},scheme=bucketed')
        if base and new is not None:
            out.setdefault('comm_bytes_drop_vs_per_param_psum', {})[
                eng] = round(1.0 - new / base, 4)
            out.setdefault('comm_bytes_drop_enabled', {})[eng] = bool(
                enabled.get(f'engine={eng}'))
        # wire-byte audit (ISSUE 7): real-parameter payload vs scale /
        # padding overhead, and the payload-vs-payload compression
        # factor — the "4x" claim measured on like bytes, with the
        # sidecar cost visible right beside it instead of hidden in it
        pay = sum(v for k, v in payload.items()
                  if k.startswith(f'engine={eng},'))
        ov_scale = sum(v for k, v in overhead.items()
                       if k.startswith(f'engine={eng},')
                       and k.endswith('kind=scale'))
        ov_pad = sum(v for k, v in overhead.items()
                     if k.startswith(f'engine={eng},')
                     and k.endswith('kind=pad'))
        if pay:
            out.setdefault('comm_wire_breakdown', {})[eng] = {
                'payload_bytes': pay, 'scale_bytes': ov_scale,
                'pad_bytes': ov_pad,
                'total_bytes': pay + ov_scale + ov_pad}
            if base:
                out.setdefault(
                    'comm_payload_factor_vs_per_param_psum', {})[
                    eng] = round(base / pay, 4)
    # overlap headline (ISSUE 10): per-engine exposed vs hidden comm
    # seconds + schedule shape — the dryrun/bench acceptance reads
    # exposed_comm_seconds < total_comm_seconds here. A trace-time
    # MODEL like the byte gauges; `enabled` says whether the overlapped
    # schedule is actually compiled into the step.
    ov_en = out.get('ptpu_comm_overlap_enabled') or {}
    for key in ov_en:
        eng = key.split('=', 1)[1]

        def _ov(name, default=0):
            return (out.get(f'ptpu_comm_overlap_{name}') or {}).get(
                key, default)

        out.setdefault('comm_overlap', {})[eng] = {
            'enabled': bool(_ov('enabled')),
            'groups': int(_ov('groups')),
            'groups_in_flight': int(_ov('groups_in_flight')),
            'prefetch_depth': int(_ov('prefetch_depth')),
            'chunk_elements': int(_ov('chunk_elements')),
            'total_comm_seconds': round(_ov('total_comm_seconds'), 9),
            'exposed_comm_seconds': round(
                _ov('exposed_comm_seconds'), 9),
            'hidden_comm_seconds': round(_ov('hidden_comm_seconds'), 9),
        }
    return out


def flatten_grad_list(grads):
    """Throwaway bucket view of an eager gradient list (GradScaler
    unscale / clip_grad_norm_): returns (layout keyed by list index as
    str, per-bucket flat arrays). One place owns the idiom so the
    fused-reduction / one-sync contract of both callers can't drift."""
    layout = BucketLayout.build(
        {str(i): (g.data.shape, g.data.dtype)
         for i, g in enumerate(grads)})
    flats = layout.flatten({str(i): g.data for i, g in enumerate(grads)})
    return layout, flats
