"""Eager autograd engine: a Wengert-list tape over `jax.vjp`.

Reference parity: paddle/fluid/imperative — `Tracer::TraceOp` (tracer.cc:144)
records an OpBase grad node per executed op; `BasicEngine::Execute`
(basic_engine.cc:305) walks the grad graph topologically; GradientAccumulator
sums multi-consumer grads. The TPU-native design replaces per-op hand-written
grad kernels with `jax.vjp`: every traced op captures a vjp closure (residuals
live on device), and `backward()` replays closures in reverse creation order —
a Wengert list, which is already a valid topological order because an op's
inputs always precede it.

Grad accumulation into leaf `.grad` matches paddle's accumulate-until-
`clear_grad` semantics (gradient_accumulator.cc).
"""
import contextlib
import weakref

import jax
import jax.numpy as jnp

from . import dtypes

_grad_enabled = True
_node_counter = 0

# Installed by paddle_tpu.static.enable_static(): fn(name, fn, args, kwargs)
# that records the op into the current Program instead of executing it.
STATIC_RECORD_HOOK = None


def grad_enabled():
    return _grad_enabled


@contextlib.contextmanager
def no_grad():
    """Parity: paddle.no_grad."""
    global _grad_enabled
    saved = _grad_enabled
    _grad_enabled = False
    try:
        yield
    finally:
        _grad_enabled = saved


@contextlib.contextmanager
def enable_grad():
    global _grad_enabled
    saved = _grad_enabled
    _grad_enabled = True
    try:
        yield
    finally:
        _grad_enabled = saved


class Node:
    """One executed op on the tape.

    Holds the vjp closure, strong refs to input Tensors (so leaf params stay
    alive), and weak refs to outputs (so dead activations break the chain).
    """
    __slots__ = ('id', 'name', 'vjp_fn', 'inputs', 'input_needs_grad',
                 'outputs', 'out_meta', 'n_outputs', 'primal_fn',
                 'diff_idx', 'input_versions', '__weakref__')

    def __init__(self, name, vjp_fn, inputs, input_needs_grad, outputs,
                 primal_fn=None, diff_idx=None):
        global _node_counter
        _node_counter += 1
        self.id = _node_counter
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = list(inputs)            # list[Tensor]
        self.input_needs_grad = input_needs_grad  # list[bool]
        # in-place version stamps: backward() refuses to route a
        # cotangent through an input that was later rebound in place
        # (tensor.inplace_rebind bumps _version — the reference's
        # inplace version-counter contract)
        self.input_versions = [getattr(t, '_version', 0) for t in inputs]
        self.outputs = [weakref.ref(t) for t in outputs]
        self.out_meta = [(t.data.shape, t.data.dtype) for t in outputs]
        self.n_outputs = len(outputs)
        # for create_graph (double grad): the primal closure over the diff
        # inputs, re-vjp'd THROUGH the tape so grads-of-grads exist
        # (parity: hand-written double-grad kernels, e.g.
        # matmul_v2_op.cc MatMulV2GradGrad)
        self.primal_fn = primal_fn
        self.diff_idx = diff_idx


def record(name, vjp_fn, inputs, input_needs_grad, outputs,
           primal_fn=None, diff_idx=None):
    node = Node(name, vjp_fn, inputs, input_needs_grad, outputs,
                primal_fn=primal_fn, diff_idx=diff_idx)
    for t in outputs:
        t._node = node
    return node


def _accumulate(slot, idx, value):
    if slot[idx] is None:
        slot[idx] = value
    else:
        slot[idx] = slot[idx] + value


def backward(tensors, grad_tensors=None, retain_graph=False, capture=None,
             accumulate_leaves=None, create_graph=False):
    """Run reverse-mode over the tape from `tensors`.

    Parity: paddle.autograd.backward / Tensor.backward →
    BasicEngine::Execute (basic_engine.cc:305). When `capture` (a dict
    id(tensor)->None) is given, grads reaching those tensors are stored there
    and leaf `.grad` fields are left untouched — the PartialGradEngine mode
    used by paddle.grad (partial_grad_engine.cc).

    With `create_graph=True` cotangents are carried as live Tensors and each
    node's vjp is re-derived from its recorded primal closure THROUGH
    run_op, so the produced grads are themselves differentiable
    (higher-order grad; parity: partial_grad_engine.cc create_graph).
    """
    from .tensor import Tensor
    if accumulate_leaves is None:
        accumulate_leaves = capture is None
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]

    def _wrap(g):
        """Cotangent representation: raw array normally, live Tensor when
        building a differentiable backward."""
        if create_graph:
            return g if isinstance(g, Tensor) else Tensor(
                g, stop_gradient=False)
        return g.data if isinstance(g, Tensor) else g

    # node_id -> (node, [cotangent per output])
    pending = {}
    roots = []
    # leaf grads accumulate here during the walk; hooks run ONCE on the
    # fully-summed value at the end (paddle/torch hook semantics)
    leaf_grads = {}   # id(t) -> (tensor, grad)
    # ids whose hooks already fired at node-pop (intermediate capture
    # targets) — finalize must not fire them a second time
    hook_done = set()

    def _apply_hooks(t, g):
        hooks = getattr(t, '_grad_hooks', None)
        if hooks:
            from .tensor import Tensor as _T
            arr = g.data if isinstance(g, _T) else g
            changed = False
            for hook in list(hooks.values()):
                r = hook(_T(arr, stop_gradient=True))
                if r is not None:
                    arr = r.data if isinstance(r, _T) else r
                    changed = True
            if changed:
                # a replacing hook detaches the value (hooks are opaque
                # Python; no double-grad through them)
                g = _wrap(arr)
            # unchanged: keep the original (possibly live) cotangent
        return g

    def leaf_store(t, g):
        prev = leaf_grads.get(id(t))
        leaf_grads[id(t)] = (t, g if prev is None else prev[1] + g)

    def seed_grad(t, g):
        if capture is not None and id(t) in capture and t._node is None:
            leaf_store(t, g)
            return
        if t._node is not None:
            node = t._node
            entry = pending.get(node.id)
            if entry is None:
                entry = (node, [None] * node.n_outputs)
                pending[node.id] = entry
            for i, ref in enumerate(node.outputs):
                if ref() is t:
                    _accumulate(entry[1], i, g)
                    break
        elif not t.stop_gradient:
            leaf_store(t, g)

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient and t._node is None:
            continue
        if g is None:
            if t.size != 1:
                raise RuntimeError(
                    "backward() on a non-scalar tensor requires grad_tensors")
            garr = _wrap(jnp.ones_like(t.data))
        else:
            garr = _wrap(g if isinstance(g, Tensor) else jnp.asarray(g))
        seed_grad(t, garr)
        roots.append(t)

    # Process nodes in decreasing creation id — a valid reverse topological
    # order for a Wengert list.
    while pending:
        nid = max(pending)
        node, cotangents = pending.pop(nid)
        if node.vjp_fn is None:
            raise RuntimeError(
                f"autograd: grad graph through op '{node.name}' was already "
                "released; pass retain_graph=True to backward()")
        for t_in, v0 in zip(node.inputs, node.input_versions):
            if getattr(t_in, '_version', 0) != v0:
                raise RuntimeError(
                    f"autograd: a tensor needed for the gradient of op "
                    f"'{node.name}' was modified by an in-place "
                    f"operation (recorded version {v0}, current "
                    f"{getattr(t_in, '_version', 0)}); use the "
                    "out-of-place spelling before reusing a tensor "
                    "another op has consumed")
        cts = []
        for i, (shape, dt) in enumerate(node.out_meta):
            ct = cotangents[i]
            if ct is None:
                ct = _wrap(jnp.zeros(shape, dt))
            else:
                out_t = node.outputs[i]()
                if out_t is not None:
                    if getattr(out_t, '_grad_hooks', None):
                        # summed cotangent for this tensor is now final
                        ct = _apply_hooks(out_t, ct)
                        hook_done.add(id(out_t))
                    if capture is not None and id(out_t) in capture:
                        # store the FINAL (post-hook) cotangent for the
                        # captured intermediate; overwrites the running
                        # pre-hook sum leaf_store accumulated on in-flow
                        leaf_grads[id(out_t)] = (out_t, ct)
                        hook_done.add(id(out_t))
            cts.append(ct)
        if create_graph:
            in_grads = _differentiable_vjp(node, cts)
        else:
            in_grads = node.vjp_fn(
                tuple(cts) if node.n_outputs > 1 else cts[0])
        for t, needs, g in zip(node.inputs, node.input_needs_grad, in_grads):
            if not needs or g is None:
                continue
            if capture is not None and id(t) in capture:
                leaf_store(t, g)
            if t._node is not None:
                prev = pending.get(t._node.id)
                if prev is None:
                    prev = (t._node, [None] * t._node.n_outputs)
                    pending[t._node.id] = prev
                for i, ref in enumerate(t._node.outputs):
                    if ref() is t:
                        _accumulate(prev[1], i, g)
                        break
            elif not t.stop_gradient:
                if (capture is None or accumulate_leaves) and \
                        not (capture is not None and id(t) in capture):
                    leaf_store(t, g)

    # finalize leaves: hooks on the fully-accumulated grads, then route to
    # capture or .grad
    for tid, (t, g) in leaf_grads.items():
        if tid not in hook_done:
            g = _apply_hooks(t, g)
        if capture is not None and tid in capture:
            capture[tid] = g if capture[tid] is None else capture[tid] + g
        elif accumulate_leaves or capture is None:
            _leaf_accumulate(t, g)

    if not retain_graph:
        for t in roots:
            _release_graph(t)


def _differentiable_vjp(node, cts):
    """Apply a node's vjp THROUGH the tape: re-derive it from the recorded
    primal closure with jax.vjp inside run_op, so the resulting grads carry
    their own tape nodes (create_graph / double grad).

    Parity: the reference's per-op GradGrad kernels (e.g.
    operators/matmul_v2_op.cc MatMulV2GradGrad) — here one generic rule
    covers every op because jax.vjp composes.
    """
    from .tensor import Tensor
    if node.primal_fn is None:
        raise RuntimeError(
            f"create_graph=True: op '{node.name}' has no recorded primal "
            "closure (custom PyLayer/recompute vjp) — double grad through "
            "it is unsupported")
    diff_in = [node.inputs[i] for i in node.diff_idx]
    nd = len(diff_in)
    multi = node.n_outputs > 1
    ct_tensors = [c if isinstance(c, Tensor) else Tensor(c) for c in cts]

    def _ho(*arrays, _f=node.primal_fn, _nd=nd, _multi=multi):
        prim, ctl = arrays[:_nd], arrays[_nd:]
        _, vjp = jax.vjp(_f, *prim)
        return tuple(vjp(tuple(ctl) if _multi else ctl[0]))

    gt = run_op('grad_' + node.name, _ho,
                tuple(diff_in) + tuple(ct_tensors))
    gt = gt if isinstance(gt, tuple) else (gt,)
    in_grads = [None] * len(node.inputs)
    for j, i in enumerate(node.diff_idx):
        in_grads[i] = gt[j]
    return in_grads


def _leaf_accumulate(t, g):
    from .tensor import Tensor
    if isinstance(g, Tensor):
        g = g.data
    if g.dtype != t.data.dtype:
        g = g.astype(t.data.dtype)
    if t.grad is None:
        t.grad = Tensor(g, stop_gradient=True)
    else:
        t.grad = Tensor(t.grad.data + g, stop_gradient=True)


def _release_graph(root):
    """Drop vjp closures reachable from root so residuals free."""
    stack = [root._node] if root._node is not None else []
    seen = set()
    while stack:
        node = stack.pop()
        if node is None or node.id in seen:
            continue
        seen.add(node.id)
        node.vjp_fn = None
        node.primal_fn = None   # closes over input arrays — must free too
        for t in node.inputs:
            if t._node is not None and t._node.vjp_fn is not None:
                stack.append(t._node)
        node.inputs = []
    root._node = None


def run_op(name, fn, tensor_args, static_kwargs=None, n_nondiff=0):
    """Execute op `fn` over Tensor args; record a tape node if needed.

    `fn(*arrays, **static_kwargs)` must be a jax-traceable function.
    `n_nondiff` trailing tensor args are passed through without vjp (e.g.
    integer index tensors).
    """
    from .tensor import Tensor
    static_kwargs = static_kwargs or {}

    # Static-graph mode: record instead of execute (parity with the
    # dual dygraph/static dispatch in python/paddle/fluid/framework.py).
    # The hook is installed by paddle_tpu.static.enable_static().
    if STATIC_RECORD_HOOK is not None:
        return STATIC_RECORD_HOOK(name, fn, tensor_args, static_kwargs)

    # Lazy fusion window (core.ops.* fast-path analogue): record
    # symbolically, one XLA dispatch per materialization
    from . import lazy as _lazy
    if _lazy.active():
        return _lazy.record(name, fn, tensor_args, static_kwargs)

    arrs = tuple(t.data for t in tensor_args)

    diff_mask = []
    for i, t in enumerate(tensor_args):
        ok = (i < len(tensor_args) - n_nondiff
              and dtypes.is_floating(t.data.dtype))
        diff_mask.append(ok)

    needs = [diff_mask[i] and not t.stop_gradient
             for i, t in enumerate(tensor_args)]
    trace = _grad_enabled and any(needs)

    if trace:
        diff_idx = [i for i, d in enumerate(diff_mask) if d]
        const_idx = [i for i, d in enumerate(diff_mask) if not d]
        const_arrs = [arrs[i] for i in const_idx]

        def closed(*diff_arrs):
            full = [None] * len(arrs)
            for j, i in enumerate(diff_idx):
                full[i] = diff_arrs[j]
            for j, i in enumerate(const_idx):
                full[i] = const_arrs[j]
            return fn(*full, **static_kwargs)

        try:
            out, vjp_fn = jax.vjp(closed, *[arrs[i] for i in diff_idx])
        except Exception as e:
            # flag consulted only on the exception path — zero per-op cost
            from .flags import flag as _flag_
            if not _flag_('FLAGS_op_error_context', False):
                raise
            from .enforce import op_error_context
            raise op_error_context(name, e) from e

        def full_vjp(ct, _vjp=vjp_fn, _dix=tuple(diff_idx), _n=len(arrs)):
            partial = _vjp(ct)
            full = [None] * _n
            for j, i in enumerate(_dix):
                full[i] = partial[j]
            return full
    else:
        try:
            out = fn(*arrs, **static_kwargs)
        except Exception as e:
            from .flags import flag as _flag_
            if not _flag_('FLAGS_op_error_context', False):
                raise
            from .enforce import op_error_context
            raise op_error_context(name, e) from e
        full_vjp = None

    multi = isinstance(out, (tuple, list))
    outs = list(out) if multi else [out]

    # FLAGS_check_nan_inf: post-kernel guard (parity:
    # details/nan_inf_utils_detail.cc:299 behind flags.cc:44), eager
    # only — jit coverage comes from the engines' numerics taps. The
    # observatory fuses the per-output scans into one device flag and
    # (with FLAGS_check_nan_inf_deferred) defers the host sync to the
    # step boundary with replay-based localization (core/numerics.py).
    from .flags import flag as _flag
    if _flag('FLAGS_check_nan_inf') and \
            not isinstance(outs[0], jax.core.Tracer):
        from . import numerics as _num
        _num.guard().observe(name, fn, static_kwargs, arrs, outs)

    out_tensors = [Tensor(o, stop_gradient=not trace) for o in outs]

    if trace:
        record(name, full_vjp, list(tensor_args), needs, out_tensors,
               primal_fn=closed, diff_idx=tuple(diff_idx))
    return tuple(out_tensors) if multi else out_tensors[0]
