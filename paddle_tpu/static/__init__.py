"""paddle_tpu.static — static Program graph mode.

Reference parity: python/paddle/static (Program/program_guard/Executor/
append_backward, SURVEY.md P1/P2). TPU-native design: a Program records ops
symbolically (each op keeps its jax-traceable fn); the Executor lowers the
whole Program in one `jax.jit` trace — the XLA-idiomatic replacement for the
reference's op-by-op C++ Executor loop (framework/executor.cc) and
ParallelExecutor SSA graphs (N15/N16): one compiled executable per
(program, feed-signature).
"""
from .program import (Program, Block, Variable, Operator, program_guard,
                      default_main_program, default_startup_program,
                      name_scope, in_static_mode, enable_static,
                      disable_static, data, InputSpec, device_guard)
from .executor import Executor, scope_guard, global_scope, Scope
from .backward import append_backward, gradients
from .nn import *  # noqa
from . import nn
from .control_flow import while_loop, cond, switch_case, case
from .serialization import (save, load, save_inference_model,
                            load_inference_model, serialize_program,
                            deserialize_program)


class BuildStrategy:
    """Option surface parity: framework/details/build_strategy.h. XLA performs
    fusion/scheduling; fields are accepted and recorded."""

    def __init__(self):
        self.enable_sequential_execution = False
        self.fuse_elewise_add_act_ops = False
        self.fuse_bn_act_ops = False
        self.fuse_bn_add_act_ops = False
        self.fuse_relu_depthwise_conv = False
        self.fuse_broadcast_ops = False
        self.fuse_all_optimizer_ops = False
        self.enable_inplace = False
        self.enable_addto = False
        self.memory_optimize = None
        self.reduce_strategy = 0
        self.gradient_scale_strategy = 0
        self.build_cinn_pass = False
        self.num_trainers = 1
        self.trainer_id = 0
        self.sync_batch_norm = False


class ExecutionStrategy:
    """Parity: ExecutionStrategy pybind struct."""

    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10
        self.num_iteration_per_run = 1
        self.use_thread_barrier = False


class CompiledProgram:
    """Parity: fluid/compiler.py:88 — on TPU every Program is compiled; this
    wrapper only carries build options."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None):
        self._build_strategy = build_strategy
        return self

    def __getattr__(self, item):
        return getattr(self.__dict__['_program'], item)


class ParallelExecutor:
    """Parity shim: framework/parallel_executor.cc — superseded by XLA SPMD;
    kept for API compat."""

    def __init__(self, use_cuda=False, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        self._program = main_program or default_main_program()
        self._exe = Executor()

    def run(self, fetch_list, feed=None, feed_dict=None, return_numpy=True):
        return self._exe.run(self._program, feed=feed or feed_dict,
                             fetch_list=fetch_list, return_numpy=return_numpy)


def py_func(func, x, out, backward_func=None, skip_vars_in_backward_input=None):
    raise NotImplementedError


def default_startup_program_():
    return default_startup_program()

from .api_tail import (cpu_places, cuda_places, xpu_places,  # noqa
                       create_parameter, create_global_var,
                       load_program_state, set_program_state,
                       serialize_persistables, deserialize_persistables,
                       save_to_file, load_from_file, normalize_program,
                       WeightNormParamAttr)
from .fluid_layers import Print  # noqa
from .nn import accuracy, auc  # noqa
