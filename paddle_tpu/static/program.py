"""Static Program IR.

Reference parity: Program/Block/Operator/Variable of
python/paddle/fluid/framework.py (6,005 LoC) over framework.proto
(ProgramDesc:202/OpDesc:43/VarDesc:169). TPU-native design: an op record
carries its jax-traceable fn (the same fns the eager ops use), so the Program
is directly lowerable — `Executor` replays it under one jax.jit trace. op_role
attrs (Forward/Backward/Optimize/LRSched, fluid/backward.py) are kept because
the distributed program rewrites (pipeline/sharding meta-optimizers) key on
them, as in the reference.
"""
import contextlib

import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.tensor import Tensor


class OpRole:
    """Parity: fluid/framework.py op_role values (load-bearing for pipeline &
    sharding passes — SURVEY.md §1-L7)."""
    Forward = 0
    Backward = 1
    Optimize = 2
    RPC = 3
    Dist = 4
    LRSched = 16
    Loss = 256


_static_mode = False
_program_stack = []
_device_stack = []


def in_static_mode():
    return _static_mode


def enable_static():
    global _static_mode
    _static_mode = True
    from ..core import autograd
    autograd.STATIC_RECORD_HOOK = record_op


def disable_static():
    global _static_mode
    _static_mode = False
    from ..core import autograd
    autograd.STATIC_RECORD_HOOK = None


_GLOBAL_NAME_COUNTER = {}
# optional name prefix installed by unique_name.guard(new_generator=str)
# (reference: fluid/unique_name.py UniqueNameGenerator prefix)
_GLOBAL_NAME_PREFIX = ''


class Variable:
    """Symbolic tensor (parity: fluid/framework.py Variable). Holds only an
    aval (shape/dtype); values live in the Scope at run time."""

    _is_symbolic = True

    def __init__(self, block, name, shape, dtype, persistable=False,
                 stop_gradient=True, is_parameter=False):
        self.block = block
        self.name = name
        self._shape = list(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.persistable = persistable
        self.stop_gradient = stop_gradient
        self.is_parameter = is_parameter
        self.op_device = _device_stack[-1] if _device_stack else ''
        # autograd tape fields unused in static mode but probed by shared code
        self._node = None
        self.grad = None

    @property
    def data(self):
        return jax.ShapeDtypeStruct(tuple(d if d is not None and d >= 0 else 1
                                          for d in self._shape), self.dtype)

    @property
    def shape(self):
        return list(self._shape)

    @property
    def ndim(self):
        return len(self._shape)

    @property
    def size(self):
        return int(np.prod([d for d in self._shape]))

    def astype(self, dtype):
        from ..ops import manip
        return manip.cast(self, dtype)

    def backward(self, *a, **k):
        raise RuntimeError("Variable.backward: use append_backward + "
                           "Executor in static mode")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self._shape}, "
                f"dtype={dtypes.dtype_name(self.dtype)})")

    # arithmetic operates through the shared op layer (records ops)
    def _binop(self, other, opname):
        from ..ops import math as M
        return getattr(M, opname)(self, other)

    def __add__(self, o):
        return self._binop(o, 'add')

    def __radd__(self, o):
        from ..ops import math as M
        return M.add(o, self)

    def __sub__(self, o):
        return self._binop(o, 'subtract')

    def __mul__(self, o):
        return self._binop(o, 'multiply')

    def __rmul__(self, o):
        from ..ops import math as M
        return M.multiply(o, self)

    def __truediv__(self, o):
        return self._binop(o, 'divide')

    def __matmul__(self, o):
        return self._binop(o, 'matmul')

    # comparisons record ops too (needed by while/cond conditions);
    # __eq__/__hash__ stay identity-based — Variables live in dicts/sets
    def __lt__(self, o):
        return self._binop(o, 'less_than')

    def __le__(self, o):
        return self._binop(o, 'less_equal')

    def __gt__(self, o):
        return self._binop(o, 'greater_than')

    def __ge__(self, o):
        return self._binop(o, 'greater_equal')


class Parameter(Variable):
    def __init__(self, *args, initializer=None, trainable=True, **kwargs):
        super().__init__(*args, persistable=True,
                         stop_gradient=not trainable, is_parameter=True,
                         **kwargs)
        self.initializer = initializer
        self.trainable = trainable
        self.optimize_attr = {'learning_rate': 1.0}
        self.regularizer = None
        self.need_clip = True


class Operator:
    """One recorded op (parity: fluid/framework.py Operator over OpDesc)."""

    _id_counter = 0

    def __init__(self, type, fn, inputs, outputs, attrs=None,
                 op_role=OpRole.Forward):
        Operator._id_counter += 1
        self.idx = Operator._id_counter
        self.type = type
        self.fn = fn                      # jax fn(*arrays, **attrs)
        self.input_names = inputs         # list[str]
        self.output_names = outputs       # list[str]
        self.attrs = attrs or {}
        self.op_role = op_role
        self.op_device = _device_stack[-1] if _device_stack else ''
        self.multi_out = False   # fn returns a tuple (even of length 1)

    def attr(self, name):
        if name == 'op_role':
            return self.op_role
        if name == 'op_device':
            return self.op_device
        return self.attrs.get(name)

    def _set_attr(self, name, value):
        if name == 'op_role':
            self.op_role = value
        elif name == 'op_device':
            self.op_device = value
        else:
            self.attrs[name] = value

    def __repr__(self):
        return (f"{{{', '.join(self.output_names)}}} = {self.type}"
                f"({', '.join(self.input_names)})")


class Block:
    """Parity: fluid/framework.py Block over BlockDesc (incl. the nested
    sub-block structure framework.proto:178 uses for conditional_block/
    while ops)."""

    def __init__(self, program, idx, parent_idx=-1):
        self.program = program
        self.idx = idx
        self.parent_idx = parent_idx
        self.vars = {}
        self.ops = []

    def var(self, name):
        if name not in self.vars:
            raise ValueError(f"var {name} not in block")
        return self.vars[name]

    def _find_var_recursive(self, name):
        """Resolve a name through the parent-block chain (parity:
        Block._var_recursive)."""
        b = self
        while b is not None:
            if name in b.vars:
                return b.vars[name]
            b = (self.program.blocks[b.parent_idx]
                 if b.parent_idx >= 0 else None)
        return None

    def has_var(self, name):
        return name in self.vars

    def create_var(self, name=None, shape=None, dtype='float32',
                   persistable=False, stop_gradient=True, **kwargs):
        name = name or self.program._unique_name('tmp')
        v = Variable(self, name, shape or [], dtype, persistable,
                     stop_gradient)
        self.vars[name] = v
        return v

    def create_parameter(self, name=None, shape=None, dtype='float32',
                         initializer=None, trainable=True, **kwargs):
        name = name or self.program._unique_name('param')
        p = Parameter(self, name, shape or [], dtype,
                      initializer=initializer, trainable=trainable)
        self.vars[name] = p
        self.program.startup_ops.append(p)
        return p

    def append_op(self, op):
        self.ops.append(op)
        return op

    def all_parameters(self):
        return [v for v in self.vars.values() if isinstance(v, Parameter)]


class Program:
    """Parity: fluid/framework.py Program."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self._block_stack = [0]
        self._name_counter = {}
        self.startup_ops = []  # parameters needing init
        self._loss_var = None
        self._grad_map = {}    # param name -> grad var name
        self.random_seed = 0
        self._pipeline_opt = None
        self._fetch_list = None

    def _unique_name(self, prefix):
        # PARAMETER names must be process-unique, not per-Program: the
        # global scope keys materialized params by name, so two
        # Programs both naming their first weight "param_0" would
        # silently share one buffer (the reference's UniqueNameGenerator
        # is likewise process-global — fluid/unique_name.py). Temp/const
        # names stay per-Program (they never enter the scope).
        if prefix == 'param':
            n = _GLOBAL_NAME_COUNTER.get(prefix, 0)
            _GLOBAL_NAME_COUNTER[prefix] = n + 1
            return f"{_GLOBAL_NAME_PREFIX}{prefix}_{n}"
        self._name_counter[prefix] = self._name_counter.get(prefix, 0) + 1
        return f"{prefix}_{self._name_counter[prefix] - 1}"

    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        stack = getattr(self, '_block_stack', None) or [0]
        return self.blocks[stack[-1]]

    def _create_block(self):
        """Push a new sub-block; subsequent record_op calls land in it
        (parity: Program._create_block)."""
        if not hasattr(self, '_block_stack'):
            self._block_stack = [0]
        b = Block(self, len(self.blocks),
                  parent_idx=self._block_stack[-1])
        self.blocks.append(b)
        self._block_stack.append(b.idx)
        return b

    def _rollback(self):
        """Pop back to the parent block (parity: Program._rollback)."""
        self._block_stack.pop()

    def all_parameters(self):
        out = []
        for b in self.blocks:
            out.extend(b.all_parameters())
        return out

    def list_vars(self):
        for b in self.blocks:
            yield from b.vars.values()

    def clone(self, for_test=False):
        p = Program.__new__(Program)
        p.__dict__.update(self.__dict__)
        p.blocks = self.blocks       # shallow: shares blocks (paddle clones
                                     # descs; our replay is non-destructive)
        if for_test:
            # prune backward + optimize ops (parity: clone(for_test=True))
            # — otherwise evaluating the clone would keep training on eval
            # data. Vars are shared; only the op list is filtered.
            p.blocks = []
            for b in self.blocks:
                nb = Block(p, b.idx, parent_idx=getattr(b, 'parent_idx', -1))
                nb.vars = b.vars
                nb.ops = [op for op in b.ops
                          if not (op.op_role & (OpRole.Backward
                                                | OpRole.Optimize))]
                p.blocks.append(nb)
            p._optimizer = None
            p._grad_map = {}
            p._loss_var = None
            p._has_backward_ops = False
        return p

    @property
    def num_blocks(self):
        return len(self.blocks)

    def save(self, path):
        """Serialize program parameters (full program serialization uses the
        Scope; see Executor)."""
        raise NotImplementedError

    def load(self, path):
        raise NotImplementedError

    def to_string(self, throw_on_error=True, with_details=False):
        lines = [f"Program(ops={len(self.global_block().ops)})"]
        for op in self.global_block().ops:
            lines.append("  " + repr(op))
        return "\n".join(lines)

    def __repr__(self):
        return self.to_string()


_default_main_program = Program()
_default_startup_program = Program()


def default_main_program():
    return _program_stack[-1][0] if _program_stack else _default_main_program


def default_startup_program():
    return _program_stack[-1][1] if _program_stack else \
        _default_startup_program


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    _program_stack.append((main_program,
                           startup_program or _default_startup_program))
    try:
        yield
    finally:
        _program_stack.pop()


@contextlib.contextmanager
def name_scope(prefix=None):
    yield


@contextlib.contextmanager
def device_guard(device=None):
    """Parity: fluid/framework.py device_guard — sets per-op op_device attr;
    pipeline stage splitting keys on it (optimizer.py:4628)."""
    _device_stack.append(device or '')
    try:
        yield
    finally:
        _device_stack.pop()


class InputSpec:
    """Parity: paddle.static.InputSpec."""

    def __init__(self, shape, dtype='float32', name=None):
        self.shape = tuple(shape)
        self.dtype = dtypes.convert_dtype(dtype)
        self.name = name

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"


def data(name, shape, dtype='float32', lod_level=0):
    """Parity: paddle.static.data — declares a feed Variable."""
    prog = default_main_program()
    block = prog.global_block()
    v = Variable(block, name, shape, dtype, stop_gradient=True)
    v.is_data = True
    block.vars[name] = v
    return v


# ---- op recording hook (called from core.autograd.run_op) -----------------
def record_op(name, fn, args, static_kwargs):
    """Record an op into the current Program and return symbolic outputs.
    Shape inference via jax.eval_shape (parity: InferShape in
    operator.cc:1132). Dynamic dims (-1/None, the paddle dynamic-batch
    idiom) infer through jax symbolic shapes so they stay dynamic on
    the outputs."""
    prog = default_main_program()
    block = prog.current_block()

    dyn = any(isinstance(a, Variable)
              and any(d is None or d < 0 for d in a._shape) for a in args)
    sym_scope = None
    if dyn:
        from jax import export as jax_export
        sym_scope = jax_export.SymbolicScope()

    def _var_aval(v):
        if not dyn or all(d is not None and d >= 0 for d in v._shape):
            return v.data
        from jax import export as jax_export
        # dynamic dims share a symbol PER AXIS POSITION so data/label
        # batch dims unify while (-1, -1) inputs keep independent dims
        parts = [f'_dyn{j}' if d is None or d < 0 else str(d)
                 for j, d in enumerate(v._shape)]
        dims = jax_export.symbolic_shape(', '.join(parts), scope=sym_scope)
        return jax.ShapeDtypeStruct(tuple(dims), v.dtype)

    in_names = []
    avals = []
    for a in args:
        if isinstance(a, Variable):
            in_names.append(a.name)
            avals.append(_var_aval(a))
        else:  # concrete Tensor closed over (e.g. constants)
            cname = prog._unique_name(f'const')
            block.vars[cname] = _ConstVar(block, cname, a)
            in_names.append(cname)
            avals.append(jax.ShapeDtypeStruct(tuple(a.data.shape),
                                              a.data.dtype))

    out_aval = jax.eval_shape(lambda *xs: fn(*xs, **static_kwargs), *avals)
    multi = isinstance(out_aval, (tuple, list))
    out_avals = list(out_aval) if multi else [out_aval]
    outs = []
    for oa in out_avals:
        oname = prog._unique_name(name)
        oshape = [d if isinstance(d, int) else -1 for d in oa.shape]
        ov = Variable(block, oname, oshape, oa.dtype,
                      stop_gradient=all(getattr(a, 'stop_gradient', True)
                                        for a in args))
        block.vars[oname] = ov
        outs.append(ov)

    role = OpRole.Forward
    op = Operator(name, lambda *xs: fn(*xs, **static_kwargs), in_names,
                  [o.name for o in outs], dict(static_kwargs), role)
    op.multi_out = multi
    block.append_op(op)
    return tuple(outs) if multi else outs[0]


def materialize_persistables(vars_iter, find, set_, apply_masters=True):
    """Initialize missing persistable vars (shared by the Executor startup
    and the pipeline/sharding interpreters). `_init_from` fp32 masters
    mirror their parameter; other vars use their initializer (default
    XavierUniform). With apply_masters=False the (var, src) master pairs
    are returned unapplied so callers can sync params across ranks first.
    """
    from ..nn import initializer as I
    deferred = []
    for v in vars_iter:
        if (not getattr(v, 'persistable', False)
                or isinstance(v, _ConstVar) or v.name == '@LR'
                or find(v.name) is not None):
            continue
        src = getattr(v, '_init_from', None)
        if src is not None:
            deferred.append((v, src))
            continue
        init = getattr(v, 'initializer', None) or I.XavierUniform()
        set_(v.name, init(v.shape, v.dtype))
    if not apply_masters:
        return deferred
    for v, src in deferred:
        base = find(src)
        if base is not None:
            set_(v.name, base.astype(jnp.float32))
    return []


def run_op_in_env(op, env, program=None):
    """Execute one recorded op against a name→array env (shared by the
    Executor replay and the pipeline/sharding interpreters). Control-flow
    ops (conditional_block / while) replay their sub-blocks through
    lax.cond / lax.while_loop — `program` must be passed for those."""
    if op.type == 'conditional_block':
        return _run_conditional_block(op, env, program)
    if op.type == 'while':
        return _run_while(op, env, program)
    ins = [env[n] for n in op.input_names]
    outs = op.fn(*ins)
    if not isinstance(outs, (tuple, list)):
        outs = (outs,)
    for n, o in zip(op.output_names, outs):
        env[n] = o


def _replay_block(block, env, program):
    for op in block.ops:
        run_op_in_env(op, env, program)


def _run_conditional_block(op, env, program):
    """conditional_block op (parity:
    operators/controlflow/conditional_block_op.cc) — both branches are
    sub-blocks; executes as lax.cond so it traces under jit."""
    if program is None:
        raise RuntimeError("conditional_block op needs the owning Program")
    pred = env[op.input_names[0]]
    tb = program.blocks[op.attrs['sub_block_true']]
    fb = program.blocks[op.attrs['sub_block_false']]
    t_outs = op.attrs['true_outs']
    f_outs = op.attrs['false_outs']

    def branch(blk, out_names):
        def run(_):
            local = dict(env)
            _replay_block(blk, local, program)
            return tuple(local[n] for n in out_names)
        return run

    outs = jax.lax.cond(jnp.asarray(pred).reshape(()).astype(bool),
                        branch(tb, t_outs), branch(fb, f_outs),
                        operand=None)
    for n, o in zip(op.output_names, outs):
        env[n] = o


def _run_while(op, env, program):
    """while op (parity: operators/controlflow/while_op.cc) — cond and
    body are sub-blocks over named carry vars; executes as
    lax.while_loop."""
    if program is None:
        raise RuntimeError("while op needs the owning Program")
    cb = program.blocks[op.attrs['cond_block']]
    bb = program.blocks[op.attrs['body_block']]
    carry_names = op.attrs['carry_names']
    n_carry = len(carry_names)
    init = tuple(jnp.asarray(env[n]) for n in op.input_names[:n_carry])

    def c(carry):
        local = dict(env)
        local.update(zip(carry_names, carry))
        _replay_block(cb, local, program)
        return jnp.asarray(local[op.attrs['cond_out']]) \
            .reshape(()).astype(bool)

    def b(carry):
        local = dict(env)
        local.update(zip(carry_names, carry))
        _replay_block(bb, local, program)
        return tuple(jnp.asarray(local[n]).astype(i.dtype)
                     for n, i in zip(op.attrs['body_outs'], init))

    outs = jax.lax.while_loop(c, b, init)
    for n, o in zip(op.output_names, outs):
        env[n] = o


class _ConstVar(Variable):
    """A captured concrete tensor appearing in a recorded program."""

    def __init__(self, block, name, tensor):
        super().__init__(block, name, list(tensor.data.shape),
                         tensor.data.dtype, persistable=True)
        self.value = tensor.data
