"""Static-graph layer API.

Reference parity: python/paddle/static/nn (fluid/layers/nn.py subset): fc,
conv2d, embedding, batch_norm, etc. These build Parameters in the current
Program and record ops through the shared op layer.
"""
import numpy as np
import jax.numpy as jnp

from ..core import dtypes
from ..ops import nn_ops as F
from ..ops import math as M
from ..ops import manip
from ..nn import initializer as I
from .program import default_main_program, Parameter

__all__ = ['fc', 'embedding', 'conv2d', 'batch_norm', 'cross_entropy',
           'softmax_with_cross_entropy', 'mean', 'dropout']


def _make_param(shape, dtype='float32', initializer=None, attr=None):
    prog = default_main_program()
    block = prog.global_block()
    init = initializer
    if attr is not None and getattr(attr, 'initializer', None) is not None:
        init = attr.initializer
    name = None
    if attr is not None and getattr(attr, 'name', None):
        name = attr.name
    return block.create_parameter(name=name, shape=shape, dtype=dtype,
                                  initializer=init or I.XavierUniform())


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Parity: fluid/layers/nn.py fc → mul + elementwise_add (+act)."""
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _make_param([in_dim, size], x.dtype, attr=weight_attr)
    if len(x.shape) > num_flatten_dims + 1:
        x = manip.reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim])
    out = M.matmul(x, w)
    if bias_attr is not False:
        b = _make_param([size], x.dtype, initializer=I.Constant(0.0),
                        attr=bias_attr)
        out = M.add(out, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype='float32'):
    w = _make_param(list(size), dtype, attr=param_attr)
    return F.embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1]
    w = _make_param([num_filters, cin // groups, k[0], k[1]], input.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               **kwargs):
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    scale = _make_param([c], input.dtype, initializer=I.Constant(1.0),
                        attr=param_attr)
    bias = _make_param([c], input.dtype, initializer=I.Constant(0.0),
                       attr=bias_attr)

    # Static BN uses in-graph batch statistics (global-stat tracking needs
    # state vars; the dygraph path owns that).
    from ..core.autograd import run_op
    ch_axis = 1 if data_layout == 'NCHW' else input.ndim - 1
    axes = tuple(i for i in range(input.ndim) if i != ch_axis)

    def fn(a, w, b):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) / jnp.sqrt(v + epsilon)
        return out * w.reshape(shape) + b.reshape(shape)
    out = run_op('batch_norm', fn, [input, scale, bias])
    if act:
        out = getattr(F, act)(out)
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return F.cross_entropy(input, label, soft_label=soft_label,
                           ignore_index=ignore_index, reduction='none',
                           use_softmax=False)


def softmax_with_cross_entropy(logits, label, **kwargs):
    return F.softmax_with_cross_entropy(logits, label, **kwargs)


def mean(x):
    return M.mean(x)


def dropout(x, dropout_prob=0.5, is_test=False, **kwargs):
    return F.dropout(x, p=dropout_prob, training=not is_test)


# ---------------------------------------------------------------------------
# fluid.layers breadth (P23): the wider static surface — parameterized
# wrappers where fluid created parameters, re-exports where the shared op
# layer already records (fluid/layers/nn.py + sequence_lod.py +
# detection.py + control_flow.py surfaces)
# ---------------------------------------------------------------------------

def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=1, param_attr=None, bias_attr=None,
                     act=None, name=None):
    """Parity: fluid/layers/nn.py conv2d_transpose."""
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1]
    w = _make_param([cin, num_filters // groups, k[0], k[1]], input.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    out = F.conv2d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    """Parity: fluid/layers/nn.py layer_norm."""
    import numpy as _np
    norm_shape = [int(_np.prod(input.shape[begin_norm_axis:]))]
    w = _make_param(norm_shape, input.dtype,
                    initializer=I.Constant(1.0),
                    attr=param_attr) if scale else None
    b = _make_param(norm_shape, input.dtype,
                    initializer=I.Constant(0.0),
                    attr=bias_attr) if shift else None
    # dynamic (-1) leading dims: flatten against the single CONCRETE
    # trailing size so only one unknown axis remains in the reshape
    lead = list(input.shape[:begin_norm_axis])
    if any(d is None or d < 0 for d in lead):
        lead = [-1]
    flat = manip.reshape(input, lead + [norm_shape[0]])
    out = F.layer_norm(flat, norm_shape, w, b, epsilon=epsilon)
    out = manip.reshape(out, [d if d is not None else -1
                              for d in input.shape])
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout='NCHW', name=None):
    """Parity: fluid/layers/nn.py group_norm."""
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    w = _make_param([c], input.dtype, initializer=I.Constant(1.0),
                    attr=param_attr)
    b = _make_param([c], input.dtype, initializer=I.Constant(0.0),
                    attr=bias_attr)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode='all', param_attr=None, name=None):
    """Parity: fluid/layers/nn.py prelu (modes all/channel/element)."""
    if mode == 'all':
        shape = [1]
    elif mode == 'channel':
        shape = [x.shape[1]]
    else:
        shape = list(x.shape[1:])
    a = _make_param(shape, x.dtype, initializer=I.Constant(0.25),
                    attr=param_attr)
    return F.prelu(x, a)


def nce(input, label, num_total_classes, num_neg_samples=5,
        param_attr=None, bias_attr=None, sampler='uniform', name=None):
    """Parity: fluid/layers/nn.py nce (parameterized wrapper over the op
    — operators/nce_op.cc)."""
    from ..ops import contrib
    d = input.shape[-1]
    w = _make_param([num_total_classes, d], input.dtype, attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_total_classes], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    return contrib.nce(input, label, num_total_classes, w, b,
                       num_neg_samples=num_neg_samples, sampler=sampler)


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Parity: fluid/layers/nn.py hsigmoid
    (operators/hierarchical_sigmoid_op.cc, default complete tree)."""
    from ..ops import contrib
    d = input.shape[-1]
    w = _make_param([num_classes - 1, d], input.dtype, attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_classes - 1], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    return contrib.hsigmoid_loss(input, label, num_classes, w, b)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Parity: fluid/layers/nn.py row_conv (operators/row_conv_op.cc)."""
    from ..ops import contrib
    d = input.shape[-1]
    w = _make_param([future_context_size + 1, d], input.dtype,
                    attr=param_attr)
    out = contrib.row_conv(input, w)
    if act:
        out = getattr(F, act)(out)
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    """Parity: fluid/layers/nn.py deformable_conv
    (operators/deformable_conv_op.cc v1/v2)."""
    from ..vision.detection import deform_conv2d
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1]
    w = _make_param([num_filters, cin // groups, k[0], k[1]], input.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    return deform_conv2d(input, offset, w, b, stride=stride,
                         padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups,
                         groups=groups,
                         mask=mask if modulated else None)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    """Parity: fluid/layers/nn.py bilinear_tensor_product."""
    from ..ops import linalg
    w = _make_param([size, x.shape[-1], y.shape[-1]], x.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([size], x.dtype, initializer=I.Constant(0.0),
                        attr=bias_attr)
    out = linalg.bilinear_tensor_product(x, y, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..ops import contrib
    return contrib.spectral_norm(weight, dim=dim, power_iters=power_iters,
                                 eps=eps)


def bilateral_slice(x, guide, grid, has_offset, name=None):
    """Parity: fluid/contrib/layers/nn.py:1499 bilateral_slice
    (operators/bilateral_slice_op.cc)."""
    from ..ops import contrib
    return contrib.bilateral_slice(x, guide, grid, has_offset=has_offset)


def correlation(x, y, pad_size, kernel_size, max_displacement, stride1,
                stride2, corr_type_multiply=1):
    """Parity: fluid/contrib/layers/nn.py:1562 correlation
    (operators/correlation_op.cc)."""
    from ..ops import contrib
    return contrib.correlation(x, y, pad_size, kernel_size,
                               max_displacement, stride1, stride2,
                               corr_type_multiply)


# ---------------------------------------------------------------------------
# fluid.layers legacy surface (VERDICT r3 #10 — fluid/layers/nn.py et al.)
# Legacy NAMES + legacy SIGNATURES adapted onto the shared op layer; every
# call records through the same ops the modern API uses.
# ---------------------------------------------------------------------------

def _legacy_binop(op, x, y, axis=-1, act=None, name=None):
    """fluid elementwise_* broadcast: align y's dims starting at `axis`."""
    if axis != -1 and len(getattr(y, 'shape', [])) < len(x.shape):
        yr = y
        trail = len(x.shape) - axis - len(y.shape)
        if trail > 0:
            yr = manip.reshape(y, list(y.shape) + [1] * trail)
        out = op(x, yr)
    else:
        out = op(x, y)
    if act:
        out = getattr(F, act)(out)
    return out


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.add, x, y, axis, act)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.subtract, x, y, axis, act)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.multiply, x, y, axis, act)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.divide, x, y, axis, act)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.pow, x, y, axis, act)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.maximum, x, y, axis, act)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.minimum, x, y, axis, act)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.mod, x, y, axis, act)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _legacy_binop(M.floor_divide, x, y, axis, act)


def _legacy_reduce(fn, input, dim=None, keep_dim=False, name=None):
    axis = dim if dim is None or isinstance(dim, (list, tuple)) else [dim]
    return fn(input, axis=axis, keepdim=keep_dim)


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _legacy_reduce(M.sum, input, dim, keep_dim)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _legacy_reduce(M.mean, input, dim, keep_dim)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _legacy_reduce(M.max, input, dim, keep_dim)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _legacy_reduce(M.min, input, dim, keep_dim)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _legacy_reduce(M.prod, input, dim, keep_dim)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _legacy_reduce(M.all, input, dim, keep_dim)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _legacy_reduce(M.any, input, dim, keep_dim)


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    from ..ops import creation
    return creation.full(shape, value, dtype=dtype)


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0,
                                  name=None):
    from ..ops import creation
    shape = list(shape)
    shape[output_dim_idx] = input.shape[input_dim_idx]
    return creation.full(shape, value, dtype=dtype)


def create_tensor(dtype, name=None, persistable=False):
    from ..ops import creation
    return creation.zeros([1], dtype=dtype)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    prog = default_main_program()
    block = prog.global_block()
    from .program import Variable
    vname = name or prog._unique_name('global_var')
    v = Variable(block, vname, list(shape), dtype,
                 persistable=persistable)
    v.initializer = I.Constant(float(value))
    block.vars[vname] = v
    if persistable:
        prog.startup_ops.append(v)
    return v


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    return _make_param(list(shape), dtype,
                       initializer=default_initializer, attr=attr)


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    xs = x
    if len(x.shape) > x_num_col_dims + 1:
        xs = manip.reshape(x, [int(np.prod(x.shape[:x_num_col_dims]))
                               if x_num_col_dims else 1, -1])
    return M.matmul(xs, y)


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0,
           name=None):
    out = M.matmul(x, y, transpose_x=transpose_x, transpose_y=transpose_y)
    if alpha != 1.0:
        out = M.scale(out, scale=alpha)
    return out


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    return F.normalize(x, p=2, axis=axis, epsilon=epsilon)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format="NCHW", name=None):
    if global_pooling:
        return (F.adaptive_max_pool2d if pool_type == 'max'
                else F.adaptive_avg_pool2d)(input, 1)
    fn = F.max_pool2d if pool_type == 'max' else F.avg_pool2d
    return fn(input, kernel_size=pool_size, stride=pool_stride,
              padding=pool_padding, ceil_mode=ceil_mode)


def image_resize(input, out_shape=None, scale=None, resample='BILINEAR',
                 align_corners=True, align_mode=1, name=None,
                 data_format='NCHW'):
    mode = {'BILINEAR': 'bilinear', 'NEAREST': 'nearest',
            'TRILINEAR': 'trilinear', 'LINEAR': 'linear'}[resample]
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode=mode)


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    align_corners=True, align_mode=1, data_format='NCHW'):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode='bilinear')


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   align_corners=True, data_format='NCHW'):
    return F.interpolate(input, size=out_shape, scale_factor=scale,
                         mode='nearest')


def cos_sim(X, Y):
    return F.cosine_similarity(X, Y, axis=-1)


def log_loss(input, label, epsilon=1e-4, name=None):
    return F.log_loss(input, label, epsilon=epsilon)


def huber_loss(input, label, delta):
    return F.smooth_l1_loss(input, label, reduction='none', delta=delta)


def smooth_l1(x, y, inside_weight=None, outside_weight=None, sigma=None):
    diff = M.subtract(x, y)
    if inside_weight is not None:
        diff = M.multiply(diff, inside_weight)
    s2 = (sigma or 1.0) ** 2
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    d = diff.data if isinstance(diff, Tensor) else _jnp.asarray(diff)
    a = _jnp.abs(d)
    out = _jnp.where(a < 1.0 / s2, 0.5 * s2 * d * d, a - 0.5 / s2)
    if outside_weight is not None:
        ow = outside_weight.data if isinstance(outside_weight, Tensor) \
            else _jnp.asarray(outside_weight)
        out = out * ow
    return Tensor(out.sum(axis=-1, keepdims=True))


def bpr_loss(input, label, name=None):
    """Bayesian personalized ranking (fluid/layers/nn.py bpr_loss)."""
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    x = input.data
    lb = label.data.reshape(-1)
    pos = _jnp.take_along_axis(x, lb[:, None].astype(_jnp.int32), axis=1)
    loss = -_jnp.log(jnn_sigmoid(pos - x) + 1e-8)
    n = x.shape[1]
    loss = (loss.sum(axis=1, keepdims=True) - (-_jnp.log(
        jnn_sigmoid(_jnp.zeros_like(pos)) + 1e-8))) / (n - 1)
    return Tensor(loss)


def rank_loss(label, left, right, name=None):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    d = left.data - right.data
    lb = label.data
    return Tensor(_jnp.log1p(_jnp.exp(d)) - lb * d)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return F.margin_ranking_loss(left, right, label, margin=margin,
                                 reduction='none')


def dice_loss(input, label, epsilon=1e-5):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    x = input.data
    lb = F.one_hot(label, x.shape[-1]).data.reshape(x.shape) \
        if label.data.shape != x.shape else label.data
    red = tuple(range(1, x.ndim))
    inter = (x * lb).sum(axis=red)
    union = x.sum(axis=red) + lb.sum(axis=red)
    return Tensor((1 - (2 * inter + epsilon) / (union + epsilon)).mean())


def sigmoid_cross_entropy_with_logits(x, label, ignore_index=-100,
                                      name=None, normalize=False):
    """fluid sigmoid_cross_entropy_with_logits: positions whose label ==
    ignore_index contribute 0; normalize divides by the valid count."""
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    out = F.binary_cross_entropy_with_logits(x, label, reduction='none')
    lb = label.data if isinstance(label, Tensor) else _jnp.asarray(label)
    valid = lb != ignore_index
    o = _jnp.where(valid, out.data, 0.0)
    if normalize:
        o = o / _jnp.maximum(valid.sum().astype(o.dtype), 1.0)
    return Tensor(o)


def teacher_student_sigmoid_loss(input, label,
                                 soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    x = _jnp.clip(input.data.reshape(-1), soft_max_lower_bound,
                  soft_max_up_bound)
    z = label.data.reshape(-1)
    loss = _jnp.log(1 + _jnp.exp(-_jnp.abs(x))) + _jnp.maximum(x, 0.0) \
        - x * z
    return Tensor(loss[:, None])


def kldiv_loss(x, target, reduction='mean', name=None):
    return F.kl_div(x, target, reduction=reduction)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(_jnp.clip(slope * x.data + offset, 0.0, 1.0))


def hard_swish(x, threshold=6.0, scale=6.0, offset=3.0, name=None):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(x.data * _jnp.clip(x.data + offset, 0, threshold)
                  / scale)


def swish(x, beta=1.0, name=None):
    from ..core.tensor import Tensor
    return Tensor(x.data * jnn_sigmoid(beta * x.data))


def mish(x, name=None):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(x.data * _jnp.tanh(_jnp.log1p(_jnp.exp(x.data))))


def brelu(x, t_min=0.0, t_max=24.0, name=None):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(_jnp.clip(x.data, t_min, t_max))


def soft_relu(x, threshold=40.0, name=None):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(_jnp.log1p(_jnp.exp(_jnp.clip(x.data, -threshold,
                                                threshold))))


def jnn_sigmoid(v):
    import jax
    return jax.nn.sigmoid(v)


def sums(input, out=None):
    out_t = input[0]
    for t in input[1:]:
        out_t = M.add(out_t, t)
    return out_t


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    v = create_global_var([1], begin - step, 'int64', persistable=True,
                          name=counter_name or '@STEP_COUNTER')
    return v


def has_inf(x):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(_jnp.isinf(x.data).any())


def has_nan(x):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    return Tensor(_jnp.isnan(x.data).any())


def shuffle_channel(x, group, name=None):
    from ..core.tensor import Tensor
    n, c, h, w = x.shape
    r = manip.reshape(x, [n, group, c // group, h, w])
    t = manip.transpose(r, [0, 2, 1, 3, 4])
    return manip.reshape(t, [n, c, h, w])


def add_position_encoding(input, alpha, beta, name=None):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    x = input.data
    B, L, D = x.shape
    pos = _jnp.arange(L)[:, None]
    half = D // 2
    div = _jnp.power(10000.0, _jnp.arange(half) / float(half))
    enc = _jnp.concatenate([_jnp.sin(pos / div), _jnp.cos(pos / div)],
                           axis=1)
    return Tensor(alpha * x + beta * enc[None, :, :D])


def fsp_matrix(x, y):
    from ..core.tensor import Tensor
    import jax.numpy as _jnp
    a, b = x.data, y.data
    n, c1 = a.shape[:2]
    c2 = b.shape[1]
    h = a.shape[2] * a.shape[3]
    return Tensor(_jnp.einsum('nch,ndh->ncd', a.reshape(n, c1, h),
                              b.reshape(n, c2, h)) / h)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype='int64'):
    from ..core.tensor import Tensor
    from ..core import rng as _rng
    import jax
    key = _rng.next_key()
    return Tensor(jax.random.categorical(key, jax.numpy.log(
        x.data + 1e-9), axis=-1))


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    """Parity: fluid.layers.filter_by_instag (host data-prep)."""
    from ..ops import recsys as _rec
    return _rec.filter_by_instag(ins, ins_tag, filter_tag, is_lod,
                                 out_val_if_empty)


# -- recsys / PS tier (fluid.contrib.layers parity) --------------------------

def continuous_value_model(input, cvm, use_cvm=True):
    from ..ops import recsys as _R
    return _R.continuous_value_model(input, cvm, use_cvm=use_cvm)


def data_norm(input, act=None, epsilon=1e-05, param_attr=None,
              data_layout='NCHW', in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              summary_decay=0.9999999):
    """fluid/layers/nn.py data_norm — creates the three persistable
    summary stats and normalizes by them (stat UPDATE happens in the
    training loop via ops.recsys.data_norm_update)."""
    from ..ops import recsys as _R
    d = input.shape[-1]
    bsize = _make_param([d], 'float32', initializer=I.Constant(1e4))
    bsum = _make_param([d], 'float32', initializer=I.Constant(0.0))
    bsq = _make_param([d], 'float32', initializer=I.Constant(1e4))
    y, _, _ = _R.data_norm(input, bsize, bsum, bsq, epsilon=epsilon)
    if act:
        y = getattr(F, act)(y)
    return y


def shuffle_batch(x, seed=None):
    from ..ops import recsys as _R
    out, _idx = _R.shuffle_batch(x, seed=seed or 0)
    return out


def batch_fc(input, param_size, param_attr=None, bias_size=None,
             bias_attr=None, act=None):
    from ..ops import recsys as _R
    w = _make_param(list(param_size), input.dtype, attr=param_attr)
    b = _make_param(list(bias_size), input.dtype, attr=bias_attr,
                    initializer=I.Constant(0.0)) \
        if bias_size is not None else None
    out = _R.batch_fc(input, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def rank_attention(input, rank_offset, rank_param_shape, rank_param_attr=None,
                   max_rank=3, max_size=0):
    from ..ops import recsys as _R
    w = _make_param(list(rank_param_shape), input.dtype,
                    attr=rank_param_attr)
    return _R.rank_attention(input, rank_offset, w, max_rank=max_rank)


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype='int32'):
    """fluid.contrib.layers.tdm_child — the tree-info table is a
    (non-trainable) parameter of shape [node_nums, 3 + child_nums]."""
    from ..ops import recsys as _R
    info = _make_param([node_nums, 3 + child_nums], 'float32',
                       attr=param_attr, initializer=I.Constant(0.0))
    return _R.tdm_child(x, info, child_nums)


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list, leaf_node_num,
                tree_travel_attr=None, tree_layer_attr=None,
                output_positive=True, output_list=False, seed=0,
                tree_dtype='int32', dtype='int32'):
    from ..ops import recsys as _R
    layer_nums = len(neg_samples_num_list)
    travel = _make_param([leaf_node_num, layer_nums], 'float32',
                         attr=tree_travel_attr, initializer=I.Constant(0.0))
    total = int(sum(layer_node_num_list))
    layer = _make_param([total], 'float32', attr=tree_layer_attr,
                        initializer=I.Constant(0.0))
    offs = [0]
    for n in layer_node_num_list:
        offs.append(offs[-1] + int(n))
    return _R.tdm_sampler(x, travel, layer, neg_samples_num_list, offs,
                          output_positive=output_positive, seed=seed)


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype='float32', name=None):
    from ..ops import recsys as _R
    d = x.shape[-1]
    w = _make_param([d, channel_num, d], dtype, attr=param_attr)
    out = _R.match_matrix_tensor(x, y, w)
    if act:
        out = getattr(F, act)(out)
    return out


def var_conv_2d(input, row, col, input_channel, output_channel, filter_size,
                stride=1, param_attr=None, act=None, dtype='float32',
                name=None):
    from ..ops import recsys as _R
    w = _make_param([output_channel,
                     input_channel * filter_size * filter_size], dtype,
                    attr=param_attr)
    out = _R.var_conv_2d(input, w, input_channel, output_channel,
                         filter_size, stride=stride, row_lens=row,
                         col_lens=col)
    if act:
        out = getattr(F, act)(out)
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act='tanh', param_attr=None, bias_attr=None,
              name=None):
    from ..ops import recsys as _R
    fdim = nodes_vector.shape[-1]
    w = _make_param([fdim, 3, output_size, num_filters],
                    nodes_vector.dtype, attr=param_attr)
    out = _R.tree_conv(nodes_vector, edge_set, w, max_depth=max_depth)
    if bias_attr is not False and bias_attr is not None:
        b = _make_param([output_size, num_filters], nodes_vector.dtype,
                        attr=bias_attr, initializer=I.Constant(0.0))
        out = M.add(out, b)
    if act:
        out = getattr(F, act)(out)
    return out


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer, rand_len,
                        drop_out_percent=0.0, is_training=True,
                        use_filter=False, white_list_len=0, black_list_len=0,
                        seed=0, lr=1.0, param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None,
                        distribute_update_vars=None, dtype='float32',
                        seq_lens=None):
    from ..ops import recsys as _R
    w = _make_param([space_len + rand_len, 1], dtype, attr=param_attr)
    return _R.pyramid_hash(input, w, num_emb=num_emb, space_len=space_len,
                           pyramid_layer=pyramid_layer, rand_len=rand_len,
                           seq_lens=seq_lens, seed=seed)


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None):
    """Parity: fluid/layers/nn.py py_func (operators/py_func_op.cc) —
    embed a host python callable as an op in the static program. The
    recorded op runs `func` through `jax.pure_callback` (the XLA host
    callback — the TPU analogue of the reference's interpreter
    re-entry), so it executes inside the one-jit Executor replay.

    `out` declares the result spec: a Variable created via
    `block.create_var(shape=..., dtype=...)`, or a (shape, dtype)
    tuple, or a list of either. With `backward_func(x..., out...,
    dout...) -> dx...` the op is differentiable (also via callback);
    without it, gradients stop.

    Platform note: host callbacks need PJRT send/recv — available on
    CPU and real TPU hosts, but NOT over the axon dev tunnel
    (axon_pjrt raises UNIMPLEMENTED). There, run the py_func program
    eagerly or place its segment under device_guard('cpu')."""
    import jax
    xs = list(x) if isinstance(x, (list, tuple)) else [x]

    def _is_spec(o):
        # a single (shape, dtype) pair, e.g. ([3, 4], 'float32')
        return (isinstance(o, tuple) and len(o) == 2
                and isinstance(o[0], (list, tuple))
                and isinstance(o[1], (str, np.dtype, type)))
    if _is_spec(out) or not isinstance(out, (list, tuple)):
        outs = [out]
        multi_out = False
    else:
        outs = list(out)
        multi_out = True

    def spec_of(o):
        if _is_spec(o):
            shape, dt = o
        else:
            shape, dt = o.shape, o.dtype
        import jax.numpy as _jnp
        if any(d is None or int(d) < 1 for d in shape):
            raise ValueError(
                f"py_func out shape {tuple(shape)} has dynamic dims; "
                "XLA host callbacks need static shapes — declare the "
                "concrete batch size (the reference's -1 dims rely on "
                "interpreter-side shape inference this backend "
                "deliberately does not do)")
        shape = tuple(int(d) for d in shape)
        return jax.ShapeDtypeStruct(shape, _jnp.dtype(dt))

    out_specs = [spec_of(o) for o in outs]

    def host_fwd(*arrays):
        res = func(*[np.asarray(a) for a in arrays])
        res = res if isinstance(res, (list, tuple)) else [res]
        return tuple(np.asarray(r, dtype=sp.dtype).reshape(sp.shape)
                     for r, sp in zip(res, out_specs))

    def fwd_fn(*arrays):
        res = jax.pure_callback(host_fwd, tuple(out_specs), *arrays)
        return tuple(res) if multi_out else res[0]

    if backward_func is not None:
        skip = skip_vars_in_backward_input or []
        skip = skip if isinstance(skip, (list, tuple)) else [skip]
        # positions of forward inputs the reference drops from
        # backward_func's argument list (matched by object identity)
        skip_idx = {i for i, v in enumerate(xs)
                    if any(v is sv for sv in skip)}

        @jax.custom_vjp
        def op(*arrays):
            return fwd_fn(*arrays)

        def op_fwd(*arrays):
            o = fwd_fn(*arrays)
            return o, (arrays, o if multi_out else (o,))

        def op_bwd(res, cts):
            arrays, os_ = res
            cts = cts if isinstance(cts, tuple) else (cts,)
            in_specs = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype)
                             for a in arrays)
            passed = tuple(a for i, a in enumerate(arrays)
                           if i not in skip_idx)

            def host_bwd(*all_args):
                grads = backward_func(*[np.asarray(a)
                                        for a in all_args])
                grads = grads if isinstance(grads, (list, tuple)) \
                    else [grads]
                grads = list(grads)
                # zeros for skipped inputs, in position
                full = []
                gi = 0
                for i, sp in enumerate(in_specs):
                    if i in skip_idx:
                        full.append(np.zeros(sp.shape, sp.dtype))
                    else:
                        full.append(np.asarray(
                            grads[gi], dtype=sp.dtype).reshape(sp.shape))
                        gi += 1
                return tuple(full)
            return jax.pure_callback(host_bwd, in_specs,
                                     *passed, *os_, *cts)

        op.defvjp(op_fwd, op_bwd)
        run_fn = op
    else:
        run_fn = fwd_fn

    from ..core.autograd import run_op as _run_op
    return _run_op('py_func', run_fn, xs,
                   n_nondiff=0 if backward_func is not None else len(xs))


def multi_box_head(inputs, image, base_size, num_classes,
                   aspect_ratios, min_ratio=None, max_ratio=None,
                   min_sizes=None, max_sizes=None, steps=None,
                   step_w=None, step_h=None, offset=0.5,
                   variance=(0.1, 0.1, 0.2, 0.2), flip=True, clip=False,
                   kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """Parity: fluid/layers/detection.py multi_box_head — the SSD
    detection head: per feature map, a prior_box ladder plus 1x1/3x3
    conv loc & conf predictors, flattened and concatenated across maps.
    Returns (mbox_locs [N, P, 4], mbox_confs [N, P, C], boxes [P, 4],
    variances [P, 4])."""
    from ..vision import detection as _det
    from ..ops import manip as _m
    n_in = len(inputs)
    if min_sizes is None:
        # the reference's min/max ratio ladder: first map fixed at
        # 10%/20% of base_size, the rest stepping min_ratio..max_ratio
        step = int(np.floor((max_ratio - min_ratio) / (n_in - 2))) \
            if n_in > 2 else 0
        min_sizes = [base_size * 0.1]
        max_sizes = [base_size * 0.2]
        r = min_ratio
        for _ in range(1, n_in):
            min_sizes.append(base_size * r / 100.0)
            max_sizes.append(base_size * (r + step) / 100.0)
            r += step
    if not isinstance(min_sizes[0], (list, tuple)):
        min_sizes = [[m] for m in min_sizes]
    if max_sizes is not None and not isinstance(max_sizes[0],
                                                (list, tuple)):
        max_sizes = [[m] for m in max_sizes]
    if not isinstance(aspect_ratios[0], (list, tuple)):
        aspect_ratios = [aspect_ratios] * n_in

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, x in enumerate(inputs):
        mins = [float(v) for v in min_sizes[i]]
        maxs = [float(v) for v in max_sizes[i]] if max_sizes else None
        st = (0.0, 0.0)
        if steps is not None:
            st = steps[i] if isinstance(steps[i], (list, tuple)) \
                else [steps[i], steps[i]]
        elif step_w is not None:
            st = [step_w[i], step_h[i] if step_h is not None else 0.0]
        box, var = _det.prior_box(
            x, image, mins, maxs, aspect_ratios[i], variance=variance,
            flip=flip, clip=clip, steps=st, offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        P_i = int(np.prod(box.shape[:-1]))
        boxes_l.append(_m.reshape(box, [P_i, 4]))
        vars_l.append(_m.reshape(var, [P_i, 4]))
        num_priors = P_i // (int(x.shape[2]) * int(x.shape[3]))
        cin = int(x.shape[1])
        wl = _make_param([num_priors * 4, cin, kernel_size, kernel_size],
                         x.dtype)
        bl = _make_param([num_priors * 4], x.dtype,
                         initializer=I.Constant(0.0))
        loc = F.conv2d(x, wl, bl, stride=stride, padding=pad)
        loc = _m.transpose(loc, [0, 2, 3, 1])
        locs.append(_m.reshape(loc, [int(x.shape[0]), P_i, 4]))
        wc = _make_param(
            [num_priors * num_classes, cin, kernel_size, kernel_size],
            x.dtype)
        bc = _make_param([num_priors * num_classes], x.dtype,
                         initializer=I.Constant(0.0))
        conf = F.conv2d(x, wc, bc, stride=stride, padding=pad)
        conf = _m.transpose(conf, [0, 2, 3, 1])
        confs.append(_m.reshape(conf,
                                [int(x.shape[0]), P_i, num_classes]))
    mbox_locs = _m.concat(locs, axis=1)
    mbox_confs = _m.concat(confs, axis=1)
    boxes = _m.concat(boxes_l, axis=0)
    variances = _m.concat(vars_l, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def _reexport():
    """The rest of the fluid.layers vocabulary records through the shared
    op layer — re-export so `static.nn.<name>` resolves (fluid/layers
    nn.py / sequence_lod.py / detection.py / control_flow.py names)."""
    from ..ops import contrib as _contrib
    from ..ops import sequence as _seq
    from . import fluid_layers as _fl
    from ..ops import creation as _cr
    from ..vision import detection as _det
    from ..vision import ops as _vops
    from . import control_flow as _cf
    g = globals()
    for mod, names in (
        (F, ['relu', 'softmax', 'log_softmax', 'sigmoid', 'tanh', 'gelu',
             'max_pool2d', 'avg_pool2d', 'adaptive_avg_pool2d',
             'adaptive_max_pool2d', 'one_hot', 'maxout', 'instance_norm',
             'pad', 'interpolate', 'grid_sample', 'pixel_shuffle',
             'label_smooth', 'kl_div', 'mse_loss', 'l1_loss',
             'smooth_l1_loss', 'margin_ranking_loss', 'nll_loss',
             'binary_cross_entropy', 'binary_cross_entropy_with_logits',
             'square_error_cost', 'elu', 'selu', 'leaky_relu', 'conv3d',
             'conv2d_transpose', 'unfold', 'affine_grid', 'temporal_shift',
             'npair_loss', 'sequence_mask', 'grid_sample']),
        (M, ['scale', 'clip', 'clip_by_norm', 'assign', 'increment',
             'stanh', 'sign', 'log', 'pow', 'topk', 'argmax', 'argmin',
             'argsort', 'where', 'multiplex', 'diag', 'isfinite',
             'equal', 'not_equal', 'less_than', 'less_equal',
             'greater_than', 'greater_equal', 'logical_and', 'logical_or',
             'logical_xor', 'logical_not', 'cumsum', 'crop']),
        (manip, ['cast', 'concat', 'reshape', 'squeeze', 'unsqueeze',
                 'transpose', 'split', 'stack', 'unstack', 'unbind',
                 'slice', 'strided_slice', 'gather', 'gather_nd',
                 'scatter', 'scatter_nd', 'scatter_nd_add', 'expand',
                 'expand_as', 'flatten', 'flip', 'shard_index', 'shape',
                 'space_to_depth', 'tile', 'triu', 'unique',
                 'index_sample']),
        (_cr, ['zeros', 'ones', 'zeros_like', 'ones_like', 'eye',
               'linspace', 'arange', 'uniform', 'full', 'full_like',
               'randperm']),
        (_contrib, ['unpool', 'im2sequence', 'spp', 'mean_iou',
                    'precision_recall', 'positive_negative_pair',
                    'affine_channel', 'sample_logits', 'random_crop',
                    'polygon_box_transform']),
        (_seq, ['sequence_pad', 'sequence_unpad', 'sequence_expand',
                'sequence_reverse', 'linear_chain_crf', 'crf_decoding',
                'beam_search', 'sequence_concat', 'sequence_conv',
                'sequence_enumerate', 'sequence_expand_as',
                'sequence_first_step', 'sequence_last_step',
                'sequence_pool', 'sequence_reshape', 'sequence_softmax',
                'sequence_slice', 'sequence_scatter', 'sequence_unpad',
                'edit_distance', 'ctc_greedy_decoder', 'warpctc',
                'gather_tree']),
        (_det, ['retinanet_target_assign',
                'roi_perspective_transform',
                'multiclass_nms', 'bipartite_match', 'iou_similarity',
                'yolo_box', 'prior_box', 'box_coder', 'box_clip',
                'anchor_generator', 'generate_proposals', 'matrix_nms',
                'density_prior_box', 'distribute_fpn_proposals',
                'collect_fpn_proposals', 'roi_align', 'roi_pool',
                'ssd_loss', 'target_assign', 'detection_output',
                'rpn_target_assign', 'sigmoid_focal_loss',
                'yolov3_loss', 'prroi_pool', 'psroi_pool',
                'locality_aware_nms', 'polygon_box_transform',
                'retinanet_detection_output', 'box_decoder_and_assign',
                'generate_proposal_labels', 'generate_mask_labels',
                'multi_box_head', 'deformable_roi_pooling']),
        (_cf, ['while_loop', 'cond', 'switch_case', 'case']),
        (_fl, ['rank', 'is_empty', 'reverse', 'crop_tensor', 'pad2d',
               'pad_constant_like', 'adaptive_pool2d', 'adaptive_pool3d',
               'pool3d', 'lrn', 'grid_sampler', 'warpctc',
               'ctc_greedy_decoder', 'unique_with_counts',
               'uniform_random_batch_size_like',
               'gaussian_random_batch_size_like', 'inplace_abn',
               'similarity_focus', 'noam_decay', 'exponential_decay',
               'natural_exp_decay', 'inverse_time_decay',
               'polynomial_decay', 'piecewise_decay', 'cosine_decay',
               'linear_lr_warmup', 'rnn', 'birnn',
               'conv3d_transpose', 'resize_linear', 'resize_trilinear',
               'image_resize_short', 'gru_unit', 'lstm_unit',
               'dynamic_lstm', 'dynamic_lstmp', 'dynamic_gru', 'lstm',
               'beam_search_decode', 'chunk_eval', 'create_array',
               'array_write', 'array_read', 'array_length',
               'tensor_array_to_tensor', 'Print', 'Assert', 'While',
               'Switch', 'IfElse', 'StaticRNN', 'DynamicRNN',
               'lod_append', 'lod_reset', 'reorder_lod_tensor_by_rank',
               'get_tensor_from_selected_rows', 'merge_selected_rows',
               'py_reader', 'double_buffer', 'read_file',
               'create_py_reader_by_data']),
        (_contrib, ['center_loss', 'sampled_softmax_with_cross_entropy',
                    'ctc_align']),
        (_vops, ['roi_align', 'roi_pool']),
    ):
        for n in names:
            if hasattr(mod, n) and n not in g:
                g[n] = getattr(mod, n)
    # legacy spellings of names the modern API renamed
    for legacy, mod, modern in (
        ('range', _cr, 'arange'), ('gaussian_random', _cr, 'gaussian'),
        ('uniform_random', _cr, 'uniform'), ('size', manip, 'numel'),
        ('hash', _contrib, 'row_hash'),
    ):
        if hasattr(mod, modern) and legacy not in g:
            g[legacy] = getattr(mod, modern)


def _nn_aliases():
    from .. import nn as _nnmod
    g = globals()
    for fluid_name, modern in (
        ('RNNCell', 'RNNCellBase'), ('GRUCell', 'GRUCell'),
        ('LSTMCell', 'LSTMCell'), ('BeamSearchDecoder',
                                   'BeamSearchDecoder'),
        ('Decoder', 'Decoder'), ('dynamic_decode', 'dynamic_decode'),
    ):
        if hasattr(_nnmod, modern):
            g.setdefault(fluid_name, getattr(_nnmod, modern))


_nn_aliases()
del _nn_aliases


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """fluid.layers.accuracy (operators/metrics/accuracy_op.cc) —
    top-k accuracy as a recordable op (works on symbolic Variables,
    unlike the eager paddle.metric.accuracy helper)."""
    import jax.numpy as _jnp
    from ..core.autograd import run_op as _run_op
    from ..ops.common import as_tensor as _as_t
    inp = _as_t(input)
    lab = _as_t(label, ref=inp)

    def fn(p, l):
        kk = min(int(k), p.shape[-1])
        _, topi = jax.lax.top_k(p, kk)
        hit = (topi == l.reshape(-1, 1)).any(axis=-1)
        return hit.mean(dtype=_jnp.float32)
    import jax
    return _run_op('accuracy', fn, [inp, lab], n_nondiff=2)


def auc(input, label, curve='ROC', num_thresholds=4095, topk=1,
        slide_steps=1, name=None):
    """fluid.layers.auc (operators/metrics/auc_op.cc) — batch ROC-AUC
    via thresholded TP/FP histograms, recordable (the reference's
    stateful accumulators live in the metric class for streaming use;
    this op returns the current batch's AUC like auc_op's BatchAuc)."""
    import jax
    import jax.numpy as _jnp
    from ..core.autograd import run_op as _run_op
    from ..ops.common import as_tensor as _as_t
    inp = _as_t(input)
    lab = _as_t(label, ref=inp)
    T = int(num_thresholds)

    def fn(p, l):
        pos_score = p[:, -1] if p.ndim > 1 else p
        y = l.reshape(-1).astype(_jnp.int32)
        bins = _jnp.clip((pos_score * T).astype(_jnp.int32), 0, T)
        tp_h = _jnp.zeros((T + 1,), _jnp.float32).at[bins].add(
            (y == 1).astype(_jnp.float32))
        fp_h = _jnp.zeros((T + 1,), _jnp.float32).at[bins].add(
            (y == 0).astype(_jnp.float32))
        # cumulate from the top threshold down
        tp = _jnp.cumsum(tp_h[::-1])
        fp = _jnp.cumsum(fp_h[::-1])
        tot_p = _jnp.maximum(tp[-1], 1.0)
        tot_n = _jnp.maximum(fp[-1], 1.0)
        if curve == 'PR':
            # precision-recall AUC over the same threshold sweep
            rec = tp / tot_p
            prec = tp / _jnp.maximum(tp + fp, 1.0)
            rec = _jnp.concatenate([_jnp.zeros((1,)), rec])
            prec = _jnp.concatenate([_jnp.ones((1,)), prec])
            return _jnp.trapezoid(prec, rec).astype(_jnp.float32)
        tpr = _jnp.concatenate([_jnp.zeros((1,)), tp]) / tot_p
        fpr = _jnp.concatenate([_jnp.zeros((1,)), fp]) / tot_n
        return _jnp.trapezoid(tpr, fpr).astype(_jnp.float32)
    return _run_op('auc', fn, [inp, lab], n_nondiff=2)


def _data_alias():
    g = globals()
    from .program import data as _data_fn
    g.setdefault('data', _data_fn)


_data_alias()
del _data_alias
_reexport()
del _reexport
