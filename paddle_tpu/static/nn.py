"""Static-graph layer API.

Reference parity: python/paddle/static/nn (fluid/layers/nn.py subset): fc,
conv2d, embedding, batch_norm, etc. These build Parameters in the current
Program and record ops through the shared op layer.
"""
import numpy as np
import jax.numpy as jnp

from ..core import dtypes
from ..ops import nn_ops as F
from ..ops import math as M
from ..ops import manip
from ..nn import initializer as I
from .program import default_main_program, Parameter

__all__ = ['fc', 'embedding', 'conv2d', 'batch_norm', 'cross_entropy',
           'softmax_with_cross_entropy', 'mean', 'dropout']


def _make_param(shape, dtype='float32', initializer=None, attr=None):
    prog = default_main_program()
    block = prog.global_block()
    init = initializer
    if attr is not None and getattr(attr, 'initializer', None) is not None:
        init = attr.initializer
    name = None
    if attr is not None and getattr(attr, 'name', None):
        name = attr.name
    return block.create_parameter(name=name, shape=shape, dtype=dtype,
                                  initializer=init or I.XavierUniform())


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Parity: fluid/layers/nn.py fc → mul + elementwise_add (+act)."""
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _make_param([in_dim, size], x.dtype, attr=weight_attr)
    if len(x.shape) > num_flatten_dims + 1:
        x = manip.reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim])
    out = M.matmul(x, w)
    if bias_attr is not False:
        b = _make_param([size], x.dtype, initializer=I.Constant(0.0),
                        attr=bias_attr)
        out = M.add(out, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype='float32'):
    w = _make_param(list(size), dtype, attr=param_attr)
    return F.embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1]
    w = _make_param([num_filters, cin // groups, k[0], k[1]], input.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               **kwargs):
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    scale = _make_param([c], input.dtype, initializer=I.Constant(1.0),
                        attr=param_attr)
    bias = _make_param([c], input.dtype, initializer=I.Constant(0.0),
                       attr=bias_attr)

    # Static BN uses in-graph batch statistics (global-stat tracking needs
    # state vars; the dygraph path owns that).
    from ..core.autograd import run_op
    ch_axis = 1 if data_layout == 'NCHW' else input.ndim - 1
    axes = tuple(i for i in range(input.ndim) if i != ch_axis)

    def fn(a, w, b):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) / jnp.sqrt(v + epsilon)
        return out * w.reshape(shape) + b.reshape(shape)
    out = run_op('batch_norm', fn, [input, scale, bias])
    if act:
        out = getattr(F, act)(out)
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return F.cross_entropy(input, label, soft_label=soft_label,
                           ignore_index=ignore_index, reduction='none',
                           use_softmax=False)


def softmax_with_cross_entropy(logits, label, **kwargs):
    return F.softmax_with_cross_entropy(logits, label, **kwargs)


def mean(x):
    return M.mean(x)


def dropout(x, dropout_prob=0.5, is_test=False, **kwargs):
    return F.dropout(x, p=dropout_prob, training=not is_test)
