"""Static-graph layer API.

Reference parity: python/paddle/static/nn (fluid/layers/nn.py subset): fc,
conv2d, embedding, batch_norm, etc. These build Parameters in the current
Program and record ops through the shared op layer.
"""
import numpy as np
import jax.numpy as jnp

from ..core import dtypes
from ..ops import nn_ops as F
from ..ops import math as M
from ..ops import manip
from ..nn import initializer as I
from .program import default_main_program, Parameter

__all__ = ['fc', 'embedding', 'conv2d', 'batch_norm', 'cross_entropy',
           'softmax_with_cross_entropy', 'mean', 'dropout']


def _make_param(shape, dtype='float32', initializer=None, attr=None):
    prog = default_main_program()
    block = prog.global_block()
    init = initializer
    if attr is not None and getattr(attr, 'initializer', None) is not None:
        init = attr.initializer
    name = None
    if attr is not None and getattr(attr, 'name', None):
        name = attr.name
    return block.create_parameter(name=name, shape=shape, dtype=dtype,
                                  initializer=init or I.XavierUniform())


def fc(x, size, num_flatten_dims=1, weight_attr=None, bias_attr=None,
       activation=None, name=None):
    """Parity: fluid/layers/nn.py fc → mul + elementwise_add (+act)."""
    in_dim = int(np.prod(x.shape[num_flatten_dims:]))
    w = _make_param([in_dim, size], x.dtype, attr=weight_attr)
    if len(x.shape) > num_flatten_dims + 1:
        x = manip.reshape(x, list(x.shape[:num_flatten_dims]) + [in_dim])
    out = M.matmul(x, w)
    if bias_attr is not False:
        b = _make_param([size], x.dtype, initializer=I.Constant(0.0),
                        attr=bias_attr)
        out = M.add(out, b)
    if activation:
        out = getattr(F, activation)(out)
    return out


def embedding(input, size, is_sparse=False, padding_idx=None,
              param_attr=None, dtype='float32'):
    w = _make_param(list(size), dtype, attr=param_attr)
    return F.embedding(input, w, padding_idx=padding_idx)


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1]
    w = _make_param([num_filters, cin // groups, k[0], k[1]], input.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    out = F.conv2d(input, w, b, stride=stride, padding=padding,
                   dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-05,
               param_attr=None, bias_attr=None, data_layout='NCHW',
               **kwargs):
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    scale = _make_param([c], input.dtype, initializer=I.Constant(1.0),
                        attr=param_attr)
    bias = _make_param([c], input.dtype, initializer=I.Constant(0.0),
                       attr=bias_attr)

    # Static BN uses in-graph batch statistics (global-stat tracking needs
    # state vars; the dygraph path owns that).
    from ..core.autograd import run_op
    ch_axis = 1 if data_layout == 'NCHW' else input.ndim - 1
    axes = tuple(i for i in range(input.ndim) if i != ch_axis)

    def fn(a, w, b):
        shape = [1] * a.ndim
        shape[ch_axis] = a.shape[ch_axis]
        m = jnp.mean(a, axis=axes, keepdims=True)
        v = jnp.var(a, axis=axes, keepdims=True)
        out = (a - m) / jnp.sqrt(v + epsilon)
        return out * w.reshape(shape) + b.reshape(shape)
    out = run_op('batch_norm', fn, [input, scale, bias])
    if act:
        out = getattr(F, act)(out)
    return out


def cross_entropy(input, label, soft_label=False, ignore_index=-100):
    return F.cross_entropy(input, label, soft_label=soft_label,
                           ignore_index=ignore_index, reduction='none',
                           use_softmax=False)


def softmax_with_cross_entropy(logits, label, **kwargs):
    return F.softmax_with_cross_entropy(logits, label, **kwargs)


def mean(x):
    return M.mean(x)


def dropout(x, dropout_prob=0.5, is_test=False, **kwargs):
    return F.dropout(x, p=dropout_prob, training=not is_test)


# ---------------------------------------------------------------------------
# fluid.layers breadth (P23): the wider static surface — parameterized
# wrappers where fluid created parameters, re-exports where the shared op
# layer already records (fluid/layers/nn.py + sequence_lod.py +
# detection.py + control_flow.py surfaces)
# ---------------------------------------------------------------------------

def conv2d_transpose(input, num_filters, filter_size, stride=1, padding=0,
                     dilation=1, groups=1, param_attr=None, bias_attr=None,
                     act=None, name=None):
    """Parity: fluid/layers/nn.py conv2d_transpose."""
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1]
    w = _make_param([cin, num_filters // groups, k[0], k[1]], input.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    out = F.conv2d_transpose(input, w, b, stride=stride, padding=padding,
                             dilation=dilation, groups=groups)
    if act:
        out = getattr(F, act)(out)
    return out


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-05, param_attr=None, bias_attr=None, act=None,
               name=None):
    """Parity: fluid/layers/nn.py layer_norm."""
    import numpy as _np
    norm_shape = [int(_np.prod(input.shape[begin_norm_axis:]))]
    w = _make_param(norm_shape, input.dtype,
                    initializer=I.Constant(1.0),
                    attr=param_attr) if scale else None
    b = _make_param(norm_shape, input.dtype,
                    initializer=I.Constant(0.0),
                    attr=bias_attr) if shift else None
    # dynamic (-1) leading dims: flatten against the single CONCRETE
    # trailing size so only one unknown axis remains in the reshape
    lead = list(input.shape[:begin_norm_axis])
    if any(d is None or d < 0 for d in lead):
        lead = [-1]
    flat = manip.reshape(input, lead + [norm_shape[0]])
    out = F.layer_norm(flat, norm_shape, w, b, epsilon=epsilon)
    out = manip.reshape(out, [d if d is not None else -1
                              for d in input.shape])
    if act:
        out = getattr(F, act)(out)
    return out


def group_norm(input, groups, epsilon=1e-05, param_attr=None,
               bias_attr=None, act=None, data_layout='NCHW', name=None):
    """Parity: fluid/layers/nn.py group_norm."""
    c = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    w = _make_param([c], input.dtype, initializer=I.Constant(1.0),
                    attr=param_attr)
    b = _make_param([c], input.dtype, initializer=I.Constant(0.0),
                    attr=bias_attr)
    out = F.group_norm(input, groups, epsilon=epsilon, weight=w, bias=b,
                       data_format=data_layout)
    if act:
        out = getattr(F, act)(out)
    return out


def prelu(x, mode='all', param_attr=None, name=None):
    """Parity: fluid/layers/nn.py prelu (modes all/channel/element)."""
    if mode == 'all':
        shape = [1]
    elif mode == 'channel':
        shape = [x.shape[1]]
    else:
        shape = list(x.shape[1:])
    a = _make_param(shape, x.dtype, initializer=I.Constant(0.25),
                    attr=param_attr)
    return F.prelu(x, a)


def nce(input, label, num_total_classes, num_neg_samples=5,
        param_attr=None, bias_attr=None, sampler='uniform', name=None):
    """Parity: fluid/layers/nn.py nce (parameterized wrapper over the op
    — operators/nce_op.cc)."""
    from ..ops import contrib
    d = input.shape[-1]
    w = _make_param([num_total_classes, d], input.dtype, attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_total_classes], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    return contrib.nce(input, label, num_total_classes, w, b,
                       num_neg_samples=num_neg_samples, sampler=sampler)


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    """Parity: fluid/layers/nn.py hsigmoid
    (operators/hierarchical_sigmoid_op.cc, default complete tree)."""
    from ..ops import contrib
    d = input.shape[-1]
    w = _make_param([num_classes - 1, d], input.dtype, attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_classes - 1], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    return contrib.hsigmoid_loss(input, label, num_classes, w, b)


def row_conv(input, future_context_size, param_attr=None, act=None,
             name=None):
    """Parity: fluid/layers/nn.py row_conv (operators/row_conv_op.cc)."""
    from ..ops import contrib
    d = input.shape[-1]
    w = _make_param([future_context_size + 1, d], input.dtype,
                    attr=param_attr)
    out = contrib.row_conv(input, w)
    if act:
        out = getattr(F, act)(out)
    return out


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    """Parity: fluid/layers/nn.py deformable_conv
    (operators/deformable_conv_op.cc v1/v2)."""
    from ..vision.detection import deform_conv2d
    k = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    cin = input.shape[1]
    w = _make_param([num_filters, cin // groups, k[0], k[1]], input.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([num_filters], input.dtype,
                        initializer=I.Constant(0.0), attr=bias_attr)
    return deform_conv2d(input, offset, w, b, stride=stride,
                         padding=padding, dilation=dilation,
                         deformable_groups=deformable_groups,
                         groups=groups,
                         mask=mask if modulated else None)


def bilinear_tensor_product(x, y, size, act=None, param_attr=None,
                            bias_attr=None, name=None):
    """Parity: fluid/layers/nn.py bilinear_tensor_product."""
    from ..ops import linalg
    w = _make_param([size, x.shape[-1], y.shape[-1]], x.dtype,
                    attr=param_attr)
    b = None
    if bias_attr is not False:
        b = _make_param([size], x.dtype, initializer=I.Constant(0.0),
                        attr=bias_attr)
    out = linalg.bilinear_tensor_product(x, y, w, b)
    if act:
        out = getattr(F, act)(out)
    return out


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    from ..ops import contrib
    return contrib.spectral_norm(weight, dim=dim, power_iters=power_iters,
                                 eps=eps)


def _reexport():
    """The rest of the fluid.layers vocabulary records through the shared
    op layer — re-export so `static.nn.<name>` resolves (fluid/layers
    nn.py / sequence_lod.py / detection.py / control_flow.py names)."""
    from ..ops import contrib as _contrib
    from ..ops import sequence as _seq
    from ..vision import detection as _det
    from . import control_flow as _cf
    g = globals()
    for mod, names in (
        (F, ['relu', 'softmax', 'log_softmax', 'sigmoid', 'tanh', 'gelu',
             'max_pool2d', 'avg_pool2d', 'adaptive_avg_pool2d',
             'adaptive_max_pool2d', 'one_hot', 'maxout', 'instance_norm',
             'pad', 'interpolate', 'grid_sample', 'pixel_shuffle',
             'label_smooth', 'kl_div', 'mse_loss', 'l1_loss',
             'smooth_l1_loss', 'margin_ranking_loss', 'nll_loss',
             'binary_cross_entropy', 'binary_cross_entropy_with_logits',
             'square_error_cost']),
        (_contrib, ['unpool', 'im2sequence', 'spp']),
        (_seq, ['sequence_pad', 'sequence_unpad', 'sequence_expand',
                'sequence_reverse', 'linear_chain_crf', 'crf_decoding',
                'beam_search']),
        (_det, ['multiclass_nms', 'bipartite_match', 'iou_similarity',
                'yolo_box', 'prior_box', 'box_coder', 'box_clip',
                'anchor_generator', 'generate_proposals', 'matrix_nms']),
        (_cf, ['while_loop', 'cond', 'switch_case', 'case']),
    ):
        for n in names:
            if hasattr(mod, n) and n not in g:
                g[n] = getattr(mod, n)


_reexport()
del _reexport
