"""Static autodiff — op-level append_backward.

Reference parity: python/paddle/fluid/backward.py append_backward (2,017 LoC,
per-op GradOpMaker): walks the block's ops in reverse from the loss, appends
one `<type>_grad` op per forward op (inputs = forward inputs + output
cotangents `<name>@GRAD`, outputs = input cotangents), inserts `sum` ops when
a variable feeds several consumers, and marks every grad op with
op_role=Backward and the forward op's op_device. These recorded ops are what
the distributed program rewrites (pipeline split, sharding prune) key on —
exactly as in the reference, where the sharding/pipeline passes move/prune
grad ops by role and device.

TPU-native grad maker: instead of ~700 hand-written GradOpMakers, each grad
op's fn is derived generically from the forward op's jax fn with `jax.vjp`
at replay-trace time — XLA CSEs the re-traced forward with the primal pass,
so the compiled program matches what a hand-fused backward would give.
"""
import jax
import jax.numpy as jnp

from ..core import dtypes
from .program import (Variable, Parameter, Operator, OpRole,
                      default_main_program, _ConstVar)


def _is_float_var(v):
    try:
        return dtypes.is_floating(v.dtype)
    except Exception:
        return False


def _make_grad_fn(op, n_in, n_out, grad_idx):
    """Build the generic vjp-based grad fn for `op`.

    Signature: (primal inputs..., output cotangents...) ->
    (cotangents of inputs listed in grad_idx...).
    """
    multi = getattr(op, 'multi_out', False) or n_out > 1
    fwd_fn = op.fn

    def grad_fn(*args):
        import numpy as _np
        primals, cots = args[:n_in], args[n_in:]
        outs, vjp_fn = jax.vjp(lambda *xs: fwd_fn(*xs), *primals)
        # integer outputs (e.g. top_k indices) take float0 cotangents
        out_list = list(outs) if isinstance(outs, (tuple, list)) else [outs]
        cots = [c if dtypes.is_floating(o.dtype)
                else _np.zeros(o.shape, jax.dtypes.float0)
                for o, c in zip(out_list, cots)]
        cot = tuple(cots) if multi else cots[0]
        dxs = vjp_fn(cot)
        outs = []
        for i in grad_idx:
            d = dxs[i]
            # jax returns float0 cotangents for int inputs; callers never
            # request those (grad_idx is float-only), but guard anyway
            if d.dtype == jax.dtypes.float0:
                d = jnp.zeros(primals[i].shape, jnp.float32)
            outs.append(d)
        return tuple(outs) if len(outs) > 1 else outs[0]
    return grad_fn


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Parity: fluid/backward.py append_backward — appends real grad ops.

    Returns [(param, grad_var)] like the reference; grad vars are named
    `<param>@GRAD` and the program gains Backward-role ops that the
    Executor replays like any others.
    """
    prog = loss.block.program if hasattr(loss, 'block') \
        else default_main_program()
    prog._loss_var = loss
    block = prog.global_block()
    params = parameter_list
    if params is None:
        params = [p for p in prog.all_parameters() if p.trainable]
    else:
        params = [block.var(p) if isinstance(p, str) else p for p in params]
    no_grad = set()
    for t in (no_grad_set or []):
        no_grad.add(t if isinstance(t, str) else t.name)

    # -- forward sweep: which vars (transitively) depend on the params ------
    needs = {p.name for p in params if p.name not in no_grad}
    ops = list(block.ops)
    # distributed_lookup outputs are reads of REMOTE parameters (PS
    # tables): their cotangents are what distributed_push sends back to
    # the server, so they are always grad targets even though no local
    # Parameter backs them (trainer_pass append_send_ops role)
    for op in ops:
        if op.type == 'distributed_lookup':
            needs.update(n for n in op.output_names if n not in no_grad)
    for op in ops:
        if any(n in needs for n in op.input_names):
            needs.update(op.output_names)
    needs -= no_grad

    grad_of = {}    # var name -> its current cotangent var name

    def _new_grad_var(name, like, suffix=''):
        gname = name + '@GRAD' + suffix
        if gname in block.vars:   # uniquify renames
            k = 0
            while f"{gname}@RENAME@{k}" in block.vars:
                k += 1
            gname = f"{gname}@RENAME@{k}"
        gv = Variable(block, gname, like.shape, like.dtype)
        gv.op_role = OpRole.Backward
        block.vars[gname] = gv
        return gv

    def _accumulate(name, contrib_name, device):
        """Point grad_of[name] at contrib, summing with any prior one
        (parity: backward.py gradient aggregation via `sum` ops)."""
        prev = grad_of.get(name)
        if prev is None:
            grad_of[name] = contrib_name
            return
        target = _new_grad_var(name, block.vars[name], suffix='')
        sum_op = Operator(
            'sum', lambda *xs: sum(xs[1:], xs[0]),
            [prev, contrib_name], [target.name], {},
            op_role=OpRole.Backward)
        sum_op.op_device = device
        block.append_op(sum_op)
        grad_of[name] = target.name

    # -- seed: d loss / d loss = 1 ------------------------------------------
    if loss.name in needs:
        seed = _new_grad_var(loss.name, loss)
        producers = {}
        for op in ops:
            for o in op.output_names:
                producers[o] = op
        loss_op = producers.get(loss.name)
        seed_op = Operator('fill_any_like', lambda x: jnp.ones_like(x),
                           [loss.name], [seed.name], {'value': 1.0},
                           op_role=OpRole.Backward | OpRole.Loss)
        seed_op.op_device = loss_op.op_device if loss_op is not None else ''
        block.append_op(seed_op)
        grad_of[loss.name] = seed.name

        # -- reverse sweep ---------------------------------------------------
        for op in reversed(ops):
            if not any(o in grad_of for o in op.output_names):
                continue
            # differentiable inputs that need a cotangent
            grad_idx = []
            for i, iname in enumerate(op.input_names):
                v = block.vars.get(iname)
                if (iname in needs and v is not None
                        and not isinstance(v, _ConstVar)
                        and _is_float_var(v)):
                    grad_idx.append(i)
            if not grad_idx:
                continue
            if op.type in ('conditional_block', 'while'):
                raise NotImplementedError(
                    "append_backward through conditional_block/while "
                    "sub-block ops is not supported: keep recorded "
                    "control flow out of the loss path, or use the "
                    "dygraph/jit path (lax.cond differentiates; "
                    "lax.while_loop is not reverse-differentiable)")
            # cotangents for every output (zeros where unused)
            cot_names = []
            for oname in op.output_names:
                if oname in grad_of:
                    cot_names.append(grad_of[oname])
                else:
                    zv = _new_grad_var(oname, block.vars[oname])
                    z_op = Operator('fill_zeros_like',
                                    lambda x: jnp.zeros_like(x),
                                    [oname], [zv.name], {},
                                    op_role=OpRole.Backward)
                    z_op.op_device = op.op_device
                    block.append_op(z_op)
                    cot_names.append(zv.name)

            out_gvars = [_new_grad_var(op.input_names[i],
                                       block.vars[op.input_names[i]],
                                       suffix='@TMP')
                         for i in grad_idx]
            g_op = Operator(
                op.type + '_grad',
                _make_grad_fn(op, len(op.input_names),
                              len(op.output_names), grad_idx),
                list(op.input_names) + cot_names,
                [gv.name for gv in out_gvars], dict(op.attrs),
                op_role=OpRole.Backward)
            g_op.multi_out = len(out_gvars) > 1
            g_op.op_device = op.op_device
            block.append_op(g_op)
            for i, gv in zip(grad_idx, out_gvars):
                _accumulate(op.input_names[i], gv.name, op.op_device)

    # -- bind params to canonical @GRAD names -------------------------------
    params_grads = []
    for p in params:
        gname = p.name + '@GRAD'
        have = grad_of.get(p.name)
        if have is None:
            # unreachable param: zero grad (reference errors at runtime
            # unless the optimizer tolerates empty grads; zeros keep the
            # optimize op well-formed)
            if gname not in block.vars:
                gv = Variable(block, gname, p.shape, p.dtype)
                gv.op_role = OpRole.Backward
                block.vars[gname] = gv
                z = Operator('fill_zeros_like', lambda x: jnp.zeros_like(x),
                             [p.name], [gname], {}, op_role=OpRole.Backward)
                block.append_op(z)
        elif have != gname:
            # alias the final accumulated grad to <param>@GRAD
            if gname not in block.vars:
                gv = Variable(block, gname, p.shape, p.dtype)
                gv.op_role = OpRole.Backward
                block.vars[gname] = gv
            a = Operator('share_data', lambda x: x, [have], [gname], {},
                         op_role=OpRole.Backward)
            prod_dev = ''
            for o in reversed(block.ops):
                if have in o.output_names:
                    prod_dev = o.op_device
                    break
            a.op_device = prod_dev
            block.append_op(a)
        prog._grad_map[p.name] = gname
        params_grads.append((p, block.vars[gname]))
    # full var→cotangent map (heter pass wires distributed_push off the
    # lookup outputs' cotangents — trainer_pass append_send_ops role)
    prog._var_grad_map = dict(grad_of)
    prog._has_backward_ops = True
    return params_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Parity: paddle.static.gradients."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    pgs = append_backward(targets[0], parameter_list=[
        i for i in inputs if isinstance(i, Parameter)])
    return [g for _, g in pgs]
