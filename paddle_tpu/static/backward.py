"""Static autodiff.

Reference parity: python/paddle/fluid/backward.py append_backward (2,017 LoC,
per-op GradOpMaker) — here gradients are derived by differentiating the whole
Program replay with jax.grad at Executor-compile time, which is both simpler
and XLA-optimal (one fused backward). append_backward's contract is kept:
grad Variables named `<param>@GRAD` appear in the block, op roles marked, and
(param, grad) pairs returned for optimizers and the distributed program
rewrites to key on.
"""
from .program import (Variable, Parameter, OpRole, default_main_program)


def append_backward(loss, parameter_list=None, no_grad_set=None,
                    callbacks=None, checkpoints=None):
    """Parity: fluid/backward.py append_backward."""
    prog = loss.block.program if hasattr(loss, 'block') \
        else default_main_program()
    prog._loss_var = loss
    block = prog.global_block()
    params = parameter_list
    if params is None:
        params = [p for p in prog.all_parameters() if p.trainable]
    else:
        params = [block.var(p) if isinstance(p, str) else p for p in params]
    params_grads = []
    for p in params:
        gname = p.name + '@GRAD'
        if gname not in block.vars:
            g = Variable(block, gname, p.shape, p.dtype)
            g.op_role = OpRole.Backward
            block.vars[gname] = g
        prog._grad_map[p.name] = gname
        params_grads.append((p, block.vars[gname]))
    return params_grads


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Parity: paddle.static.gradients."""
    if not isinstance(targets, (list, tuple)):
        targets = [targets]
    if not isinstance(inputs, (list, tuple)):
        inputs = [inputs]
    pgs = append_backward(targets[0], parameter_list=[
        i for i in inputs if isinstance(i, Parameter)])
    return [g for _, g in pgs]
