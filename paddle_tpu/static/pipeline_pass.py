"""Static pipeline program splitting.

Reference parity: fluid.optimizer.PipelineOptimizer's program surgery —
`_add_op_device_attr` (optimizer.py:4628, devices inferred for unmarked ops),
`_check_validation` (:4647, every op gets a role + device),
`_split_program` (:4374, one program per stage keyed on the op_device attr),
`_insert_sendrecv_ops_for_boundaries` (:4722, send_v2/recv_v2 pairs per
cross-stage edge, relay chains hop-by-hop for non-adjacent stages, one
dedicated ring per (prev, cur) pair keyed prev*1000+cur), and
`_accumulate_gradients` (:4974, grads merged across microbatches with the
optimizer run once) — executed by PipelineTrainer/SectionWorker
(section_worker.cc:104-185).

TPU-native split: the per-stage programs carry real Forward/Backward/Optimize
ops (append_backward records op-level grads), so ONE generic boundary rule
covers both directions — a forward activation crossing stages gets
send_v2/recv_v2, and so does its @GRAD flowing back, because the grad op is
just another op whose input is produced on a different stage. The
LocalPipelineRunner mirrors the single-node PipelineTrainer semantics for
tests; multi-chip pipelines execute through the SPMD engine
(meta_parallel/spmd_pipeline.py), which is the ICI-native fast path.
"""
import re

import numpy as np
import jax.numpy as jnp

from .program import (Program, Block, Operator, OpRole, _ConstVar,
                      run_op_in_env)


def _stage_of(device, num_stages):
    """'gpu:3' / 'tpu:3' / 'stage:3' -> 3; ''/'all'/'gpu:all' -> None."""
    if not device:
        return None
    m = re.match(r'^[a-z]*:?(\d+|all)$', device)
    if m is None:
        return None
    tok = m.group(1)
    if tok == 'all':
        return None
    s = int(tok)
    if s >= num_stages:
        raise ValueError(f"op_device {device!r} >= num_stages {num_stages}")
    return s


def _add_op_device_attr(block, num_stages):
    """Fill op_device for unmarked ops (parity: optimizer.py:4628-4645).

    Forward ops inherit the max stage among their inputs' producers
    (data-feed inputs pin to stage 0); backward/sum ops already carry the
    forward op's device from append_backward; optimize ops follow their
    parameter's consuming stage (:4587); global ops (clip) stay 'all'.
    """
    producer_stage = {}
    param_stage = {}
    for op in block.ops:
        # normalize explicit replicate-everywhere marks ('gpu:all',
        # 'tpu:all', 'all') so they survive inference untouched
        if op.op_device and op.op_device.split(':')[-1] == 'all':
            op.op_device = 'all'
    for op in block.ops:
        if op.op_device == 'all':
            continue
        if op.op_role & (OpRole.Backward | OpRole.Optimize):
            continue
        s = _stage_of(op.op_device, num_stages)
        if s is None:
            cands = [producer_stage[i] for i in op.input_names
                     if i in producer_stage]
            s = max(cands) if cands else 0
            op.op_device = f'stage:{s}'
        for i in op.input_names:
            v = block.vars.get(i)
            if v is not None and getattr(v, 'is_parameter', False) \
                    and i not in param_stage:
                param_stage[i] = s
        for o in op.output_names:
            producer_stage[o] = s

    for op in block.ops:
        if op.op_device == 'all':
            continue
        s = _stage_of(op.op_device, num_stages)
        if s is not None:
            for o in op.output_names:
                producer_stage.setdefault(o, s)
            continue
        if op.op_role & OpRole.Optimize:
            pname = op.attrs.get('param')
            s = param_stage.get(pname, 0)
        else:  # backward op whose forward op had no explicit device
            cands = [producer_stage[i] for i in op.input_names
                     if i in producer_stage]
            s = max(cands) if cands else 0
        op.op_device = f'stage:{s}'
        for o in op.output_names:
            producer_stage[o] = s
    return producer_stage


def _check_validation(block):
    """Parity: optimizer.py:4647 — every op must carry a role + device."""
    valid = (OpRole.Forward, OpRole.Backward, OpRole.Optimize,
             OpRole.LRSched, OpRole.Backward | OpRole.Loss,
             OpRole.Forward | OpRole.Loss)
    for op in block.ops:
        if op.op_role not in valid:
            raise ValueError(f"op {op.type} has invalid op_role "
                             f"{op.op_role}")
        if op.op_device is None or op.op_device == '':
            raise ValueError(f"op {op.type} has no op_device")


def split_program(program, num_stages):
    """Split one Program into per-stage Programs with send/recv boundary
    ops (parity: _split_program:4374 + _insert_sendrecv:4722).

    Returns (stage_programs, pair_rings): stage_programs[s].global_block()
    holds stage s's ops (device s or 'all') plus inserted send_v2/recv_v2;
    pair_rings maps (src, dst) -> ring_id (src*1000+dst, the reference's
    pair_key convention).
    """
    block = program.global_block()
    _add_op_device_attr(block, num_stages)
    _check_validation(block)

    stage_ops = [[] for _ in range(num_stages)]
    op_stage = {}
    for op in block.ops:
        s = _stage_of(op.op_device, num_stages)
        if s is None:   # 'all': replicate into every stage
            for lst in stage_ops:
                lst.append(op)
            op_stage[id(op)] = None
        else:
            stage_ops[s].append(op)
            op_stage[id(op)] = s

    producer = {}
    for op in block.ops:
        s = op_stage[id(op)]
        for o in op.output_names:
            if s is not None:
                producer[o] = s

    pair_rings = {}
    inserted = set()
    # per-stage op lists are rebuilt with sends after producers and recvs
    # before first consumer; relay hop-by-hop for non-adjacent stages
    out_lists = [[] for _ in range(num_stages)]

    def _ring(src, dst):
        key = (src, dst)
        if key not in pair_rings:
            pair_rings[key] = src * 1000 + dst   # reference pair_key
        return pair_rings[key]

    def _mk_send(var, src, dst, role):
        op = Operator('send_v2', lambda x: x, [var], [],
                      {'peer': dst, 'ring_id': _ring(src, dst),
                       'use_calc_stream': True}, op_role=role)
        op.op_device = f'stage:{src}'
        return op

    def _mk_recv(var, src, dst, role):
        v = block.vars[var]
        op = Operator('recv_v2', lambda: None, [], [var],
                      {'peer': src, 'ring_id': _ring(src, dst),
                       'out_shape': list(v.shape),
                       'dtype': str(v.dtype),
                       'use_calc_stream': True}, op_role=role)
        op.op_device = f'stage:{dst}'
        return op

    # which stages consume each var (cross-stage edges only); 'all'-ops'
    # inputs are excluded — globals (e.g. the clip op's grads) are the
    # dist-rewrites' job, as in the reference (gpu:all reduction ops)
    consumers = {}
    for op in block.ops:
        s = op_stage[id(op)]
        if s is None:
            continue
        for i in op.input_names:
            consumers.setdefault(i, set()).add(s)

    # walk ops in global order; sends follow their producer immediately, so
    # the matching recv lands in the consumer stage's list before any
    # consumer op (which comes later in global order)
    for op in block.ops:
        s = op_stage[id(op)]
        if s is None:
            for lst in out_lists:
                lst.append(op)
            continue
        out_lists[s].append(op)
        for o in op.output_names:
            for dst in sorted(consumers.get(o, ())):
                if dst == s:
                    continue
                cur, step = s, (1 if dst > s else -1)
                while cur != dst:   # relay chain (optimizer.py:4772-4790)
                    nxt = cur + step
                    if (o, cur, nxt) not in inserted:
                        inserted.add((o, cur, nxt))
                        out_lists[cur].append(
                            _mk_send(o, cur, nxt, op.op_role))
                        out_lists[nxt].append(
                            _mk_recv(o, cur, nxt, op.op_role))
                    cur = nxt

    progs = []
    for s in range(num_stages):
        p = Program.__new__(Program)
        p.__dict__.update(program.__dict__)
        b = Block(p, 0)
        b.vars = block.vars          # shared var table
        b.ops = out_lists[s]
        p.blocks = [b]
        p._stage_id = s
        progs.append(p)
    return progs, pair_rings


class LocalPipelineRunner:
    """Single-process multi-stage interpreter for split programs (parity:
    PipelineTrainer + SectionWorker on one device — the
    pipeline_mnist_one_device.py test pattern). send/recv resolve through
    an in-memory channel; per-microbatch Forward+Backward run per stage in
    order, param grads accumulate across microbatches (mean), then
    Optimize-role ops run once (parity: _accumulate_gradients:4974).

    This is the semantics-checking path; the performance path for real
    meshes is the SPMD pipeline engine.
    """

    def __init__(self, stage_programs, scope):
        self.progs = stage_programs
        self.scope = scope

    def run(self, feeds_per_microbatch, fetch_name=None):
        from .program import materialize_persistables
        scope = self.scope
        # startup: shared var table → params initialized once
        for prog in self.progs:
            materialize_persistables(prog.global_block().vars.values(),
                                     scope.find_var, scope.set)

        A = len(feeds_per_microbatch)
        merged = {}
        channel = {}
        fetch_vals = []
        opt = getattr(self.progs[0], '_optimizer', None)
        lr = jnp.asarray(opt.get_lr() if opt is not None else 0.0,
                         jnp.float32)

        def run_op(op, env, mb):
            if op.type == 'send_v2':
                channel[(op.input_names[0], op.attrs['ring_id'], mb)] = \
                    env[op.input_names[0]]
                return
            if op.type == 'recv_v2':
                env[op.output_names[0]] = \
                    channel[(op.output_names[0], op.attrs['ring_id'], mb)]
                return
            run_op_in_env(op, env)

        grad_names = set(self.progs[0]._grad_map.values())
        nstages = len(self.progs)
        for mb, feed in enumerate(feeds_per_microbatch):
            envs = []
            for s, prog in enumerate(self.progs):
                env = {'@LR': lr}
                for k, v in feed.items():
                    env[k] = jnp.asarray(np.asarray(v))
                for v in prog.global_block().vars.values():
                    if isinstance(v, _ConstVar):
                        env[v.name] = v.value
                    elif getattr(v, 'persistable', False) \
                            and scope.find_var(v.name) is not None:
                        env[v.name] = scope.find_var(v.name)
                envs.append(env)
            # forward sweep stage 0→N-1, backward sweep N-1→0 (SectionWorker
            # RunForward/RunBackward filtering by op_role)
            for s in range(nstages):
                for op in self.progs[s].global_block().ops:
                    if not (op.op_role & (OpRole.Backward
                                          | OpRole.Optimize)):
                        run_op(op, envs[s], mb)
            for s in reversed(range(nstages)):
                for op in self.progs[s].global_block().ops:
                    if op.op_role & OpRole.Backward:
                        run_op(op, envs[s], mb)
            seen_mb = set()
            fetched = False
            for s, env in enumerate(envs):
                for gname in grad_names:
                    if gname in env and gname not in seen_mb:
                        seen_mb.add(gname)
                        merged[gname] = merged.get(gname, 0) + env[gname]
                if fetch_name and fetch_name in env and not fetched:
                    fetched = True
                    fetch_vals.append(env[fetch_name])

        # optimize once over mean grads (loss is per-microbatch mean)
        for s, prog in enumerate(self.progs):
            env = {'@LR': lr}
            for v in prog.global_block().vars.values():
                if isinstance(v, _ConstVar):
                    env[v.name] = v.value
                elif getattr(v, 'persistable', False) \
                        and scope.find_var(v.name) is not None:
                    env[v.name] = scope.find_var(v.name)
            for g, val in merged.items():
                env[g] = val / A
            ran = False
            for op in prog.global_block().ops:
                if not (op.op_role & OpRole.Optimize):
                    continue
                if not all(n in env for n in op.input_names):
                    continue
                run_op(op, env, -1)
                ran = True
            if ran:
                for v in prog.global_block().vars.values():
                    if getattr(v, 'persistable', False) and v.name in env \
                            and v.name != '@LR':
                        scope.set(v.name, env[v.name])
        if fetch_vals:
            return float(jnp.mean(jnp.stack(
                [jnp.asarray(v) for v in fetch_vals])))
        return None
