"""Static activation-recompute program rewrite.

Reference parity: fluid RecomputeOptimizer
(/root/reference/python/paddle/fluid/optimizer.py:5402) →
backward._append_backward_ops_with_checkpoints_: the forward is segmented
at user-named checkpoint variables; each segment's intermediate
activations are NOT kept for backward — the segment's forward ops are
duplicated into the backward region (reading only the stored checkpoint
inputs) and the grad ops are rewired to the recomputed copies.

TPU-native note: the whole Program replays inside one jax.jit trace, so a
naive duplicate would be CSE'd away by XLA (it dedupes identical
subgraphs, reconstructing exactly the memory we tried to free). Each
recompute segment therefore reads its external inputs through a
`recompute_barrier` op (`lax.optimization_barrier`) — an opaque boundary
XLA will not merge across — making the recomputation real: the original
segment intermediates die at the end of the forward, and live again only
from the barrier to their grad ops. This is the recorded-program analogue
of `jax.checkpoint` (which only acts under jax-level AD, not op replay).
"""
import jax
import jax.numpy as jnp

from .program import Variable, Operator, OpRole, _ConstVar


def rewrite_recompute(program, checkpoints):
    """Rewrite `program` in place for activation recompute.

    `checkpoints`: variable names that delimit segments; they (plus
    params/feeds) are the only forward values kept live into backward.
    Every other forward intermediate consumed by a Backward-role op is
    recomputed from the nearest upstream checkpoint right before its
    first backward consumer. Raises on unknown checkpoint names — a
    misspelled knob must not silently no-op.

    Returns the number of recompute segments inserted.
    """
    block = program.global_block()
    unknown = [c for c in checkpoints if c not in block.vars]
    if unknown:
        raise ValueError(
            f"recompute checkpoints not found in program: {unknown}; "
            f"known vars include {sorted(block.vars)[:10]}...")

    ops = list(block.ops)
    first_bwd = len(ops)
    for i, op in enumerate(ops):
        if op.op_role & OpRole.Backward:
            first_bwd = i
            break
    fwd_ops, tail_ops = ops[:first_bwd], ops[first_bwd:]

    produced_at = {}
    for i, op in enumerate(fwd_ops):
        for o in op.output_names:
            produced_at[o] = i
    cp_positions = sorted({produced_at[c] for c in checkpoints
                           if c in produced_at})
    if not cp_positions:
        return 0
    stored = set(checkpoints)

    # segments: [0..cp0], (cp0..cp1], ... — the tail after the last
    # checkpoint is not recomputed (its intermediates die quickly: their
    # grad ops run first in the reverse sweep)
    bounds = [-1] + cp_positions
    segments = [(bounds[j] + 1, bounds[j + 1])
                for j in range(len(bounds) - 1)]

    n_inserted = 0
    inserts = {}            # tail position -> [ops to insert before it]
    for seg_id, (lo, hi) in enumerate(segments):
        seg_ops = fwd_ops[lo:hi + 1]
        seg_produced = {o for op in seg_ops for o in op.output_names}
        # intermediates: produced in-segment, not stored checkpoints
        inter = seg_produced - stored
        if not inter:
            continue
        # where the recompute must land: before the first backward
        # consumer of any segment intermediate
        consumer_pos = None
        for i, op in enumerate(tail_ops):
            if (op.op_role & OpRole.Backward) \
                    and set(op.input_names) & inter:
                consumer_pos = i
                break
        if consumer_pos is None:
            continue

        # external inputs of the segment (checkpoints/params/feeds/consts)
        ext = []
        for op in seg_ops:
            for n in op.input_names:
                if n not in seg_produced and n not in ext:
                    ext.append(n)
        sfx = f"@RECOMPUTE@{seg_id}"

        def _mapped(n):
            return n + sfx if n in seg_produced else n

        rc_ops = []
        # barrier the external inputs feeding the duplicated ops so XLA
        # cannot CSE the recomputation with the original forward
        barrier_ext = [n for n in ext
                       if not isinstance(block.vars.get(n), _ConstVar)]
        if barrier_ext:
            b_outs = []
            for n in barrier_ext:
                bn = n + sfx + '@B'
                v = block.vars[n]
                bv = Variable(block, bn, list(v.shape or []), v.dtype)
                bv.op_role = OpRole.Backward
                block.vars[bn] = bv
                b_outs.append(bn)
            bop = Operator(
                'recompute_barrier',
                lambda *xs: jax.lax.optimization_barrier(tuple(xs)),
                list(barrier_ext), b_outs, {'segment': seg_id},
                op_role=OpRole.Backward)
            bop.multi_out = True
            rc_ops.append(bop)
            barrier_of = dict(zip(barrier_ext, b_outs))
        else:
            barrier_of = {}

        def _in_name(n):
            if n in seg_produced:
                return n + sfx
            return barrier_of.get(n, n)

        for op in seg_ops:
            if all(o in stored for o in op.output_names):
                continue            # its outputs are kept anyway
            new_outs = []
            for o in op.output_names:
                on = _mapped(o)
                if on not in block.vars:
                    v = block.vars[o]
                    nv = Variable(block, on, list(v.shape or []), v.dtype)
                    nv.op_role = OpRole.Backward
                    block.vars[on] = nv
                new_outs.append(on)
            dup = Operator(op.type + '_recompute', op.fn,
                           [_in_name(n) for n in op.input_names],
                           new_outs, dict(op.attrs),
                           op_role=OpRole.Backward)
            dup.multi_out = getattr(op, 'multi_out', False)
            dup.op_device = op.op_device
            rc_ops.append(dup)

        # rewire every backward consumer of a segment intermediate
        for op in tail_ops:
            if not (op.op_role & OpRole.Backward):
                continue
            if set(op.input_names) & inter:
                op.input_names = [n + sfx if n in inter else n
                                  for n in op.input_names]
        inserts.setdefault(consumer_pos, []).extend(rc_ops)
        n_inserted += 1

    new_tail = []
    for i, op in enumerate(tail_ops):
        new_tail.extend(inserts.get(i, []))
        new_tail.append(op)
    block.ops = fwd_ops + new_tail
    program._recompute_checkpoints = list(checkpoints)
    return n_inserted
