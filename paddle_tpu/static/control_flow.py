"""Control-flow ops.

Reference parity: operators/controlflow (while, conditional_block, select —
N28) and the fluid.layers control_flow user API (While/cond/case/
switch_case). TPU-native: these ARE lax.while_loop/cond/switch — compiled
structured control flow instead of the reference's op-microkernel
interpreters; they run eagerly too (lax executes op-by-op outside jit).
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor


def _unbox(x):
    return x.data if isinstance(x, Tensor) else x


def _box(x):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a) if not isinstance(a, Tensor) else a, x,
        is_leaf=lambda a: not isinstance(a, (list, tuple, dict)))


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Parity: paddle.static.nn.while_loop."""
    def c(vs):
        out = cond(*_rebox_args(vs))
        return _unbox(out).reshape(())

    def b(vs):
        out = body(*_rebox_args(vs))
        out = out if isinstance(out, (list, tuple)) else [out]
        return [_unbox(o) for o in out]

    def _rebox_args(vs):
        return [Tensor(v) for v in vs]

    res = lax.while_loop(c, b, [_unbox(v) for v in loop_vars])
    return [Tensor(r) for r in res]


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Parity: paddle.static.nn.cond (an omitted branch is a no-op
    returning a zero scalar so both branches match structurally)."""
    p = _unbox(pred)
    true_fn = true_fn or (lambda: Tensor(jnp.asarray(0)))
    false_fn = false_fn or (lambda: Tensor(jnp.asarray(0)))

    def t(_):
        out = true_fn()
        return jax.tree_util.tree_map(
            _unbox, out, is_leaf=lambda a: isinstance(a, Tensor))

    def f(_):
        out = false_fn()
        return jax.tree_util.tree_map(
            _unbox, out, is_leaf=lambda a: isinstance(a, Tensor))

    res = lax.cond(p.reshape(()), t, f, 0)
    return jax.tree_util.tree_map(
        lambda a: Tensor(a), res,
        is_leaf=lambda a: not isinstance(a, (list, tuple, dict)))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Parity: paddle.static.nn.switch_case — branch keys are the DECLARED
    indices (dict keys or (index, fn) pairs); unmatched keys route to
    `default` (or the last branch when default is None, as in paddle)."""
    idx = _unbox(branch_index).reshape(()).astype(jnp.int32)
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        pairs = [(int(k), f) for k, f in branch_fns]
    else:
        pairs = list(enumerate(branch_fns))
    keys = jnp.asarray([k for k, _ in pairs], jnp.int32)
    fns = [f for _, f in pairs]
    if default is None:
        default = fns[-1]
    fns = fns + [default]
    default_pos = len(fns) - 1
    # exact-match key → position; miss → default
    matches = (keys == idx)
    pos = jnp.where(jnp.any(matches),
                    jnp.argmax(matches).astype(jnp.int32),
                    jnp.asarray(default_pos, jnp.int32))

    def wrap(f):
        return lambda _: jax.tree_util.tree_map(
            _unbox, f(), is_leaf=lambda a: isinstance(a, Tensor))

    res = lax.switch(pos, [wrap(f) for f in fns], 0)
    return jax.tree_util.tree_map(
        lambda a: Tensor(a), res,
        is_leaf=lambda a: not isinstance(a, (list, tuple, dict)))


def case(pred_fn_pairs, default=None, name=None):
    """Parity: paddle.static.nn.case — first true predicate wins; with no
    default the LAST fn is the fallback (paddle semantics; lax.cond traces
    both branches so the fallback must be a callable, never a raise)."""
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]

    def build(i):
        if i >= len(pairs):
            return default()
        pred, fn = pairs[i]
        return cond(pred, fn, lambda: build(i + 1))
    return build(0)
