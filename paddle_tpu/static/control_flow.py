"""Control-flow ops.

Reference parity: operators/controlflow (while, conditional_block, select —
N28) and the fluid.layers control_flow user API (While/cond/case/
switch_case). TPU-native: these ARE lax.while_loop/cond/switch — compiled
structured control flow instead of the reference's op-microkernel
interpreters; they run eagerly too (lax executes op-by-op outside jit).

Under static recording (enable_static + program_guard) cond/while_loop
instead record `conditional_block` / `while` OPS whose branches/bodies are
nested sub-Blocks (parity: framework.proto BlockDesc:178 nesting +
conditional_block_op.cc / while_op.cc) — so a recorded Program carries
data-dependent control flow, serializes with it, and the Executor replays
it through lax.cond / lax.while_loop.
"""
import jax
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor


def _unbox(x):
    return x.data if isinstance(x, Tensor) else x


def _box(x):
    return jax.tree_util.tree_map(
        lambda a: Tensor(a) if not isinstance(a, Tensor) else a, x,
        is_leaf=lambda a: not isinstance(a, (list, tuple, dict)))


def _as_var_list(out):
    if out is None:
        return []
    return list(out) if isinstance(out, (list, tuple)) else [out]


def _ensure_var(prog, block, v):
    """Materialize a concrete Tensor as a captured const Variable of
    `block` (untouched state leaves / loop initials)."""
    from .program import Variable, _ConstVar
    if isinstance(v, Variable):
        return v
    cname = prog._unique_name('const')
    cv = _ConstVar(block, cname, v)
    block.vars[cname] = cv
    return cv


def _external_inputs(prog, blocks):
    """Names sub-block ops consume that are not defined inside them —
    listed as the control-flow op's inputs so program pruning
    (save_inference_model) keeps their producers."""
    used, defined = [], set()

    def walk(b):
        defined.update(b.vars)
        for op in b.ops:
            for n in op.input_names:
                if n not in defined:
                    used.append(n)
            defined.update(op.output_names)
            for key in ('sub_block_true', 'sub_block_false',
                        'cond_block', 'body_block'):
                if key in op.attrs:
                    walk(prog.blocks[op.attrs[key]])
    for b in blocks:
        walk(b)
    seen, out = set(), []
    for n in used:
        if n not in seen:
            seen.add(n)
            out.append(n)
    return out


def _record_cond(pred, true_fn, false_fn):
    """Record a conditional_block op with two sub-blocks (parity:
    conditional_block_op.cc; layers/control_flow.py cond)."""
    from .program import default_main_program, Variable, Operator
    prog = default_main_program()
    outer = prog.current_block()
    true_fn = true_fn or (lambda: None)
    false_fn = false_fn or (lambda: None)

    tb = prog._create_block()
    t_list = [_ensure_var(prog, tb, v) for v in _as_var_list(true_fn())]
    prog._rollback()
    fb = prog._create_block()
    f_list = [_ensure_var(prog, fb, v) for v in _as_var_list(false_fn())]
    prog._rollback()
    if len(t_list) != len(f_list):
        raise ValueError(
            f"cond branches return {len(t_list)} vs {len(f_list)} outputs "
            "— both branches must produce the same structure")
    outs = []
    for tv, fv in zip(t_list, f_list):
        if list(tv.shape) != list(fv.shape) or tv.dtype != fv.dtype:
            raise ValueError(
                f"cond branch outputs mismatch: {tv.shape}/{tv.dtype} vs "
                f"{fv.shape}/{fv.dtype}")
        name = prog._unique_name('cond')
        ov = Variable(outer, name, tv.shape, tv.dtype,
                      stop_gradient=tv.stop_gradient and fv.stop_gradient)
        outer.vars[name] = ov
        outs.append(ov)
    ext = _external_inputs(prog, [tb, fb])
    op = Operator('conditional_block', None, [pred.name] + ext,
                  [o.name for o in outs],
                  {'sub_block_true': tb.idx, 'sub_block_false': fb.idx,
                   'true_outs': [v.name for v in t_list],
                   'false_outs': [v.name for v in f_list]})
    outer.append_op(op)
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else tuple(outs)


def _record_while(cond_fn, body_fn, loop_vars):
    """Record a while op whose cond/body are sub-blocks over named carry
    vars (parity: while_op.cc; layers/control_flow.py While)."""
    from .program import (default_main_program, Variable, Operator,
                          _ConstVar)
    prog = default_main_program()
    outer = prog.current_block()
    # concrete Tensors among loop vars (e.g. paddle.zeros initials)
    # become captured consts
    resolved = []
    for v in loop_vars:
        if isinstance(v, Variable):
            resolved.append(v)
        else:
            cname = prog._unique_name('const')
            cv = _ConstVar(outer, cname, v)
            outer.vars[cname] = cv
            resolved.append(cv)
    loop_vars = resolved
    infos = [(prog._unique_name('while_carry'), v.shape, v.dtype,
              v.stop_gradient) for v in loop_vars]

    cb = prog._create_block()
    c_shadows = []
    for nm, shp, dt, sg in infos:
        sv = Variable(cb, nm, shp, dt, stop_gradient=sg)
        cb.vars[nm] = sv
        c_shadows.append(sv)
    c_out = cond_fn(*c_shadows)
    prog._rollback()

    bb = prog._create_block()
    b_shadows = []
    for nm, shp, dt, sg in infos:
        sv = Variable(bb, nm, shp, dt, stop_gradient=sg)
        bb.vars[nm] = sv
        b_shadows.append(sv)
    b_list = [_ensure_var(prog, bb, v)
              for v in _as_var_list(body_fn(*b_shadows))]
    prog._rollback()
    if len(b_list) != len(loop_vars):
        raise ValueError(
            f"while body returns {len(b_list)} vars for {len(loop_vars)} "
            "loop vars")

    outs = []
    for v in loop_vars:
        name = prog._unique_name('while')
        ov = Variable(outer, name, v.shape, v.dtype,
                      stop_gradient=v.stop_gradient)
        outer.vars[name] = ov
        outs.append(ov)
    ext = _external_inputs(prog, [cb, bb])
    op = Operator('while', None, [v.name for v in loop_vars] + ext,
                  [o.name for o in outs],
                  {'cond_block': cb.idx, 'body_block': bb.idx,
                   'carry_names': [i[0] for i in infos],
                   'cond_out': c_out.name,
                   'body_outs': [o.name for o in b_list]})
    outer.append_op(op)
    return outs


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """Parity: paddle.static.nn.while_loop."""
    from .program import Variable as _V
    if any(isinstance(v, _V) for v in loop_vars):
        return _record_while(cond, body, loop_vars)
    def c(vs):
        out = cond(*_rebox_args(vs))
        return _unbox(out).reshape(())

    def b(vs):
        out = body(*_rebox_args(vs))
        out = out if isinstance(out, (list, tuple)) else [out]
        return [_unbox(o) for o in out]

    def _rebox_args(vs):
        return [Tensor(v) for v in vs]

    res = lax.while_loop(c, b, [_unbox(v) for v in loop_vars])
    return [Tensor(r) for r in res]


def cond(pred, true_fn=None, false_fn=None, name=None):
    """Parity: paddle.static.nn.cond (an omitted branch is a no-op
    returning a zero scalar so both branches match structurally)."""
    from .program import Variable as _V
    if isinstance(pred, _V):
        return _record_cond(pred, true_fn, false_fn)
    p = _unbox(pred)
    true_fn = true_fn or (lambda: Tensor(jnp.asarray(0)))
    false_fn = false_fn or (lambda: Tensor(jnp.asarray(0)))

    def t(_):
        out = true_fn()
        return jax.tree_util.tree_map(
            _unbox, out, is_leaf=lambda a: isinstance(a, Tensor))

    def f(_):
        out = false_fn()
        return jax.tree_util.tree_map(
            _unbox, out, is_leaf=lambda a: isinstance(a, Tensor))

    res = lax.cond(p.reshape(()), t, f, 0)
    return jax.tree_util.tree_map(
        lambda a: Tensor(a), res,
        is_leaf=lambda a: not isinstance(a, (list, tuple, dict)))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """Parity: paddle.static.nn.switch_case — branch keys are the DECLARED
    indices (dict keys or (index, fn) pairs); unmatched keys route to
    `default` (or the last branch when default is None, as in paddle)."""
    idx = _unbox(branch_index).reshape(()).astype(jnp.int32)
    if isinstance(branch_fns, dict):
        pairs = sorted(branch_fns.items())
    elif branch_fns and isinstance(branch_fns[0], (tuple, list)):
        pairs = [(int(k), f) for k, f in branch_fns]
    else:
        pairs = list(enumerate(branch_fns))
    keys = jnp.asarray([k for k, _ in pairs], jnp.int32)
    fns = [f for _, f in pairs]
    if default is None:
        default = fns[-1]
    fns = fns + [default]
    default_pos = len(fns) - 1
    # exact-match key → position; miss → default
    matches = (keys == idx)
    pos = jnp.where(jnp.any(matches),
                    jnp.argmax(matches).astype(jnp.int32),
                    jnp.asarray(default_pos, jnp.int32))

    def wrap(f):
        return lambda _: jax.tree_util.tree_map(
            _unbox, f(), is_leaf=lambda a: isinstance(a, Tensor))

    res = lax.switch(pos, [wrap(f) for f in fns], 0)
    return jax.tree_util.tree_map(
        lambda a: Tensor(a), res,
        is_leaf=lambda a: not isinstance(a, (list, tuple, dict)))


def case(pred_fn_pairs, default=None, name=None):
    """Parity: paddle.static.nn.case — first true predicate wins; with no
    default the LAST fn is the fallback (paddle semantics; lax.cond traces
    both branches so the fallback must be a callable, never a raise)."""
    pairs = list(pred_fn_pairs)
    if default is None:
        default = pairs[-1][1]
        pairs = pairs[:-1]

    def build(i):
        if i >= len(pairs):
            return default()
        pred, fn = pairs[i]
        return cond(pred, fn, lambda: build(i + 1))
    return build(0)
