"""Heterogeneous program split: host(PS) sparse segments vs TPU dense
segments.

Reference parity: incubate/fleet/parameter_server/ir/trainer_pass.py —
find_heter_ops:441 (segment the program into device-contiguous op blocks
by op_device) and create_heter_program:558 (carve the host segments out to
run against the parameter-server tier), plus the HeterClient/HeterServer
execution split (distributed/service/heter_server.h). heterPS pairs a CPU
host (huge sparse tables) with an accelerator (dense towers); on TPU the
same disaggregation pairs the host-resident `csrc/sparse_table.cc` tier
with the jitted dense program.

TPU-native design: ops recorded under `device_guard('cpu')` (and the
distributed_lookup/distributed_push PS ops, which are born host-side)
carry op_device='cpu'. `find_heter_ops` segments the op list;
`HeterProgramRunner` replays device segments as cached jax.jit programs
and host segments eagerly — distributed_lookup/push route to the PS
worker (PsClient or an in-process table). `wire_sparse_grads` appends the
push ops that carry each lookup output's cotangent back to the server
(the reference's backward send — trainer_pass append_send_ops role).
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtypes
from .program import (Variable, Operator, OpRole, _ConstVar,
                      default_main_program, run_op_in_env)

DEVICE_LIST = ('tpu', 'cpu', 'gpu')
_PS_OPS = ('distributed_lookup', 'distributed_push')


# ---------------------------------------------------------------------------
# recordable PS ops (host-side by construction)
# ---------------------------------------------------------------------------

def distributed_lookup(ids, table_id, dim, name=None):
    """Record a host-side PS embedding lookup: ids [...] int → rows
    [..., dim] (parity: distributed_lookup_table / pscore
    distributed_lookup_table_op; execution happens in the runner via the
    PS worker, never inside the jitted device program)."""
    prog = default_main_program()
    block = prog.current_block()
    out_name = prog._unique_name('dist_lookup')
    out = Variable(block, out_name, list(ids.shape) + [dim], 'float32',
                   stop_gradient=False)
    block.vars[out_name] = out
    op = Operator('distributed_lookup', None, [ids.name], [out_name],
                  {'table_id': int(table_id), 'dim': int(dim)})
    op.op_device = 'cpu'
    block.append_op(op)
    return out


def wire_sparse_grads(program, lr_name='@LR'):
    """Post-backward pass: for every distributed_lookup whose output has a
    gradient var, append a distributed_push op (op_device cpu, Backward
    role) carrying that cotangent to the server — the reference's
    append_send_ops half of the split. Returns the number of push ops."""
    block = program.global_block()
    grad_of = dict(getattr(program, '_var_grad_map', {}))
    grad_of.update(getattr(program, '_grad_map', {}))
    n = 0
    pushes = []
    for op in block.ops:
        if op.type != 'distributed_lookup':
            continue
        gname = grad_of.get(op.output_names[0])
        if gname is None or gname not in block.vars:
            continue
        push = Operator('distributed_push', None,
                        [op.input_names[0], gname], [],
                        {'table_id': op.attrs['table_id'],
                         'dim': op.attrs['dim']},
                        op_role=OpRole.Backward)
        push.op_device = 'cpu'
        pushes.append(push)
        n += 1
    block.ops.extend(pushes)
    return n


# ---------------------------------------------------------------------------
# segmentation (find_heter_ops parity)
# ---------------------------------------------------------------------------

def find_heter_ops(program, default_device='tpu'):
    """Segment the global block into device-contiguous op runs.

    Returns (segments, heter_ops, default_ops) where segments is an
    ordered [(device, [ops])] list and heter_ops/default_ops mirror the
    reference's {device: {segment_index: [ops]}} summaries
    (trainer_pass.py:441)."""
    if default_device not in DEVICE_LIST:
        raise ValueError(f"device {default_device} not in {DEVICE_LIST}")
    segments = []
    cur_dev, cur_ops = None, []
    for op in program.global_block().ops:
        dev = op.op_device or default_device
        if op.type in _PS_OPS:
            dev = 'cpu'
        if dev != cur_dev and cur_ops:
            segments.append((cur_dev, cur_ops))
            cur_ops = []
        cur_dev = dev
        cur_ops.append(op)
    if cur_ops:
        segments.append((cur_dev, cur_ops))
    heter_ops, default_ops = {}, {default_device: {}}
    for i, (dev, ops) in enumerate(segments):
        if dev == default_device:
            default_ops[default_device][i] = ops
        else:
            heter_ops.setdefault(dev, {})[i] = ops
    return segments, heter_ops, default_ops


# ---------------------------------------------------------------------------
# split execution
# ---------------------------------------------------------------------------

class HeterProgramRunner:
    """Execute a heter-split program: host segments eagerly (PS ops via
    the worker), device segments as cached jitted replays (parity: the
    trainer side of HeterClient/HeterServer — heter_server.h — collapsed
    into one process boundary: host python vs XLA program)."""

    def __init__(self, program, ps, default_device='tpu'):
        """ps: object with pull(table_id, ids, dim) -> np [n, dim] and
        push(table_id, ids, grads, lr) (PsClient or an in-process
        adapter)."""
        self.program = program
        self.ps = ps
        self.segments, self.heter_ops, _ = find_heter_ops(
            program, default_device)
        self._jitted = {}
        self.lr = 0.01

    # -- host segment -------------------------------------------------------
    def _run_host_op(self, op, env):
        if op.type == 'distributed_lookup':
            ids = np.asarray(env[op.input_names[0]])
            rows = self.ps.pull(op.attrs['table_id'], ids.reshape(-1),
                                op.attrs['dim'])
            env[op.output_names[0]] = jnp.asarray(
                rows.reshape(ids.shape + (op.attrs['dim'],)))
        elif op.type == 'distributed_push':
            ids = np.asarray(env[op.input_names[0]])
            g = np.asarray(env[op.input_names[1]], np.float32)
            self.ps.push(op.attrs['table_id'], ids.reshape(-1),
                         g.reshape(-1, op.attrs['dim']), self.lr)
        else:
            run_op_in_env(op, env, self.program)

    # -- device segment -----------------------------------------------------
    def _segment_io(self, idx, ops):
        """Input names the segment reads from outside itself; output names
        it defines that later segments (or fetches) read."""
        defined = set()
        reads = []
        for op in ops:
            for nm in op.input_names:
                if nm not in defined:
                    reads.append(nm)
            defined.update(op.output_names)
        later_reads = set()
        for _, later in self.segments[idx + 1:]:
            for op in later:
                later_reads.update(op.input_names)
        persist = {v.name for v in self.program.list_vars()
                   if getattr(v, 'persistable', False)}
        outs = [nm for op in ops for nm in op.output_names
                if nm in later_reads or nm in self._fetch_names
                or nm in persist]
        seen = set()
        reads = [r for r in reads if not (r in seen or seen.add(r))]
        seen = set()
        outs = [o for o in outs if not (o in seen or seen.add(o))]
        return reads, outs

    def _run_device_segment(self, idx, ops, env):
        key = idx
        if key not in self._jitted:
            reads, outs = self._segment_io(idx, ops)

            def replay(in_arrays, _reads=tuple(reads), _outs=tuple(outs),
                       _ops=tuple(ops)):
                local = dict(zip(_reads, in_arrays))
                for v in self.program.global_block().vars.values():
                    if isinstance(v, _ConstVar):
                        local[v.name] = v.value
                for op in _ops:
                    run_op_in_env(op, local, self.program)
                return tuple(local[o] for o in _outs)
            self._jitted[key] = (jax.jit(replay), reads, outs)
        fn, reads, outs = self._jitted[key]
        results = fn(tuple(jnp.asarray(env[r]) for r in reads))
        env.update(zip(outs, results))

    # -- public -------------------------------------------------------------
    def run(self, feed, fetch_list, lr=None):
        if lr is not None:
            self.lr = lr
        self._fetch_names = [f.name if isinstance(f, Variable) else str(f)
                             for f in fetch_list]
        env = {'@LR': jnp.asarray(self.lr, jnp.float32)}
        for k, v in feed.items():
            env[k] = jnp.asarray(v)
        for v in self.program.global_block().vars.values():
            if isinstance(v, _ConstVar):
                env[v.name] = v.value
        from .program import materialize_persistables
        from .executor import global_scope
        scope = global_scope()
        materialize_persistables(self.program.list_vars(),
                                 scope.find_var, scope.set)
        for v in self.program.list_vars():
            if getattr(v, 'persistable', False) \
                    and not isinstance(v, _ConstVar):
                arr = scope.find_var(v.name)
                if arr is not None and v.name not in env:
                    env[v.name] = arr

        for idx, (dev, ops) in enumerate(self.segments):
            if dev == 'cpu':
                for op in ops:
                    self._run_host_op(op, env)
            else:
                self._run_device_segment(idx, ops, env)

        # persist updated persistables (optimizer state etc.)
        for v in self.program.list_vars():
            if getattr(v, 'persistable', False) \
                    and not isinstance(v, _ConstVar) and v.name in env:
                scope.set(v.name, env[v.name])
        return [np.asarray(env[n]) for n in self._fetch_names]


class InProcessPsAdapter:
    """The runner's `ps` interface over an in-process NativeSparseTable —
    the single-node heterPS shape (host tables + device towers in one
    process), also the loss-parity oracle's table."""

    def __init__(self, tables):
        self.tables = dict(tables)

    def pull(self, table_id, ids, dim):
        return self.tables[table_id].pull(np.asarray(ids, np.int64))

    def push(self, table_id, ids, grads, lr):
        self.tables[table_id].push(np.asarray(ids, np.int64),
                                   np.asarray(grads, np.float32), lr=lr)
