"""Static ZeRO sharding program rewrite.

Reference parity: fleet/meta_optimizers/sharding_optimizer.py —
`_build_shard`/`sharding/shard.py Shard` (param→rank assignment; greedy size
balancing as in dygraph_sharding_optimizer._partition_parameters:90),
`_prune_main_program:636` (delete optimizer ops/state for params the rank
does not own), `_add_broadcast_allreduce:746` (c_broadcast of updated params
from their owners; grad reduction per segment), plus the stage-2 grad
sharding of ZeRO-2 (reduce-to-owner instead of allreduce).

The rewrite operates on the real Backward/Optimize ops recorded by
append_backward/_append_optimize_ops, so the golden tests can assert on the
rewritten op list exactly like the reference's compile-only meta-optimizer
tests (SURVEY §4.3). Multi-rank execution semantics are checked with
MultiRankShardingSimulator (the in-process stand-in for the reference's
2-process test_dist_base pattern); on a real mesh the same semantics run
through the hybrid SPMD engine.
"""
import numpy as np
import jax.numpy as jnp

from .program import (Program, Block, Operator, OpRole, _ConstVar,
                      run_op_in_env)

SHARDING_RING = 1          # reference ring convention (A.1): sharding ring 1


def partition_parameters(params, degree):
    """Greedy size-balanced param→rank map (parity:
    dygraph_sharding_optimizer._partition_parameters:90)."""
    sizes = [0] * degree
    mapping = {}
    for p in sorted(params, key=lambda p: -int(np.prod(p.shape or [1]))):
        r = int(np.argmin(sizes))
        mapping[p.name] = r
        sizes[r] += int(np.prod(p.shape or [1]))
    return mapping


def shard_program(program, rank, degree, stage=2):
    """Rewrite `program` in place for sharding rank `rank` of `degree`.

    - inserts one grad-sync collective per parameter gradient before the
      optimize ops: `c_allreduce_sum` + `scale` (1/degree) for ZeRO-1, or
      `c_reduce_sum` to the owner (+ scale on the owner) for ZeRO-2;
    - prunes optimize ops and optimizer-state vars of parameters this rank
      does not own (the ZeRO state-memory saving);
    - appends `c_broadcast` of every updated parameter from its owner.

    Returns {param_name: owner_rank}.
    """
    block = program.global_block()
    params = [p for p in program.all_parameters()
              if p.name in program._grad_map]
    param2rank = partition_parameters(params, degree)

    ops = list(block.ops)
    first_opt = len(ops)
    for i, op in enumerate(ops):
        if op.op_role & OpRole.Optimize:
            first_opt = i
            break

    sync_ops = []
    for p in params:
        gname = program._grad_map[p.name]
        owner = param2rank[p.name]
        if stage >= 2:
            op = Operator('c_reduce_sum', lambda x: x, [gname], [gname],
                          {'ring_id': SHARDING_RING, 'root_id': owner,
                           'use_calc_stream': True},
                          op_role=OpRole.Backward)
        else:
            op = Operator('c_allreduce_sum', lambda x: x, [gname], [gname],
                          {'ring_id': SHARDING_RING,
                           'use_calc_stream': True},
                          op_role=OpRole.Backward)
        sync_ops.append(op)
        sc = Operator('scale', lambda x, _d=degree: x / _d, [gname],
                      [gname], {'scale': 1.0 / degree},
                      op_role=OpRole.Backward)
        sync_ops.append(sc)

    # ZeRO-2 global-norm clip: each rank only holds valid (reduced) grads
    # for the params it owns, so the single fused clip op would compute a
    # wrong, per-rank-divergent norm. Rewrite it: local sum-of-squares over
    # OWNED grads -> c_allreduce_sum of the scalar -> scale owned grads
    # (parity: sharding/gradient_clip_helper.py syncing the global norm
    # across shards). ZeRO-1 grads are allreduced everywhere, so the
    # original clip op stays correct there.
    clip_ops = []
    clip_op = next((op for op in ops[first_opt:]
                    if op.type == 'clip_by_global_norm'), None)
    if clip_op is not None and stage >= 2:
        owned_g = [program._grad_map[p.name] for p in params
                   if param2rank[p.name] == rank]
        cn = float(clip_op.attrs['clip_norm'])
        sq_name = '@sharding_local_sq'
        if sq_name not in block.vars:
            from .program import Variable
            block.vars[sq_name] = Variable(block, sq_name, [], 'float32')

        def local_sq_fn(*gs):
            if not gs:
                return jnp.asarray(0.0, jnp.float32)
            return sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in gs)
        clip_ops.append(Operator('squared_l2_norm', local_sq_fn,
                                 list(owned_g), [sq_name], {},
                                 op_role=OpRole.Optimize))
        clip_ops.append(Operator('c_allreduce_sum', lambda x: x,
                                 [sq_name], [sq_name],
                                 {'ring_id': SHARDING_RING,
                                  'use_calc_stream': True},
                                 op_role=OpRole.Optimize))

        def clip_scale_fn(sq, *gs, _cn=cn):
            factor = _cn / jnp.maximum(jnp.sqrt(sq), _cn)
            return tuple(g * factor.astype(g.dtype) for g in gs)
        sc_op = Operator('clip_by_global_norm', clip_scale_fn,
                         [sq_name] + list(owned_g), list(owned_g),
                         {'clip_norm': cn}, op_role=OpRole.Optimize)
        sc_op.multi_out = True
        clip_ops.append(sc_op)

    kept, pruned_state = [], set()
    for op in ops[first_opt:]:
        if op is clip_op and stage >= 2:
            continue   # replaced by the sharded clip sequence above
        if op.op_role & OpRole.Optimize and 'param' in op.attrs:
            if param2rank.get(op.attrs['param'], rank) != rank:
                # prune non-owned optimize op + its state vars
                for n in op.input_names:
                    if n not in (op.attrs['param'], '@LR') \
                            and not n.endswith('@GRAD'):
                        pruned_state.add(n)
                continue
        kept.append(op)

    for n in pruned_state:
        block.vars.pop(n, None)
    program.startup_ops = [v for v in program.startup_ops
                           if getattr(v, 'name', None) not in pruned_state]

    bcast_ops = []
    for p in params:
        op = Operator('c_broadcast', lambda x: x, [p.name], [p.name],
                      {'ring_id': SHARDING_RING,
                       'root': param2rank[p.name],
                       'use_calc_stream': True},
                      op_role=OpRole.Optimize)
        bcast_ops.append(op)

    block.ops = ops[:first_opt] + sync_ops + clip_ops + kept + bcast_ops
    program._sharding_rank = rank
    program._sharding_degree = degree
    program._sharding_param2rank = param2rank

    # pass-time telemetry: how many collectives this rewrite scheduled
    # per step and their payload (var shapes are known statically)
    from ..core.monitor import counter

    def _var_bytes(name):
        v = block.vars.get(name)
        shape = getattr(v, 'shape', None) or [1]
        return int(np.prod([d for d in shape if d and d > 0]) or 1) * 4
    for op in sync_ops + clip_ops + bcast_ops:
        if not op.type.startswith('c_'):
            continue
        counter('ptpu_sharding_pass_collectives_total',
                help='collective ops inserted by the sharding rewrite',
                labelnames=('op',)).inc(1, op=op.type)
        counter('ptpu_sharding_pass_bytes_total',
                help='per-step payload bytes the sharding rewrite '
                     'schedules',
                labelnames=('op',)).inc(
                    sum(_var_bytes(n) for n in op.input_names), op=op.type)
    return param2rank


class MultiRankShardingSimulator:
    """Run all ranks' sharded programs lockstep in one process, resolving
    c_* collectives across the rank envs — the in-process analogue of the
    reference's 2-process localhost collective tests (test_dist_base:744).
    """

    def __init__(self, rank_programs, seed=None):
        self.progs = rank_programs
        self.scopes = [{} for _ in rank_programs]
        # executed cross-rank collectives (one count per rendezvous, not
        # per rank) — lets tests assert LocalSGD's off-boundary steps
        # really run zero allreduces
        self.collective_count = 0
        self._startup(seed)

    def _startup(self, seed=None):
        from .program import materialize_persistables
        masters = []
        for r, prog in enumerate(self.progs):
            if seed is not None:   # identical init draws on every rank,
                import paddle_tpu  # like seeded multi-process startup
                paddle_tpu.seed(seed)
            scope = self.scopes[r]
            deferred = materialize_persistables(
                prog.global_block().vars.values(), scope.get,
                scope.__setitem__, apply_masters=False)
            masters.extend((r, v.name, src) for v, src in deferred)
        # startup param broadcast from each param's owner (parity: the
        # sharding pass rewrites the startup program with c_broadcast so
        # all ranks start from identical weights)
        p2r = getattr(self.progs[0], '_sharding_param2rank', {})
        for pname, owner in p2r.items():
            val = self.scopes[owner].get(pname)
            if val is not None:
                for scope in self.scopes:
                    scope[pname] = val
        for r, name, src in masters:   # fp32 masters of the synced params
            self.scopes[r][name] = self.scopes[r][src].astype(jnp.float32)

    def run(self, feeds_per_rank, fetch_name=None):
        envs = []
        opt = getattr(self.progs[0], '_optimizer', None)
        lr = jnp.asarray(opt.get_lr() if opt is not None else 0.0,
                         jnp.float32)
        for r, prog in enumerate(self.progs):
            env = {'@LR': lr}
            for k, v in feeds_per_rank[r].items():
                env[k] = jnp.asarray(np.asarray(v))
            for v in prog.global_block().vars.values():
                if isinstance(v, _ConstVar):
                    env[v.name] = v.value
                elif v.name in self.scopes[r]:
                    env[v.name] = self.scopes[r][v.name]
            envs.append(env)

        # LocalSGD host gating (mirrors Executor.run): off-boundary
        # steps skip the whole marked sync tail — zero collectives.
        # The step counter is lockstep across ranks, so skipping is
        # symmetric and the rendezvous stays aligned.
        skip_tail = [False] * len(self.progs)
        for r, prog in enumerate(self.progs):
            lk = getattr(prog, '_localsgd_k', 0)
            if lk and lk > 1:
                cur = self.scopes[r].get(
                    getattr(prog, '_localsgd_step_var', '@LOCALSGD_step'))
                cur = int(cur) if cur is not None else 0
                skip_tail[r] = ((cur + 1) % lk) != 0

        # ops run in list position order; collectives synchronize ranks.
        # Rank programs share the pre-optimize prefix and the broadcast
        # tail; the optimize middle differs per rank (pruning), so walk
        # each rank's list with a cursor and rendezvous at collectives.
        COLLECTIVE = {'c_allreduce_sum', 'c_reduce_sum', 'c_broadcast'}
        cursors = [0] * len(self.progs)
        done = [False] * len(self.progs)
        while not all(done):
            # advance each rank to its next collective (or end)
            pending = {}
            for r, prog in enumerate(self.progs):
                ops = prog.global_block().ops
                while cursors[r] < len(ops):
                    op = ops[cursors[r]]
                    if skip_tail[r] and op.attrs.get('localsgd_tail'):
                        cursors[r] += 1
                        continue
                    if op.type in COLLECTIVE:
                        pending[r] = op
                        break
                    self._run_local(op, envs[r])
                    cursors[r] += 1
                if cursors[r] >= len(ops):
                    done[r] = True
            if not pending:
                continue
            if len(pending) != len(self.progs):
                raise RuntimeError("collective rendezvous mismatch: only "
                                   f"ranks {sorted(pending)} reached one")
            ref = pending[0]
            if any(op.type != ref.type or op.input_names != ref.input_names
                   for op in pending.values()):
                raise RuntimeError("ranks diverged at collective: "
                                   f"{[op.type for op in pending.values()]}")
            self._run_collective(ref, envs)
            for r in pending:
                cursors[r] += 1

        for r, env in enumerate(envs):
            for v in self.progs[r].global_block().vars.values():
                if getattr(v, 'persistable', False) and v.name in env \
                        and v.name != '@LR':
                    self.scopes[r][v.name] = env[v.name]
        if fetch_name is not None:
            return [float(env[fetch_name]) for env in envs]
        return None

    def _run_local(self, op, env):
        run_op_in_env(op, env)

    def _run_collective(self, op, envs):
        self.collective_count += 1
        name = op.input_names[0]
        from ..core.monitor import counter
        from .. import profiler as _prof
        arr = envs[0].get(name)
        nbytes = 0
        if arr is not None and hasattr(arr, 'shape'):
            nbytes = int(np.prod(arr.shape or (1,))) * \
                jnp.dtype(arr.dtype).itemsize * len(envs)
        counter('ptpu_collective_calls_total',
                help='collective API invocations',
                labelnames=('op',)).inc(1, op=op.type)
        counter('ptpu_collective_bytes_total',
                help='payload bytes through collective APIs',
                labelnames=('op',)).inc(nbytes, op=op.type)
        from ..distributed import flight_recorder as _fr
        with _fr.record_span(op.type, nbytes=nbytes, mode='sim'):
            with _prof.RecordEvent(f'collective::{op.type}',
                                   event_type='collective', bytes=nbytes):
                self._run_collective_impl(op, envs)

    def _run_collective_impl(self, op, envs):
        name = op.input_names[0]
        if op.type == 'c_allreduce_sum':
            total = sum(env[name] for env in envs)
            for env in envs:
                env[name] = total
        elif op.type == 'c_reduce_sum':
            total = sum(env[name] for env in envs)
            envs[op.attrs['root_id']][name] = total
        elif op.type == 'c_broadcast':
            val = envs[op.attrs['root']][name]
            for env in envs:
                env[name] = val
