"""Static AMP: cast-insertion program rewrite.

Reference parity: fluid/contrib/mixed_precision/fp16_utils.py
`rewrite_program:484` + `_insert_cast_op:83` over AutoMixedPrecisionLists
(fp16_lists.py): white-list ops run in low precision (cast ops inserted on
their float inputs), black-list ops are pinned to fp32, gray ops follow
their inputs. On TPU the low-precision dtype is bf16 (MXU-native; no loss
scaling needed, though GradScaler still accepts the knobs for parity).

The rewrite runs BEFORE append_backward, so the recorded backward ops
differentiate straight through the inserted casts — the same ordering as
the reference's OptimizerWithMixedPrecision.
"""
import jax.numpy as jnp

from ..core import dtypes
from .program import Variable, Operator, OpRole

# auto_cast.py:27-52 lists (bf16 spellings)
WHITE_LIST = {'matmul', 'matmul_v2', 'mul', 'conv2d', 'fc'}
BLACK_LIST = {'exp', 'square', 'log', 'mean', 'reduce_mean', 'sum',
              'reduce_sum', 'cos_sim', 'softmax',
              'softmax_with_cross_entropy',
              'sigmoid_cross_entropy_with_logits', 'cross_entropy',
              'cross_entropy2'}


class AutoMixedPrecisionLists:
    """Parity: fp16_lists.AutoMixedPrecisionLists."""

    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        cw = set(custom_white_list or ())
        cb = set(custom_black_list or ())
        if cw & cb:
            raise ValueError(f"ops in both custom lists: {cw & cb}")
        # custom entries override the defaults (fp16_lists.py moves an op
        # out of the default list before adding it to the other)
        self.white_list = (set(WHITE_LIST) - cb) | cw
        self.black_list = (set(BLACK_LIST) - cw) | cb
        self.black_varnames = set(custom_black_varnames or ())


def rewrite_program_amp(program, amp_lists=None, dest_dtype='bfloat16'):
    """Insert cast ops so white-list ops consume `dest_dtype` and
    black-list ops consume float32 (parity: rewrite_program:484).
    Returns the number of cast ops inserted."""
    lists = amp_lists or AutoMixedPrecisionLists()
    block = program.global_block()
    low = dtypes.convert_dtype(dest_dtype)
    f32 = dtypes.convert_dtype('float32')
    cast_cache = {}      # (var, dtype name) -> cast var name
    out_ops = []
    n_casts = 0

    def _cast_to(name, dt, role):
        nonlocal n_casts
        key = (name, str(dt))
        if key in cast_cache:
            return cast_cache[key]
        src = block.vars[name]
        cname = f"{name}.cast_{dtypes.dtype_name(dt)}"
        if cname not in block.vars:
            cv = Variable(block, cname, src.shape, dt,
                          stop_gradient=src.stop_gradient)
            block.vars[cname] = cv
        op = Operator('cast', lambda a, _d=dt: a.astype(_d), [name],
                      [cname], {'out_dtype': dtypes.dtype_name(dt)},
                      op_role=role)
        out_ops.append(op)
        cast_cache[key] = cname
        n_casts += 1
        return cname

    var_dtype = {n: v.dtype for n, v in block.vars.items()}

    def _infer_out_dtypes(op):
        """Real output dtypes via jax.eval_shape on the op's fn at the
        (possibly cast) input avals — JAX type promotion at replay is the
        ground truth, not an all-inputs-low heuristic (a bf16+f32 gray op
        yields f32). Dynamic dims use a placeholder extent: dtype inference
        is size-independent."""
        import jax
        avals = []
        for n in op.input_names:
            v = block.vars.get(n)
            if v is None:
                return None
            shape = tuple(2 if (d is None or d < 0) else d
                          for d in v.shape)
            avals.append(jax.ShapeDtypeStruct(shape,
                                              var_dtype.get(n, v.dtype)))
        try:
            out = jax.eval_shape(op.fn, *avals)
        except Exception:
            return None
        outs = out if isinstance(out, (tuple, list)) else [out]
        return [o.dtype for o in outs]

    for op in block.ops:
        if op.op_role & (OpRole.Backward | OpRole.Optimize):
            out_ops.append(op)
            continue
        if op.type in lists.white_list:
            want = low
        elif op.type in lists.black_list:
            want = f32
        else:
            want = None     # gray: follow inputs
        if want is not None:
            new_ins = []
            for n in op.input_names:
                v = block.vars.get(n)
                if (v is not None and dtypes.is_floating(var_dtype[n])
                        and var_dtype[n] != want
                        and n not in lists.black_varnames):
                    new_ins.append(_cast_to(n, want, op.op_role))
                else:
                    new_ins.append(n)
            op.input_names = new_ins
        out_ops.append(op)
        out_dts = _infer_out_dtypes(op)
        for i, o in enumerate(op.output_names):
            if o in block.vars and dtypes.is_floating(var_dtype.get(o,
                                                                    f32)):
                if out_dts is not None and i < len(out_dts) \
                        and dtypes.is_floating(out_dts[i]):
                    var_dtype[o] = out_dts[i]
                    block.vars[o].dtype = out_dts[i]
    block.ops = out_ops
    program._amp_rewritten = True
    return n_casts
