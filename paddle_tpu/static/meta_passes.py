"""Program rewrites behind the strategy-driven static meta-optimizers.

Reference parity:
- GradientMerge: fluid GradientMergeOptimizer
  (/root/reference/python/paddle/fluid/optimizer.py:6255) — per-grad
  persistable accumulators, a step counter, and the optimize ops moved
  under a conditional that fires every k steps (then zeroing the
  accumulators).
- LocalSGD: fleet/meta_optimizers/localsgd_optimizer.py:27,63-79 —
  ranks train independently; every k steps parameters are synchronized
  across the data-parallel group.
- dp grad sync: raw_program_optimizer.py:158 _insert_allreduce_ops and
  tensor_parallel_optimizer.py _transpile_main_program — scale the loss
  cotangent by 1/nranks and c_allreduce_sum every parameter gradient.

TPU-native notes: the conditional apply uses the nested-sub-block
`conditional_block` op (replayed as lax.cond). LocalSGD's periodic sync
keeps its collectives as TOP-LEVEL ops (so multi-rank runners can
rendezvous on them) but marks the whole tail `localsgd_tail`; the k-step
boundary is a HOST-side decision — the step counter is persistable
scope state, so the runner picks between two cached executables
(sync-step / local-step) by `step % k` and off-boundary steps execute
zero collectives. In-program where-blend gating is kept as a fallback
for marker-unaware runners (correct, just not comm-saving).
"""
import jax.numpy as jnp

from .program import Variable, Operator, OpRole


def _first_optimize_pos(ops):
    for i, op in enumerate(ops):
        if op.op_role & OpRole.Optimize:
            return i
    return len(ops)


def _make_counter(program, name):
    """Persistable int32 scalar counter var initialized to 0."""
    block = program.global_block()
    if name not in block.vars:
        v = Variable(block, name, [], 'int32', persistable=True)
        v.initializer = lambda shape, dtype: jnp.zeros((), jnp.int32)
        block.vars[name] = v
        program.startup_ops.append(v)
    return block.vars[name]


def apply_gradient_merge(program, k_steps, avg=True):
    """Rewrite `program` in place: accumulate each parameter gradient into
    a persistable `<grad>@GradientMerge` buffer every step and run the
    Optimize-role ops only every `k_steps`-th step, inside a
    conditional_block sub-block, on the (optionally averaged) accumulated
    gradients; the accumulators are zeroed after the apply.
    """
    k = int(k_steps)
    if k < 1:
        raise ValueError(f"gradient_merge k_steps must be >= 1, got {k}")
    block = program.global_block()
    ops = list(block.ops)
    first_opt = _first_optimize_pos(ops)
    head, opt_ops = ops[:first_opt], ops[first_opt:]
    grads = sorted({g for g in program._grad_map.values()
                    if g in block.vars})
    if not grads or not opt_ops:
        raise ValueError("gradient_merge needs recorded backward + "
                         "optimize ops (call minimize first)")

    # persistable accumulators + step counter
    acc_of = {}
    for g in grads:
        an = g + '@GradientMerge'
        gv = block.vars[g]
        av = Variable(block, an, list(gv.shape or []), gv.dtype,
                      persistable=True)
        av.initializer = (lambda shape, dtype:
                          jnp.zeros(tuple(shape), dtype))
        block.vars[an] = av
        program.startup_ops.append(av)
        acc_of[g] = an
    step = _make_counter(program, '@GM_step')

    new_ops = list(head)
    for g, a in acc_of.items():
        new_ops.append(Operator('gm_accumulate', lambda acc, grad:
                                acc + grad.astype(acc.dtype),
                                [a, g], [a], {}, op_role=OpRole.Backward))
    new_ops.append(Operator('increment', lambda s: s + 1,
                            [step.name], [step.name], {},
                            op_role=OpRole.Optimize))
    pred = '@GM_cond'
    block.vars[pred] = Variable(block, pred, [], 'bool')
    new_ops.append(Operator('gm_cond',
                            lambda s, _k=k: (s % _k) == 0,
                            [step.name], [pred], {'k': k},
                            op_role=OpRole.Optimize))

    # true branch sub-block: scale accumulators -> optimize ops (grad
    # inputs rewired to the scaled accumulators) -> zero accumulators
    tb = program._create_block()
    program._rollback()
    fb = program._create_block()
    program._rollback()
    scaled_of = {}
    for g, a in acc_of.items():
        sn = a + '@AVG'
        av = block.vars[a]
        block.vars[sn] = Variable(block, sn, list(av.shape or []),
                                  av.dtype)
        factor = (1.0 / k) if avg else 1.0
        tb.ops.append(Operator('scale',
                               lambda x, _f=factor: x * _f,
                               [a], [sn], {'scale': factor},
                               op_role=OpRole.Optimize))
        scaled_of[g] = sn
    touched = []            # vars the branch updates (params/state/accs)
    for op in opt_ops:
        op.input_names = [scaled_of.get(n, n) for n in op.input_names]
        tb.ops.append(op)
        for o in op.output_names:
            if o not in touched:
                touched.append(o)
    for g, a in acc_of.items():
        tb.ops.append(Operator('fill_zeros_like',
                               lambda x: jnp.zeros_like(x),
                               [a], [a], {}, op_role=OpRole.Optimize))
        touched.append(a)

    cond_op = Operator(
        'conditional_block', None, [pred], list(touched),
        {'sub_block_true': tb.idx, 'sub_block_false': fb.idx,
         'true_outs': list(touched), 'false_outs': list(touched)},
        op_role=OpRole.Optimize)
    new_ops.append(cond_op)
    block.ops = new_ops
    program._gradient_merge_k = k
    program._gradient_merge_avg = bool(avg)
    return len(acc_of)


def apply_localsgd(program, k_steps, nranks, ring_id=0):
    """Append the LocalSGD parameter-sync tail: every `k_steps`-th step
    each trainable parameter is replaced by the cross-rank average
    (c_allreduce_sum + 1/nranks blend on the step gate); other steps the
    parameters keep their locally-optimized values.

    The tail ops carry `localsgd_tail: True` and the program records
    `_localsgd_k`: runners that understand the marker (Executor,
    MultiRankShardingSimulator) gate the WHOLE tail host-side on the
    k-step boundary — k-1 of every k steps execute ZERO collectives,
    which is the communication saving LocalSGD exists for
    (localsgd_optimizer.py:63-79 syncs only at boundaries). A runner
    that ignores the marker still trains correctly (allreduce every
    step, where-blend keeps off-boundary params local) — just without
    the comm saving."""
    k = int(k_steps)
    if k < 1:
        raise ValueError(f"localsgd k_steps must be >= 1, got {k}")
    block = program.global_block()
    params = [p for p in program.all_parameters()
              if p.name in program._grad_map]
    if not params:
        raise ValueError("localsgd needs trained parameters "
                         "(call minimize first)")
    step = _make_counter(program, '@LOCALSGD_step')
    gate = '@LOCALSGD_gate'
    block.vars[gate] = Variable(block, gate, [], 'bool')
    block.ops.append(Operator('increment', lambda s: s + 1,
                              [step.name], [step.name], {},
                              op_role=OpRole.Optimize))
    block.ops.append(Operator('localsgd_gate',
                              lambda s, _k=k: (s % _k) == 0,
                              [step.name], [gate], {'k': k},
                              op_role=OpRole.Optimize))
    tail = {'localsgd_tail': True}
    for p in params:
        tmp = p.name + '@LOCALSGD_sum'
        block.vars[tmp] = Variable(block, tmp, list(p.shape or []),
                                   p.dtype)
        block.ops.append(Operator('share_data', lambda x: x,
                                  [p.name], [tmp], dict(tail),
                                  op_role=OpRole.Optimize))
        block.ops.append(Operator('c_allreduce_sum', lambda x: x,
                                  [tmp], [tmp],
                                  {'ring_id': ring_id,
                                   'use_calc_stream': True, **tail},
                                  op_role=OpRole.Optimize))

        def blend(pv, sv, gv, _n=nranks):
            avg = (sv.astype(jnp.float32) / _n).astype(pv.dtype)
            return jnp.where(gv, avg, pv)
        block.ops.append(Operator('localsgd_blend', blend,
                                  [p.name, tmp, gate], [p.name],
                                  {'nranks': nranks, **tail},
                                  op_role=OpRole.Optimize))
    program._localsgd_k = k
    program._localsgd_nranks = nranks
    program._localsgd_step_var = step.name
    return len(params)


def insert_dp_grad_sync(program, nranks, ring_id=0):
    """Insert the data-parallel gradient exchange: scale the loss
    cotangent by 1/nranks right after its seed op, then c_allreduce_sum
    every parameter gradient before the first Optimize-role op."""
    if nranks < 2:
        return 0
    block = program.global_block()
    ops = list(block.ops)

    loss = getattr(program, '_loss_var', None)
    if loss is not None:
        seed_name = loss.name + '@GRAD'
        for i, op in enumerate(ops):
            if seed_name in op.output_names \
                    and (op.op_role & OpRole.Backward):
                ops.insert(i + 1, Operator(
                    'scale', lambda x, _n=nranks: x / _n,
                    [seed_name], [seed_name],
                    {'scale': 1.0 / nranks}, op_role=OpRole.Backward))
                break

    first_opt = _first_optimize_pos(ops)
    sync = []
    for g in sorted({g for g in program._grad_map.values()
                     if g in block.vars}):
        sync.append(Operator('c_allreduce_sum', lambda x: x, [g], [g],
                             {'ring_id': ring_id,
                              'use_calc_stream': True},
                             op_role=OpRole.Backward))
    block.ops = ops[:first_opt] + sync + ops[first_opt:]
    program._dp_allreduce = True
    program._dp_nranks = nranks
    return len(sync)
