"""paddle.static sheet remainder: program-state utilities, places,
param helpers (reference: python/paddle/static/__init__.py __all__,
python/paddle/fluid/framework.py program-state fns)."""
import os

import numpy as np

from ..core.tensor import Tensor


def cpu_places(device_count=None):
    """paddle.static.cpu_places."""
    from ..device import CPUPlace
    n = device_count or int(os.environ.get('CPU_NUM', 1))
    return [CPUPlace() for _ in range(n)]


def cuda_places(device_ids=None):
    """paddle.static.cuda_places — maps to the accelerator devices PJRT
    exposes (TPU chips here)."""
    import jax
    from ..device import CUDAPlace
    ids = device_ids if device_ids is not None else \
        range(len(jax.devices()))
    return [CUDAPlace(i) for i in ids]


def xpu_places(device_ids=None):
    """paddle.static.xpu_places — same accelerator mapping."""
    return cuda_places(device_ids)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """paddle.static.create_parameter."""
    from .nn import _make_param
    return _make_param(list(shape), dtype, initializer=default_initializer,
                       attr=attr)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    """paddle.static.create_global_var — a filled persistable var in the
    startup/main programs."""
    from .program import default_main_program
    from ..nn import initializer as I
    prog = default_main_program()
    block = prog.global_block()
    v = block.create_parameter(name=name, shape=list(shape), dtype=dtype,
                               initializer=I.Constant(float(value)))
    v.persistable = persistable
    return v


def load_program_state(model_path, var_list=None):
    """paddle.static.load_program_state — read a saved .pdiparams file
    into a {name: ndarray} dict (pairs with static.save's npz
    container)."""
    from .serialization import _load_npz
    path = model_path if model_path.endswith('.pdiparams') \
        else model_path + '.pdiparams'
    with open(path, 'rb') as f:
        state = _load_npz(f.read())
    if var_list is not None:
        names = {getattr(v, 'name', v) for v in var_list}
        state = {k: v for k, v in state.items() if k in names}
    return {k: np.asarray(v) for k, v in state.items()}


def set_program_state(program, state_dict):
    """paddle.static.set_program_state — install ndarray values into
    the program's parameter variables."""
    import jax.numpy as jnp
    from .executor import global_scope
    scope = global_scope()
    for name, arr in state_dict.items():
        scope.set(name, jnp.asarray(arr))
    for block in program.blocks:
        for name, var in getattr(block, 'vars', {}).items():
            if name in state_dict and hasattr(var, 'set_value'):
                var.set_value(state_dict[name])


def serialize_persistables(feed_vars, fetch_vars, executor=None,
                           program=None):
    """paddle.static.serialize_persistables — the params side of the
    inference-model pair as bytes (the npz container static.save
    writes)."""
    import jax
    from .program import default_main_program
    from .serialization import _npz_bytes, _ConstVar
    from .executor import global_scope
    prog = program or default_main_program()
    scope = global_scope()
    state = {}
    for v in prog.list_vars():
        if getattr(v, 'persistable', False) \
                and not isinstance(v, _ConstVar):
            arr = scope.find_var(v.name)
            if arr is not None:
                state[v.name] = np.asarray(jax.device_get(arr))
    return _npz_bytes(state)


def deserialize_persistables(program, data, executor=None):
    """paddle.static.deserialize_persistables — stage the serialized
    params back into the scope."""
    import jax.numpy as jnp
    from .serialization import _load_npz
    from .executor import global_scope
    scope = global_scope()
    for name, arr in _load_npz(data).items():
        scope.set(name, jnp.asarray(arr))
    return program


def save_to_file(path, content):
    """paddle.static.save_to_file."""
    with open(path, 'wb') as f:
        f.write(content)


def load_from_file(path):
    """paddle.static.load_from_file."""
    with open(path, 'rb') as f:
        return f.read()


def normalize_program(program, feed_vars, fetch_vars):
    """paddle.static.normalize_program — prune to the feed->fetch
    closure (the Executor's replay already dead-code-eliminates through
    XLA; pruning here keeps the serialized artifact minimal)."""
    if hasattr(program, '_prune'):
        return program._prune(feed_vars, fetch_vars)
    return program


class WeightNormParamAttr:
    """paddle.static.WeightNormParamAttr — ParamAttr carrying a
    weight-norm reparameterization request (dim)."""

    def __init__(self, dim=None, name=None, initializer=None,
                 learning_rate=1.0, regularizer=None, trainable=True,
                 do_model_average=False, need_clip=True):
        self.dim = dim
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
