"""Static Executor + Scope.

Reference parity: fluid/executor.py (Executor.run:916 → _run_impl:1112) and
the C++ op-loop Executor (framework/executor.cc, N15). TPU-native: the whole
Program replays inside ONE `jax.jit` trace per (program, feed signature) —
XLA fuses and schedules; persistable parameters live in a Scope and are
donated/threaded through the compiled function so optimizer updates stay on
device.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.tensor import Tensor
from .program import (Program, Parameter, Variable, _ConstVar,
                      default_main_program, default_startup_program, OpRole)


class Scope:
    """Parity: framework/scope.h — name → value map."""

    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)

    def set(self, name, value):
        self.vars[name] = value


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        _scope_stack.append(self.scope)

    def __exit__(self, *a):
        _scope_stack.pop()


def _program_fingerprint(program):
    """Structural hash of every block's op list (type, io names, fn
    identity, attrs) so the jit cache invalidates on ANY program
    mutation — including in-place op rewrites that keep the op count
    constant (parity: CompiledProgram invalidation semantics,
    fluid/compiler.py). O(ops) Python per run, amortized noise next to
    the jit dispatch itself."""
    h = 0
    for b in program.blocks:
        for op in b.ops:
            try:
                attrs = tuple(sorted((k, str(v))
                              for k, v in op.attrs.items()))
            except Exception:
                attrs = ()
            h = hash((h, op.type, tuple(op.input_names),
                      tuple(op.output_names), id(op.fn), attrs))
    return h


class Executor:
    """Parity: fluid/executor.py Executor. place is accepted and ignored —
    PJRT owns placement."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        if getattr(program, '_sharding_degree', 1) > 1:
            # A sharded program's c_reduce_sum/c_broadcast ops need peer
            # ranks; replaying them single-process as identities would
            # silently skip the pruned params' updates and train wrong.
            raise RuntimeError(
                "this program was rewritten for sharding_degree="
                f"{program._sharding_degree}: run one rank per process "
                "with real collectives (fleetrun + the hybrid SPMD "
                "engine), or use MultiRankShardingSimulator for "
                "single-process checks")

        # Startup program: initialize parameters eagerly.
        if program.startup_ops or not program.global_block().ops:
            self._run_startup(program, scope)
            if not program.global_block().ops:
                return []

        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]

        feed_items = sorted(feed.items())
        feed_names = tuple(k for k, _ in feed_items)
        feed_arrays = []
        for k, v in feed_items:
            if isinstance(v, Tensor):
                feed_arrays.append(v.data)
            else:
                feed_arrays.append(jnp.asarray(np.asarray(v)))

        param_names, param_arrays = self._collect_params(program, scope)
        opt = getattr(program, '_optimizer', None)
        lr = jnp.asarray(
            opt.get_lr() if opt is not None
            else getattr(program, '_loaded_lr', 0.0), jnp.float32)

        # LocalSGD host gating: the step counter is scope state, so the
        # k-step boundary picks between TWO cached executables — the
        # local-step one simply omits the `localsgd_tail` ops (zero
        # collectives off-boundary; VERDICT r4 weak #3)
        skip_tail = False
        lk = getattr(program, '_localsgd_k', 0)
        if lk and lk > 1:
            sv = scope.find_var(getattr(program, '_localsgd_step_var',
                                        '@LOCALSGD_step'))
            cur = int(sv) if sv is not None else 0
            skip_tail = ((cur + 1) % lk) != 0

        from .. import profiler as _prof
        from ..core import memory as _mem
        from ..core.monitor import stat_add

        key = (id(program), feed_names,
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_names), _program_fingerprint(program),
               id(opt), skip_tail)
        entry = self._cache.get(key)
        if entry is None:
            # compile-cache miss: trace+lower+compile split out from
            # execution (observability v2) — the AOT executable is the
            # fast path, the plain jitted fn the signature-drift fallback
            stat_add('STAT_executor_cache_miss')
            with _prof.RecordEvent('executor::build_program',
                                   event_type='compile',
                                   ops=len(program.global_block().ops)), \
                    _mem.phase('executor.compile'):
                jitted = jax.jit(self._make_replay(
                    program, feed_names, param_names, fetch_names,
                    skip_tail=skip_tail))
                compiled, _aot = _prof.compile_with_telemetry(
                    jitted, 'executor',
                    (tuple(feed_arrays), tuple(param_arrays), lr))
            entry = self._cache[key] = (compiled, jitted)
        else:
            stat_add('STAT_executor_cache_hit')

        stat_add('STAT_executor_runs')
        compiled, jitted = entry
        with _prof.RecordEvent('executor::run', event_type='executor'), \
                _mem.oom_guard('executor.run'), \
                _mem.phase('executor.execute'):
            try:
                fetches, new_params = compiled(
                    tuple(feed_arrays), tuple(param_arrays), lr)
            except TypeError:
                # AOT signature drift (e.g. param dtype changed without a
                # program mutation): retrace via the jitted fallback
                if compiled is jitted:
                    raise
                self._cache[key] = (jitted, jitted)
                fetches, new_params = jitted(
                    tuple(feed_arrays), tuple(param_arrays), lr)
        for name, arr in zip(param_names, new_params):
            scope.set(name, arr)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- helpers -------------------------------------------------------------
    def _run_startup(self, program, scope):
        from .program import materialize_persistables
        materialize_persistables(program.startup_ops, scope.find_var,
                                 scope.set)
        program.startup_ops = []

    def _collect_params(self, program, scope):
        """All persistable state threaded through the jitted replay:
        Parameters plus optimizer-state vars (recorded by
        _append_optimize_ops)."""
        from .program import materialize_persistables
        materialize_persistables(program.list_vars(), scope.find_var,
                                 scope.set)
        names, arrays = [], []
        for v in program.list_vars():
            if isinstance(v, _ConstVar) or v.name == '@LR':
                continue
            if v.persistable:
                arr = scope.find_var(v.name)
                if arr is None:
                    continue
                names.append(v.name)
                arrays.append(arr)
        return names, arrays

    def _make_replay(self, program, feed_names, param_names, fetch_names,
                     skip_tail=False):
        """Pure op replay: every recorded op (forward, backward, optimize)
        executes in order inside one jax.jit trace. Gradients and optimizer
        updates are ordinary ops appended by append_backward /
        _append_optimize_ops, so distributed rewrites that moved or pruned
        ops replay exactly what they left in the block."""
        block = program.global_block()
        from .program import run_op_in_env

        def replay(feed_arrays, param_arrays, lr):
            env = {'@LR': lr}
            for name, arr in zip(feed_names, feed_arrays):
                env[name] = arr
            for name, arr in zip(param_names, param_arrays):
                env[name] = arr
            for b in program.blocks:     # consts incl. sub-block captures
                for v in b.vars.values():
                    if isinstance(v, _ConstVar):
                        env[v.name] = v.value

            for op in block.ops:
                if skip_tail and op.attrs.get('localsgd_tail'):
                    continue
                run_op_in_env(op, env, program)

            new_params = [env[n] for n in param_names]
            fetches = [env[n] for n in fetch_names]
            return fetches, new_params
        return replay


class NaiveExecutor(Executor):
    pass
