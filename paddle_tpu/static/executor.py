"""Static Executor + Scope.

Reference parity: fluid/executor.py (Executor.run:916 → _run_impl:1112) and
the C++ op-loop Executor (framework/executor.cc, N15). TPU-native: the whole
Program replays inside ONE `jax.jit` trace per (program, feed signature) —
XLA fuses and schedules; persistable parameters live in a Scope and are
donated/threaded through the compiled function so optimizer updates stay on
device.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core import dtypes
from ..core.tensor import Tensor
from .program import (Program, Parameter, Variable, _ConstVar,
                      default_main_program, default_startup_program, OpRole)


class Scope:
    """Parity: framework/scope.h — name → value map."""

    def __init__(self):
        self.vars = {}

    def var(self, name):
        return self.vars.setdefault(name, None)

    def find_var(self, name):
        return self.vars.get(name)

    def set(self, name, value):
        self.vars[name] = value


_global_scope = Scope()
_scope_stack = [_global_scope]


def global_scope():
    return _scope_stack[-1]


class scope_guard:
    def __init__(self, scope):
        self.scope = scope

    def __enter__(self):
        _scope_stack.append(self.scope)

    def __exit__(self, *a):
        _scope_stack.pop()


class Executor:
    """Parity: fluid/executor.py Executor. place is accepted and ignored —
    PJRT owns placement."""

    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def run(self, program=None, feed=None, fetch_list=None, scope=None,
            return_numpy=True, use_program_cache=True):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        scope = scope or global_scope()

        # Startup program: initialize parameters eagerly.
        if program.startup_ops or not program.global_block().ops:
            self._run_startup(program, scope)
            if not program.global_block().ops:
                return []

        fetch_names = [f.name if isinstance(f, Variable) else str(f)
                       for f in fetch_list]

        feed_items = sorted(feed.items())
        feed_names = tuple(k for k, _ in feed_items)
        feed_arrays = []
        for k, v in feed_items:
            if isinstance(v, Tensor):
                feed_arrays.append(v.data)
            else:
                feed_arrays.append(jnp.asarray(np.asarray(v)))

        param_names, param_arrays = self._collect_params(program, scope)
        opt = getattr(program, '_optimizer', None)
        states_key = f'__opt_states__/{id(program)}/{id(opt)}'
        opt_states = scope.find_var(states_key)
        if opt is not None and opt_states is None:
            opt_states = {}
            for name in param_names:
                arr = scope.find_var(name)
                st = opt.init_state(Tensor(arr))
                if arr.dtype != jnp.float32 and \
                        getattr(opt, '_multi_precision', True):
                    st['master'] = arr.astype(jnp.float32)
                opt_states[name] = st
            scope.set(states_key, opt_states)
        if opt_states is None:
            opt_states = {}
        lr = jnp.asarray(opt.get_lr() if opt is not None else 0.0,
                         jnp.float32)

        key = (id(program), feed_names,
               tuple((a.shape, str(a.dtype)) for a in feed_arrays),
               tuple(fetch_names), len(program.global_block().ops),
               id(opt))
        compiled = self._cache.get(key)
        if compiled is None:
            compiled = jax.jit(self._make_replay(program, feed_names,
                                                 param_names, fetch_names))
            self._cache[key] = compiled

        fetches, new_params, new_states = compiled(
            tuple(feed_arrays), tuple(param_arrays), opt_states, lr)
        for name, arr in zip(param_names, new_params):
            scope.set(name, arr)
        if opt is not None:
            scope.set(states_key, new_states)
        if return_numpy:
            return [np.asarray(f) for f in fetches]
        return [Tensor(f) for f in fetches]

    # -- helpers -------------------------------------------------------------
    def _run_startup(self, program, scope):
        from ..nn import initializer as I
        for p in program.startup_ops:
            if scope.find_var(p.name) is None:
                init = p.initializer or I.XavierUniform()
                scope.set(p.name, init(p.shape, p.dtype))
        program.startup_ops = []

    def _collect_params(self, program, scope):
        names, arrays = [], []
        for v in program.list_vars():
            if isinstance(v, Parameter):
                arr = scope.find_var(v.name)
                if arr is None:
                    from ..nn import initializer as I
                    arr = (v.initializer or I.XavierUniform())(v.shape,
                                                              v.dtype)
                    scope.set(v.name, arr)
                names.append(v.name)
                arrays.append(arr)
        return names, arrays

    def _make_replay(self, program, feed_names, param_names, fetch_names):
        block = program.global_block()
        loss_name = program._loss_var.name if program._loss_var is not None \
            else None
        grad_map = dict(program._grad_map)
        opt = getattr(program, '_optimizer', None)

        def replay(feed_arrays, param_arrays, opt_states, lr):
            env = {}
            for name, arr in zip(feed_names, feed_arrays):
                env[name] = arr
            for name, arr in zip(param_names, param_arrays):
                env[name] = arr
            for v in block.vars.values():
                if isinstance(v, _ConstVar):
                    env[v.name] = v.value

            def run_ops():
                for op in block.ops:
                    ins = [env[n] for n in op.input_names]
                    outs = op.fn(*ins)
                    if not isinstance(outs, (tuple, list)):
                        outs = (outs,)
                    for n, o in zip(op.output_names, outs):
                        env[n] = o
                return env

            if grad_map and loss_name is not None:
                # Differentiate the whole replay wrt parameters — the
                # XLA-native append_backward (fluid/backward.py parity).
                grad_param_names = [p for p in grad_map
                                    if p in set(param_names)]

                def loss_of(pa):
                    env_local = dict(env)
                    for n, a in zip(grad_param_names, pa):
                        env_local[n] = a
                    for op in block.ops:
                        ins = [env_local[n] for n in op.input_names]
                        outs = op.fn(*ins)
                        if not isinstance(outs, (tuple, list)):
                            outs = (outs,)
                        for n, o in zip(op.output_names, outs):
                            env_local[n] = o
                    return env_local[loss_name].sum(), env_local

                pa = tuple(env[n] for n in grad_param_names)
                grads, env2 = jax.grad(loss_of, has_aux=True)(pa)
                env.update(env2)
                for n, g in zip(grad_param_names, grads):
                    env[grad_map[n]] = g
            else:
                run_ops()

            new_params = [env[n] for n in param_names]
            new_states = opt_states
            if opt is not None and grad_map:
                params = {n: env[n] for n in param_names}
                grads = {n: env.get(grad_map.get(n, '__none__'))
                         for n in param_names}
                grads = {n: g for n, g in grads.items() if g is not None}
                updated, new_states = opt.functional_apply(
                    params, grads, opt_states, lr)
                new_params = [updated.get(n, env[n]) for n in param_names]

            fetches = [env[n] for n in fetch_names]
            return fetches, new_params, new_states
        return replay


class NaiveExecutor(Executor):
    pass
