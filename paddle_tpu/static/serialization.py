"""Program serialization — the ProgramDesc round-trip.

Reference parity: framework.proto (ProgramDesc:202 / OpDesc:43 /
VarDesc:169) and fluid/io.py save/load_inference_model — a Program saved by
one process is loadable in a fresh process, runnable by the Executor, and
still an editable op-level IR (the distributed rewrites operate on loaded
programs exactly like recorded ones).

TPU-native format: the op table (type, inputs, outputs, attrs, op_role,
op_device) is plain data, and each op's kernel is its jax fn exported as
portable StableHLO (jax.export, cpu+tpu platforms) at the op's recorded
input shapes — the "kernel" the reference looks up by op type at run time
ships with the program instead. Parameters are saved separately
(save/load_inference_model) like the reference's .pdiparams.
"""
import io
import pickle

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core import dtypes
from .program import (Program, Block, Variable, Parameter, Operator,
                      _ConstVar)

FORMAT_VERSION = 1
_PLATFORMS = ('cpu', 'tpu')


def _aval_of(v, scope=None):
    """Dynamic dims (None/-1, the paddle dynamic-batch idiom) export as
    jax symbolic dimensions so loaded kernels accept any size there.
    Dynamic dims share a symbol per axis position, matching record_op."""
    if all(d is not None and d >= 0 for d in v.shape):
        return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
    parts = [f'_dyn{j}' if d is None or d < 0 else str(d)
             for j, d in enumerate(v.shape)]
    dims = jax_export.symbolic_shape(', '.join(parts), scope=scope)
    return jax.ShapeDtypeStruct(tuple(dims), v.dtype)


def _safe_attrs(attrs):
    out = {}
    for k, v in (attrs or {}).items():
        try:
            pickle.dumps(v)
            out[k] = v
        except Exception:
            out[k] = repr(v)
    return out


def serialize_program(program):
    """Program -> bytes. Ops whose fn cannot be exported (host-side ops
    like recv_v2) are stored with a named fallback instead of a kernel."""
    block = program.global_block()
    vars_desc, consts = [], {}
    for v in block.vars.values():
        d = {'name': v.name, 'shape': list(v.shape),
             'dtype': dtypes.dtype_name(v.dtype),
             'persistable': bool(getattr(v, 'persistable', False)),
             'stop_gradient': bool(getattr(v, 'stop_gradient', True)),
             'is_parameter': isinstance(v, Parameter),
             'op_device': getattr(v, 'op_device', ''),
             'init_from': getattr(v, '_init_from', None),
             'is_const': isinstance(v, _ConstVar)}
        if isinstance(v, _ConstVar):
            consts[v.name] = np.asarray(jax.device_get(v.value))
        vars_desc.append(d)

    ops_desc, kernels = [], []
    for op in block.ops:
        desc = {'type': op.type, 'inputs': list(op.input_names),
                'outputs': list(op.output_names),
                'attrs': _safe_attrs(op.attrs),
                'op_role': op.op_role, 'op_device': op.op_device,
                'multi_out': bool(getattr(op, 'multi_out', False)),
                'kernel': None}
        if op.type == 'recv_v2':
            desc['fallback'] = 'none'
        elif op.type == 'send_v2':
            desc['fallback'] = 'identity'
        else:
            sym_scope = jax_export.SymbolicScope()
            avals = [_aval_of(block.vars[n], sym_scope)
                     for n in op.input_names]
            exported = jax_export.export(
                jax.jit(op.fn), platforms=list(_PLATFORMS))(*avals)
            desc['kernel'] = len(kernels)
            kernels.append(exported.serialize())
        ops_desc.append(desc)

    payload = {
        'version': FORMAT_VERSION,
        'vars': vars_desc,
        'ops': ops_desc,
        'kernels': kernels,
        'consts': consts,
        'grad_map': dict(program._grad_map),
        'loss_var': program._loss_var.name
        if program._loss_var is not None else None,
        'has_backward_ops': bool(getattr(program, '_has_backward_ops',
                                         False)),
        'lr': (float(program._optimizer.get_lr())
               if getattr(program, '_optimizer', None) is not None
               else None),
    }
    return pickle.dumps(payload, protocol=4)


def _kernel_fn(blob, multi_out):
    exported = jax_export.deserialize(blob)

    def fn(*xs):
        out = exported.call(*xs)
        # jax.export flattens single outputs into a 1-tuple
        if not multi_out and isinstance(out, (tuple, list)) \
                and len(out) == 1:
            return out[0]
        return tuple(out) if isinstance(out, (tuple, list)) else out
    return fn


def deserialize_program(data):
    """bytes -> Program (editable, Executor-runnable)."""
    payload = pickle.loads(data)
    if payload['version'] != FORMAT_VERSION:
        raise ValueError(f"program format {payload['version']} "
                         f"(expected {FORMAT_VERSION})")
    prog = Program()
    block = prog.global_block()
    for d in payload['vars']:
        if d['is_const']:
            v = _ConstVar.__new__(_ConstVar)
            Variable.__init__(v, block, d['name'], d['shape'], d['dtype'],
                              persistable=True)
            v.value = jnp.asarray(payload['consts'][d['name']])
        elif d['is_parameter']:
            v = Parameter(block, d['name'], d['shape'], d['dtype'],
                          trainable=not d['stop_gradient'])
        else:
            v = Variable(block, d['name'], d['shape'], d['dtype'],
                         persistable=d['persistable'],
                         stop_gradient=d['stop_gradient'])
        if d.get('init_from'):
            v._init_from = d['init_from']
        v.op_device = d.get('op_device', '')
        block.vars[d['name']] = v
        if d['persistable'] and not d['is_const']:
            prog.startup_ops.append(v)

    for d in payload['ops']:
        if d['kernel'] is not None:
            fn = _kernel_fn(payload['kernels'][d['kernel']],
                            d['multi_out'])
        elif d.get('fallback') == 'identity':
            fn = lambda x: x                      # noqa: E731
        else:
            fn = lambda: None                     # noqa: E731
        op = Operator(d['type'], fn, d['inputs'], d['outputs'],
                      d['attrs'], op_role=d['op_role'])
        op.op_device = d['op_device']
        op.multi_out = d['multi_out']
        block.append_op(op)

    prog._grad_map = dict(payload['grad_map'])
    prog._has_backward_ops = payload['has_backward_ops']
    if payload.get('lr') is not None:
        prog._loaded_lr = payload['lr']   # Executor lr fallback
    if payload['loss_var'] and payload['loss_var'] in block.vars:
        prog._loss_var = block.vars[payload['loss_var']]
    return prog


# ---- paddle.static.save/load + inference model -----------------------------
def save(program, path_prefix, protocol=4, scope=None, **configs):
    """Parity: paddle.static.save(program, model_path, protocol) —
    program + persistable values. `protocol` accepted for signature
    parity (pickle protocol 4 is always used)."""
    from .executor import global_scope
    scope = scope or global_scope()
    with open(path_prefix + '.pdmodel', 'wb') as f:
        f.write(serialize_program(program))
    state = {}
    for v in program.list_vars():
        if getattr(v, 'persistable', False) and not isinstance(v, _ConstVar):
            arr = scope.find_var(v.name)
            if arr is not None:
                state[v.name] = np.asarray(jax.device_get(arr))
    with open(path_prefix + '.pdiparams', 'wb') as f:
        pickle.dump(state, f, protocol=4)
    return path_prefix


def load(program_or_path, path_prefix=None, executor=None, var_names=None,
         scope=None):
    """Parity: paddle.static.load(program, model_path, executor,
    var_names). `load(path)` -> fresh Program with params staged into the
    scope; `load(program, path)` loads params only. `executor`/`var_names`
    accepted for signature parity."""
    from .executor import global_scope
    if isinstance(program_or_path, str):
        path_prefix, program = program_or_path, None
    else:
        program = program_or_path
    scope = scope or global_scope()
    if program is None:
        with open(path_prefix + '.pdmodel', 'rb') as f:
            program = deserialize_program(f.read())
    with open(path_prefix + '.pdiparams', 'rb') as f:
        state = pickle.load(f)
    for name, arr in state.items():
        scope.set(name, jnp.asarray(arr))
    # loaded values supersede initializers
    program.startup_ops = [v for v in program.startup_ops
                           if v.name not in state]
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, scope=None):
    """Parity: paddle.static.save_inference_model (fluid/io.py) — prunes
    to the forward graph, records feed/fetch targets, saves program +
    params."""
    from .program import default_main_program
    program = program or default_main_program()
    pruned = program.clone(for_test=True)
    feed_names = [v.name if isinstance(v, Variable) else str(v)
                  for v in feed_vars]
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in fetch_vars]
    # prune to the fetch targets' slice (parity: framework/prune.cc via
    # fluid/io.py prepend/append feed-fetch + prune)
    block = pruned.global_block()
    needed = set(fetch_names)
    kept = []
    for op in reversed(block.ops):
        if any(o in needed for o in op.output_names):
            kept.append(op)
            needed.update(op.input_names)
    block.ops = list(reversed(kept))
    # drop vars the pruned slice never touches (training-only state:
    # optimizer accumulators, grads, masters) so the inference artifact
    # carries only what it runs (parity: prune.cc var pruning)
    used = set(feed_names) | set(fetch_names)
    for op in block.ops:
        used.update(op.input_names)
        used.update(op.output_names)
    block.vars = {n: v for n, v in block.vars.items() if n in used}
    pruned.startup_ops = [v for v in pruned.startup_ops
                          if getattr(v, 'name', None) in used]
    pruned._grad_map = {}
    pruned._optimizer = None
    save(pruned, path_prefix, scope=scope)
    with open(path_prefix + '.pdmodel.meta', 'wb') as f:
        pickle.dump({'feed': feed_names, 'fetch': fetch_names}, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, scope=None):
    """Parity: paddle.static.load_inference_model -> (program,
    feed_names, fetch_names)."""
    program = load(path_prefix, scope=scope)
    with open(path_prefix + '.pdmodel.meta', 'rb') as f:
        meta = pickle.load(f)
    return program, meta['feed'], meta['fetch']
