"""Program serialization — the ProgramDesc round-trip.

Reference parity: framework.proto (ProgramDesc:202 / OpDesc:43 /
VarDesc:169) and fluid/io.py save/load_inference_model — a Program saved by
one process is loadable in a fresh process, runnable by the Executor, and
still an editable op-level IR (the distributed rewrites operate on loaded
programs exactly like recorded ones).

TPU-native format: the op table (type, inputs, outputs, attrs, op_role,
op_device) is plain data, and each op's kernel is its jax fn exported as
portable StableHLO (jax.export, cpu+tpu platforms) at the op's recorded
input shapes — the "kernel" the reference looks up by op type at run time
ships with the program instead. Parameters are saved separately
(save/load_inference_model) like the reference's .pdiparams.

Container: a zip holding program.json (data-only op/var tables),
arrays.npz (consts + array-valued attrs, loaded with allow_pickle=False)
and kernels/<i> StableHLO blobs. Like the reference's protobuf
ProgramDesc, NOTHING in a model file is evaluated as code — loading an
untrusted .pdmodel/.pdiparams cannot execute arbitrary Python (the round-2
advisor flagged the earlier pickle container for exactly that).
"""
import io
import json
import zipfile

import numpy as np
import jax
import jax.numpy as jnp
from jax import export as jax_export

from ..core import dtypes
from .program import (Program, Block, Variable, Parameter, Operator,
                      _ConstVar)

FORMAT_VERSION = 3   # v3: nested blocks; v2: data-only zip; v1: pickle
_PLATFORMS = ('cpu', 'tpu')


def _aval_of(v, scope=None):
    """Dynamic dims (None/-1, the paddle dynamic-batch idiom) export as
    jax symbolic dimensions so loaded kernels accept any size there.
    Dynamic dims share a symbol per axis position, matching record_op."""
    if all(d is not None and d >= 0 for d in v.shape):
        return jax.ShapeDtypeStruct(tuple(v.shape), v.dtype)
    parts = [f'_dyn{j}' if d is None or d < 0 else str(d)
             for j, d in enumerate(v.shape)]
    dims = jax_export.symbolic_shape(', '.join(parts), scope=scope)
    return jax.ShapeDtypeStruct(tuple(dims), v.dtype)


def _encode_attr(v, arrays):
    """Attr value -> JSON-safe structure; ndarray payloads go to `arrays`
    (saved in the npz section). Unknown objects degrade to repr — data, not
    code."""
    if isinstance(v, (bool, int, float, str, type(None))):
        return v
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, list):
        return [_encode_attr(x, arrays) for x in v]
    if isinstance(v, tuple):
        return {'__tuple__': [_encode_attr(x, arrays) for x in v]}
    if isinstance(v, dict):
        return {'__dict__': {str(k): _encode_attr(x, arrays)
                             for k, x in v.items()}}
    if hasattr(v, '__array__'):
        arr = np.asarray(v)
        if arr.dtype == object:
            # np.savez would silently pickle object arrays on write while
            # the allow_pickle=False load refuses them — degrade to repr
            # at save time instead of producing an unloadable artifact
            return {'__repr__': repr(v)}
        key = f'attr_{len(arrays)}'
        arrays[key] = arr
        return {'__ndarray__': key}
    return {'__repr__': repr(v)}


def _decode_attr(v, arrays):
    if isinstance(v, list):
        return [_decode_attr(x, arrays) for x in v]
    if isinstance(v, dict):
        if '__tuple__' in v:
            return tuple(_decode_attr(x, arrays) for x in v['__tuple__'])
        if '__dict__' in v:
            return {k: _decode_attr(x, arrays)
                    for k, x in v['__dict__'].items()}
        if '__ndarray__' in v:
            return arrays[v['__ndarray__']]
        if '__repr__' in v:
            return v['__repr__']
    return v


def _safe_attrs(attrs, arrays):
    return {k: _encode_attr(v, arrays) for k, v in (attrs or {}).items()}


def _zip_bytes(entries):
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, 'w', zipfile.ZIP_STORED) as z:
        for name, data in entries.items():
            z.writestr(name, data)
    return buf.getvalue()


def _npz_bytes(arrays):
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _load_npz(data):
    if not data:
        return {}
    loaded = np.load(io.BytesIO(data), allow_pickle=False)
    return {k: loaded[k] for k in loaded.files}


def serialize_program(program):
    """Program -> bytes. Ops whose fn cannot be exported (host-side ops
    like recv_v2) are stored with a named fallback instead of a kernel;
    conditional_block/while ops serialize as block references (parity:
    BlockDesc nesting, framework.proto:178) — their sub-blocks' op kernels
    ship like any others."""
    arrays, kernels, blocks_desc = {}, [], []
    for block in program.blocks:
        vars_desc = []
        for v in block.vars.values():
            d = {'name': v.name, 'shape': list(v.shape),
                 'dtype': dtypes.dtype_name(v.dtype),
                 'persistable': bool(getattr(v, 'persistable', False)),
                 'stop_gradient': bool(getattr(v, 'stop_gradient', True)),
                 'is_parameter': isinstance(v, Parameter),
                 'op_device': getattr(v, 'op_device', ''),
                 'init_from': getattr(v, '_init_from', None),
                 'is_const': isinstance(v, _ConstVar)}
            if isinstance(v, _ConstVar):
                arrays['const:' + v.name] = np.asarray(
                    jax.device_get(v.value))
            vars_desc.append(d)

        ops_desc = []
        for op in block.ops:
            desc = {'type': op.type, 'inputs': list(op.input_names),
                    'outputs': list(op.output_names),
                    'attrs': _safe_attrs(op.attrs, arrays),
                    'op_role': op.op_role, 'op_device': op.op_device,
                    'multi_out': bool(getattr(op, 'multi_out', False)),
                    'kernel': None}
            if op.type in ('conditional_block', 'while'):
                desc['fallback'] = 'control_flow'
            elif op.type == 'recv_v2':
                desc['fallback'] = 'none'
            elif op.type == 'send_v2':
                desc['fallback'] = 'identity'
            else:
                sym_scope = jax_export.SymbolicScope()
                avals = [_aval_of(block._find_var_recursive(n), sym_scope)
                         for n in op.input_names]
                exported = jax_export.export(
                    jax.jit(op.fn), platforms=list(_PLATFORMS))(*avals)
                desc['kernel'] = len(kernels)
                kernels.append(exported.serialize())
            ops_desc.append(desc)
        blocks_desc.append({'idx': block.idx,
                            'parent_idx': getattr(block, 'parent_idx', -1),
                            'vars': vars_desc, 'ops': ops_desc})

    payload = {
        'version': FORMAT_VERSION,
        'blocks': blocks_desc,
        'n_kernels': len(kernels),
        'grad_map': dict(program._grad_map),
        'loss_var': program._loss_var.name
        if program._loss_var is not None else None,
        'has_backward_ops': bool(getattr(program, '_has_backward_ops',
                                         False)),
        'lr': (float(program._optimizer.get_lr())
               if getattr(program, '_optimizer', None) is not None
               else None),
    }
    entries = {'program.json': json.dumps(payload)}
    if arrays:
        entries['arrays.npz'] = _npz_bytes(arrays)
    for i, blob in enumerate(kernels):
        entries[f'kernels/{i}'] = blob
    return _zip_bytes(entries)


def _kernel_fn(blob, multi_out):
    exported = jax_export.deserialize(blob)

    def fn(*xs):
        out = exported.call(*xs)
        # jax.export flattens single outputs into a 1-tuple
        if not multi_out and isinstance(out, (tuple, list)) \
                and len(out) == 1:
            return out[0]
        return tuple(out) if isinstance(out, (tuple, list)) else out
    return fn


def deserialize_program(data):
    """bytes -> Program (editable, Executor-runnable). Data-only: json +
    npz + StableHLO; no code is evaluated from the file."""
    try:
        zf = zipfile.ZipFile(io.BytesIO(data))
    except zipfile.BadZipFile:
        raise ValueError(
            "not a paddle_tpu program container (v2+ is a zip; "
            "v1 pickle-era files are no longer loadable)")
    with zf as z:
        payload = json.loads(z.read('program.json'))
        if payload.get('version') != FORMAT_VERSION:
            raise ValueError(f"program format {payload.get('version')} "
                             f"(expected {FORMAT_VERSION})")
        names = set(z.namelist())
        arrays = _load_npz(z.read('arrays.npz')
                           if 'arrays.npz' in names else b'')
        kernels = [z.read(f'kernels/{i}')
                   for i in range(payload['n_kernels'])]
    prog = Program()
    from .program import Block
    attr_arrays = {k: v for k, v in arrays.items()
                   if not k.startswith('const:')}
    for bd in payload['blocks']:
        if bd['idx'] == 0:
            block = prog.global_block()
        else:
            block = Block(prog, bd['idx'], parent_idx=bd['parent_idx'])
            prog.blocks.append(block)
        for d in bd['vars']:
            if d['is_const']:
                v = _ConstVar.__new__(_ConstVar)
                Variable.__init__(v, block, d['name'], d['shape'],
                                  d['dtype'], persistable=True)
                v.value = jnp.asarray(arrays['const:' + d['name']])
            elif d['is_parameter']:
                v = Parameter(block, d['name'], d['shape'], d['dtype'],
                              trainable=not d['stop_gradient'])
            else:
                v = Variable(block, d['name'], d['shape'], d['dtype'],
                             persistable=d['persistable'],
                             stop_gradient=d['stop_gradient'])
            if d.get('init_from'):
                v._init_from = d['init_from']
            v.op_device = d.get('op_device', '')
            block.vars[d['name']] = v
            if d['persistable'] and not d['is_const']:
                prog.startup_ops.append(v)

        for d in bd['ops']:
            d['attrs'] = {k: _decode_attr(v, attr_arrays)
                          for k, v in d.get('attrs', {}).items()}
            if d['kernel'] is not None:
                fn = _kernel_fn(kernels[d['kernel']],
                                d['multi_out'])
            elif d.get('fallback') == 'control_flow':
                fn = None       # executed via sub-block replay
            elif d.get('fallback') == 'identity':
                fn = lambda x: x                      # noqa: E731
            else:
                fn = lambda: None                     # noqa: E731
            op = Operator(d['type'], fn, d['inputs'], d['outputs'],
                          d['attrs'], op_role=d['op_role'])
            op.op_device = d['op_device']
            op.multi_out = d['multi_out']
            block.append_op(op)

    prog._grad_map = dict(payload['grad_map'])
    prog._has_backward_ops = payload['has_backward_ops']
    if payload.get('lr') is not None:
        prog._loaded_lr = payload['lr']   # Executor lr fallback
    if payload['loss_var'] and payload['loss_var'] in block.vars:
        prog._loss_var = block.vars[payload['loss_var']]
    return prog


# ---- paddle.static.save/load + inference model -----------------------------
def save(program, path_prefix, protocol=4, scope=None, **configs):
    """Parity: paddle.static.save(program, model_path, protocol) —
    program + persistable values. `protocol` accepted for signature
    parity only: the format is the data-only zip/npz container, not
    pickle."""
    from .executor import global_scope
    scope = scope or global_scope()
    with open(path_prefix + '.pdmodel', 'wb') as f:
        f.write(serialize_program(program))
    state = {}
    for v in program.list_vars():
        if getattr(v, 'persistable', False) and not isinstance(v, _ConstVar):
            arr = scope.find_var(v.name)
            if arr is not None:
                state[v.name] = np.asarray(jax.device_get(arr))
    with open(path_prefix + '.pdiparams', 'wb') as f:
        f.write(_npz_bytes(state))          # data-only (npz)
    return path_prefix


def load(program_or_path, path_prefix=None, executor=None, var_names=None,
         scope=None):
    """Parity: paddle.static.load(program, model_path, executor,
    var_names). `load(path)` -> fresh Program with params staged into the
    scope; `load(program, path)` loads params only. `executor`/`var_names`
    accepted for signature parity."""
    from .executor import global_scope
    if isinstance(program_or_path, str):
        path_prefix, program = program_or_path, None
    else:
        program = program_or_path
    scope = scope or global_scope()
    if program is None:
        with open(path_prefix + '.pdmodel', 'rb') as f:
            program = deserialize_program(f.read())
    with open(path_prefix + '.pdiparams', 'rb') as f:
        state = _load_npz(f.read())
    for name, arr in state.items():
        scope.set(name, jnp.asarray(arr))
    # loaded values supersede initializers
    program.startup_ops = [v for v in program.startup_ops
                           if v.name not in state]
    return program


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, scope=None):
    """Parity: paddle.static.save_inference_model (fluid/io.py) — prunes
    to the forward graph, records feed/fetch targets, saves program +
    params."""
    from .program import default_main_program
    program = program or default_main_program()
    pruned = program.clone(for_test=True)
    feed_names = [v.name if isinstance(v, Variable) else str(v)
                  for v in feed_vars]
    fetch_names = [v.name if isinstance(v, Variable) else str(v)
                   for v in fetch_vars]
    # prune to the fetch targets' slice (parity: framework/prune.cc via
    # fluid/io.py prepend/append feed-fetch + prune)
    block = pruned.global_block()
    needed = set(fetch_names)
    kept = []
    for op in reversed(block.ops):
        if any(o in needed for o in op.output_names):
            kept.append(op)
            needed.update(op.input_names)
    block.ops = list(reversed(kept))
    # drop vars the pruned slice never touches (training-only state:
    # optimizer accumulators, grads, masters) so the inference artifact
    # carries only what it runs (parity: prune.cc var pruning)
    used = set(feed_names) | set(fetch_names)
    for op in block.ops:
        used.update(op.input_names)
        used.update(op.output_names)
    block.vars = {n: v for n, v in block.vars.items() if n in used}
    pruned.startup_ops = [v for v in pruned.startup_ops
                          if getattr(v, 'name', None) in used]
    pruned._grad_map = {}
    pruned._optimizer = None
    save(pruned, path_prefix, scope=scope)
    with open(path_prefix + '.pdmodel.meta', 'w') as f:
        json.dump({'feed': feed_names, 'fetch': fetch_names}, f)
    return path_prefix


def load_inference_model(path_prefix, executor=None, scope=None):
    """Parity: paddle.static.load_inference_model -> (program,
    feed_names, fetch_names)."""
    program = load(path_prefix, scope=scope)
    with open(path_prefix + '.pdmodel.meta') as f:
        meta = json.load(f)
    return program, meta['feed'], meta['fetch']
