"""fluid.layers remainder: legacy-signature wrappers over the modern op
surface (reference: python/paddle/fluid/layers/{nn,tensor,control_flow,
learning_rate_scheduler,detection}.py __all__ sheet).

Every name here is a THIN adapter: the compute lives in the shared op
layer (ops/, nn/functional), so these record into static programs and
run eagerly alike. LoD/SelectedRows-specific names are deliberately
absent (SURVEY N11 disposition: dense padded tensors + lengths).
"""
import numpy as np

from ..core.tensor import Tensor
from ..ops.common import as_tensor
from .. import nn as _nn
from ..nn import functional as F
from ..ops import math as M
from ..ops import manip as _manip
from ..ops import creation as _cr
from ..ops import contrib as _contrib
from ..ops import sequence as _seq


def rank(input):
    """fluid.layers.rank — the tensor's number of dimensions as a
    0-D int32 tensor."""
    import jax.numpy as jnp
    return Tensor(jnp.asarray(len(as_tensor(input).shape), jnp.int32))


def is_empty(x, name=None):
    """fluid.layers.is_empty (operators/is_empty_op.cc)."""
    import jax.numpy as jnp
    return Tensor(jnp.asarray(int(np.prod(as_tensor(x).shape)) == 0))


def reverse(x, axis):
    """fluid.layers.reverse (operators/reverse_op.cc) → flip."""
    if isinstance(axis, int):
        axis = [axis]
    return _manip.flip(x, axis)


def crop_tensor(x, shape=None, offsets=None, name=None):
    """fluid.layers.crop_tensor (operators/crop_tensor_op.cc)."""
    return M.crop(x, shape=shape, offsets=offsets)


def pad2d(input, paddings=(0, 0, 0, 0), mode='constant', pad_value=0.0,
          data_format='NCHW', name=None):
    """fluid.layers.pad2d (operators/pad2d_op.cc): paddings
    [top, bottom, left, right] on the spatial dims."""
    t, b, l, r = [int(p) for p in paddings]
    if data_format == 'NCHW':
        pad = [0, 0, 0, 0, t, b, l, r]
    else:
        pad = [0, 0, t, b, l, r, 0, 0]
    mode_map = {'constant': 'constant', 'reflect': 'reflect',
                'edge': 'replicate'}
    return F.pad(input, pad, mode=mode_map[mode], value=pad_value)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """fluid.layers.pad_constant_like (operators/pad_constant_like_op.cc):
    pad y at the tail of every dim up to x's shape."""
    xs, ys = as_tensor(x).shape, as_tensor(y).shape
    pad = []
    for dx, dy in zip(xs, ys):
        pad += [0, int(dx) - int(dy)]
    return F.pad(y, pad, mode='constant', value=pad_value)


def adaptive_pool2d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    """fluid.layers.adaptive_pool2d."""
    if pool_type == 'max':
        if require_index:
            return F.adaptive_max_pool2d(input, pool_size,
                                         return_mask=True)
        return F.adaptive_max_pool2d(input, pool_size)
    return F.adaptive_avg_pool2d(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    """fluid.layers.adaptive_pool3d — [N, C, D, H, W]: fold depth into
    the batch, reuse the 2-D kernel per depth slice, then pool depth."""
    x = as_tensor(input)
    if isinstance(pool_size, int):
        pool_size = [pool_size] * 3
    N, C, D, H, W = [int(d) for d in x.shape]
    od, oh, ow = [int(p) for p in pool_size]
    xf = _manip.reshape(x, [N * C, D, H, W])
    # adaptive over (H, W) per depth slice
    xf = _manip.reshape(xf, [N * C * D, 1, H, W])
    hw = (F.adaptive_max_pool2d(xf, [oh, ow]) if pool_type == 'max'
          else F.adaptive_avg_pool2d(xf, [oh, ow]))
    hw = _manip.reshape(hw, [N * C, D, oh * ow])
    hw = _manip.transpose(hw, [0, 2, 1])
    hw = _manip.reshape(hw, [N * C * oh * ow, 1, D, 1])
    d = (F.adaptive_max_pool2d(hw, [od, 1]) if pool_type == 'max'
         else F.adaptive_avg_pool2d(hw, [od, 1]))
    d = _manip.reshape(d, [N * C, oh, ow, od])
    d = _manip.transpose(d, [0, 3, 1, 2])
    return _manip.reshape(d, [N, C, od, oh, ow])


def pool3d(input, pool_size=-1, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format='NCDHW', name=None):
    """fluid.layers.pool3d (operators/pool_op.cc 3-D path)."""
    x = as_tensor(input)
    if global_pooling:
        axes = [2, 3, 4] if data_format == 'NCDHW' else [1, 2, 3]
        return (M.max(x, axis=axes, keepdim=True) if pool_type == 'max'
                else M.mean(x, axis=axes, keepdim=True))
    if pool_type == 'max':
        return F.max_pool3d(x, pool_size, stride=pool_stride,
                            padding=pool_padding, ceil_mode=ceil_mode) \
            if hasattr(F, 'max_pool3d') else _pool3d_generic(
                x, pool_size, pool_stride, pool_padding, 'max',
                ceil_mode, exclusive)
    return _pool3d_generic(x, pool_size, pool_stride, pool_padding,
                           'avg', ceil_mode, exclusive)


def _pool3d_generic(x, ksize, stride, padding, kind, ceil_mode,
                    exclusive):
    import jax
    import jax.numpy as jnp
    from ..core.autograd import run_op
    if isinstance(ksize, int):
        ksize = [ksize] * 3
    if isinstance(stride, int):
        stride = [stride] * 3
    if isinstance(padding, int):
        padding = [padding] * 3

    def fn(a):
        dims = (1, 1) + tuple(ksize)
        strides = (1, 1) + tuple(stride)
        spatial = a.shape[2:]
        hi = []
        for d, k, st, p in zip(spatial, ksize, stride, padding):
            if ceil_mode:
                out = -(-(d + 2 * p - k) // st) + 1     # ceil
                need = (out - 1) * st + k - d - p
                hi.append(max(int(need), p))
            else:
                hi.append(p)
        pads = ((0, 0), (0, 0)) + tuple(
            (p, h) for p, h in zip(padding, hi))
        if kind == 'max':
            return jax.lax.reduce_window(
                a, -jnp.inf, jax.lax.max, dims, strides, pads)
        s = jax.lax.reduce_window(a, 0.0, jax.lax.add, dims, strides,
                                  pads)
        if exclusive and any(padding):
            ones = jnp.ones_like(a)
            cnt = jax.lax.reduce_window(ones, 0.0, jax.lax.add, dims,
                                        strides, pads)
            return s / jnp.maximum(cnt, 1.0)
        return s / float(np.prod(ksize))
    return run_op('pool3d', fn, [x])


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format='NCHW'):
    """fluid.layers.lrn (operators/lrn_op.cc) → local_response_norm
    (this backend's impl already uses the fluid alpha*sum convention —
    no /n — so alpha passes straight through)."""
    return F.local_response_norm(input, size=n, alpha=alpha,
                                 beta=beta, k=k,
                                 data_format=data_format)


def grid_sampler(x, grid, name=None):
    """fluid.layers.grid_sampler → F.grid_sample."""
    return F.grid_sample(x, grid)


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """fluid.layers.warpctc (operators/warpctc_op.cc) → F.ctc_loss.
    input [T, B, C] logits (or [B, T, C] with lengths, per the modern
    contract)."""
    return F.ctc_loss(input, label, input_length, label_length,
                      blank=blank, reduction='none')


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """fluid.layers.ctc_greedy_decoder: argmax per step, collapse
    repeats, drop blanks (ctc_align)."""
    probs = as_tensor(input)
    ids = M.argmax(probs, axis=-1)
    out, lens = _contrib.ctc_align(ids, blank=blank,
                                   lengths=input_length,
                                   padding_value=padding_value)
    return out, lens


def unique_with_counts(x, dtype='int32'):
    """fluid.layers.unique_with_counts (operators/unique_with_counts_op
    .cc): returns (unique values, index map, counts)."""
    out, inverse, counts = _manip.unique(
        x, return_inverse=True, return_counts=True)
    return out, _manip.cast(inverse, dtype), _manip.cast(counts, dtype)


def uniform_random_batch_size_like(input, shape, dtype='float32',
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """fluid.layers.uniform_random_batch_size_like."""
    shape = list(shape)
    shape[output_dim_idx] = int(
        as_tensor(input).shape[input_dim_idx])
    return _cr.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype='float32'):
    """fluid.layers.gaussian_random_batch_size_like."""
    shape = list(shape)
    shape[output_dim_idx] = int(
        as_tensor(input).shape[input_dim_idx])
    if seed:
        import jax
        import jax.numpy as jnp
        key = jax.random.key(int(seed))
        return Tensor(jax.random.normal(
            key, tuple(shape), jnp.dtype(dtype)) * std + mean)
    return _cr.gaussian(shape, mean=mean, std=std, dtype=dtype)


def inplace_abn(input, act=None, **bn_kwargs):
    """fluid.layers.inplace_abn (operators/inplace_abn_op.cc): fused
    BN + activation. XLA fuses these anyway and buffers are immutable,
    so this is batch_norm + act — same math, no aliasing."""
    out = F.batch_norm(input, **bn_kwargs) if bn_kwargs else \
        _nn.BatchNorm2D(int(as_tensor(input).shape[1]))(input)
    if act:
        out = getattr(F, act)(out)
    return out


def similarity_focus(input, axis, indexes, name=None):
    """similarity_focus_op.cc: build a focus mask — select slices along
    `axis` (1, 2, or 3 of the 4-D input) at `indexes`; in each selected
    slice mark the max position per row and per column; broadcast the
    union mask back over the selected axis."""
    import jax.numpy as jnp
    from ..core.autograd import run_op
    x = as_tensor(input)
    if axis not in (1, 2, 3):
        raise ValueError(f"similarity_focus axis must be 1, 2 or 3, "
                         f"got {axis}")
    dim = int(x.shape[axis])
    bad = [i for i in indexes if not 0 <= int(i) < dim]
    if bad:
        raise ValueError(f"similarity_focus indexes {bad} out of range "
                         f"for axis {axis} (size {dim})")

    def fn(a):
        # move the selected axis to position 1; rows/cols are the two
        # remaining trailing dims
        perm = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
        at = a.transpose(perm)
        N = at.shape[0]
        H, W = at.shape[2], at.shape[3]
        sel = at[:, jnp.asarray(indexes)]

        def one_image(img_sel):
            m = jnp.zeros((H, W), a.dtype)
            for k in range(len(indexes)):
                fm = img_sel[k]
                row_best = jnp.argmax(fm, axis=1)      # per row
                col_best = jnp.argmax(fm, axis=0)      # per col
                m = m.at[jnp.arange(H), row_best].set(1.0)
                m = m.at[col_best, jnp.arange(W)].set(1.0)
            return m
        masks = jnp.stack([one_image(sel[i]) for i in range(N)])
        full = jnp.broadcast_to(masks[:, None], at.shape)
        inv = tuple(np.argsort(perm))
        return full.transpose(inv)
    return run_op('similarity_focus', fn, [x])


# -- learning-rate decay bridge (fluid.layers.learning_rate_scheduler) --
# The fluid decay fns appended lr-computation ops to the startup
# program; under the one-jit Executor the schedule lives host-side in
# the optimizer, so each returns the MODERN scheduler object preloaded
# with the same formula (optimizer.set_lr_scheduler consumes it).

def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from ..optimizer import lr as _lr
    return _lr.NoamDecay(d_model, warmup_steps,
                         learning_rate=learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr as _lr

    def fn(epoch):
        e = (epoch // decay_steps) if staircase else (epoch
                                                     / decay_steps)
        return decay_rate ** e
    return _lr.LambdaDecay(learning_rate, fn)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr as _lr

    def fn(epoch):
        e = (epoch // decay_steps) if staircase else (epoch
                                                     / decay_steps)
        return float(np.exp(-decay_rate * e))
    return _lr.LambdaDecay(learning_rate, fn)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from ..optimizer import lr as _lr

    def fn(epoch):
        e = (epoch // decay_steps) if staircase else (epoch
                                                     / decay_steps)
        return 1.0 / (1.0 + decay_rate * e)
    return _lr.LambdaDecay(learning_rate, fn)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    from ..optimizer import lr as _lr
    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_lr=end_learning_rate, power=power,
                               cycle=cycle)


def piecewise_decay(boundaries, values):
    from ..optimizer import lr as _lr
    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from ..optimizer import lr as _lr
    return _lr.CosineAnnealingDecay(learning_rate,
                                    T_max=step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from ..optimizer import lr as _lr
    return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr,
                            end_lr)


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """fluid.layers.rnn — functional driver over a cell (rnn.py:~440)."""
    runner = _nn.RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return runner(inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    """fluid.layers.birnn — bidirectional functional driver."""
    runner = _nn.BiRNN(cell_fw, cell_bw, time_major=time_major)
    return runner(inputs, initial_states, sequence_length)
