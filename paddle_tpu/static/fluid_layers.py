"""fluid.layers remainder: legacy-signature wrappers over the modern op
surface (reference: python/paddle/fluid/layers/{nn,tensor,control_flow,
learning_rate_scheduler,detection}.py __all__ sheet).

Every name here is a THIN adapter: the compute lives in the shared op
layer (ops/, nn/functional), so these record into static programs and
run eagerly alike. LoD/SelectedRows-specific names are deliberately
absent (SURVEY N11 disposition: dense padded tensors + lengths).
"""
import numpy as np

from ..core.tensor import Tensor
from ..ops.common import as_tensor
from .. import nn as _nn
from ..nn import functional as F
from ..ops import math as M
from ..ops import manip as _manip
from ..ops import creation as _cr
from ..ops import contrib as _contrib
from ..ops import sequence as _seq


def rank(input):
    """fluid.layers.rank — the tensor's number of dimensions as a
    0-D int32 tensor."""
    import jax.numpy as jnp
    return Tensor(jnp.asarray(len(as_tensor(input).shape), jnp.int32))


def is_empty(x, name=None):
    """fluid.layers.is_empty (operators/is_empty_op.cc)."""
    import jax.numpy as jnp
    return Tensor(jnp.asarray(int(np.prod(as_tensor(x).shape)) == 0))


def reverse(x, axis):
    """fluid.layers.reverse (operators/reverse_op.cc) → flip."""
    if isinstance(axis, int):
        axis = [axis]
    return _manip.flip(x, axis)


def crop_tensor(x, shape=None, offsets=None, name=None):
    """fluid.layers.crop_tensor (operators/crop_tensor_op.cc)."""
    return M.crop(x, shape=shape, offsets=offsets)


def pad2d(input, paddings=(0, 0, 0, 0), mode='constant', pad_value=0.0,
          data_format='NCHW', name=None):
    """fluid.layers.pad2d (operators/pad2d_op.cc): paddings
    [top, bottom, left, right] on the spatial dims."""
    t, b, l, r = [int(p) for p in paddings]
    if data_format == 'NCHW':
        pad = [0, 0, 0, 0, t, b, l, r]
    else:
        pad = [0, 0, t, b, l, r, 0, 0]
    mode_map = {'constant': 'constant', 'reflect': 'reflect',
                'edge': 'replicate'}
    return F.pad(input, pad, mode=mode_map[mode], value=pad_value)


def pad_constant_like(x, y, pad_value=0.0, name=None):
    """fluid.layers.pad_constant_like (operators/pad_constant_like_op.cc):
    pad y at the tail of every dim up to x's shape."""
    xs, ys = as_tensor(x).shape, as_tensor(y).shape
    pad = []
    for dx, dy in zip(xs, ys):
        pad += [0, int(dx) - int(dy)]
    return F.pad(y, pad, mode='constant', value=pad_value)


def adaptive_pool2d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    """fluid.layers.adaptive_pool2d."""
    if pool_type == 'max':
        if require_index:
            return F.adaptive_max_pool2d(input, pool_size,
                                         return_mask=True)
        return F.adaptive_max_pool2d(input, pool_size)
    return F.adaptive_avg_pool2d(input, pool_size)


def adaptive_pool3d(input, pool_size, pool_type='max', require_index=False,
                    name=None):
    """fluid.layers.adaptive_pool3d — [N, C, D, H, W]: fold depth into
    the batch, reuse the 2-D kernel per depth slice, then pool depth."""
    x = as_tensor(input)
    if isinstance(pool_size, int):
        pool_size = [pool_size] * 3
    N, C, D, H, W = [int(d) for d in x.shape]
    od, oh, ow = [int(p) for p in pool_size]
    xf = _manip.reshape(x, [N * C, D, H, W])
    # adaptive over (H, W) per depth slice
    xf = _manip.reshape(xf, [N * C * D, 1, H, W])
    hw = (F.adaptive_max_pool2d(xf, [oh, ow]) if pool_type == 'max'
          else F.adaptive_avg_pool2d(xf, [oh, ow]))
    hw = _manip.reshape(hw, [N * C, D, oh * ow])
    hw = _manip.transpose(hw, [0, 2, 1])
    hw = _manip.reshape(hw, [N * C * oh * ow, 1, D, 1])
    d = (F.adaptive_max_pool2d(hw, [od, 1]) if pool_type == 'max'
         else F.adaptive_avg_pool2d(hw, [od, 1]))
    d = _manip.reshape(d, [N * C, oh, ow, od])
    d = _manip.transpose(d, [0, 3, 1, 2])
    return _manip.reshape(d, [N, C, od, oh, ow])


def pool3d(input, pool_size=-1, pool_type='max', pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, data_format='NCDHW', name=None):
    """fluid.layers.pool3d (operators/pool_op.cc 3-D path)."""
    x = as_tensor(input)
    if global_pooling:
        axes = [2, 3, 4] if data_format == 'NCDHW' else [1, 2, 3]
        return (M.max(x, axis=axes, keepdim=True) if pool_type == 'max'
                else M.mean(x, axis=axes, keepdim=True))
    if pool_type == 'max':
        return F.max_pool3d(x, pool_size, stride=pool_stride,
                            padding=pool_padding, ceil_mode=ceil_mode) \
            if hasattr(F, 'max_pool3d') else _pool3d_generic(
                x, pool_size, pool_stride, pool_padding, 'max',
                ceil_mode, exclusive)
    return _pool3d_generic(x, pool_size, pool_stride, pool_padding,
                           'avg', ceil_mode, exclusive)


def _pool3d_generic(x, ksize, stride, padding, kind, ceil_mode,
                    exclusive):
    """Delegates to the shared reduce_window pooling helper
    (ops/nn_ops.py _pool_nd) — one implementation for every N-D pool."""
    from ..ops.nn_ops import _pool_nd
    return _pool_nd(x, 3, ksize, stride, padding, kind, ceil_mode,
                    exclusive)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format='NCHW'):
    """fluid.layers.lrn (operators/lrn_op.cc) → local_response_norm
    (this backend's impl already uses the fluid alpha*sum convention —
    no /n — so alpha passes straight through)."""
    return F.local_response_norm(input, size=n, alpha=alpha,
                                 beta=beta, k=k,
                                 data_format=data_format)


def grid_sampler(x, grid, name=None):
    """fluid.layers.grid_sampler → F.grid_sample."""
    return F.grid_sample(x, grid)


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """fluid.layers.warpctc (operators/warpctc_op.cc) → F.ctc_loss.
    input [T, B, C] logits (or [B, T, C] with lengths, per the modern
    contract)."""
    return F.ctc_loss(input, label, input_length, label_length,
                      blank=blank, reduction='none')


def ctc_greedy_decoder(input, blank, input_length=None, padding_value=0,
                       name=None):
    """fluid.layers.ctc_greedy_decoder: argmax per step, collapse
    repeats, drop blanks (ctc_align)."""
    probs = as_tensor(input)
    ids = M.argmax(probs, axis=-1)
    out, lens = _contrib.ctc_align(ids, blank=blank,
                                   lengths=input_length,
                                   padding_value=padding_value)
    return out, lens


def unique_with_counts(x, dtype='int32'):
    """fluid.layers.unique_with_counts (operators/unique_with_counts_op
    .cc): returns (unique values, index map, counts)."""
    out, inverse, counts = _manip.unique(
        x, return_inverse=True, return_counts=True)
    return out, _manip.cast(inverse, dtype), _manip.cast(counts, dtype)


def uniform_random_batch_size_like(input, shape, dtype='float32',
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    """fluid.layers.uniform_random_batch_size_like."""
    shape = list(shape)
    shape[output_dim_idx] = int(
        as_tensor(input).shape[input_dim_idx])
    return _cr.uniform(shape, dtype=dtype, min=min, max=max, seed=seed)


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype='float32'):
    """fluid.layers.gaussian_random_batch_size_like."""
    shape = list(shape)
    shape[output_dim_idx] = int(
        as_tensor(input).shape[input_dim_idx])
    if seed:
        import jax
        import jax.numpy as jnp
        key = jax.random.key(int(seed))
        return Tensor(jax.random.normal(
            key, tuple(shape), jnp.dtype(dtype)) * std + mean)
    return _cr.gaussian(shape, mean=mean, std=std, dtype=dtype)


def inplace_abn(input, act=None, **bn_kwargs):
    """fluid.layers.inplace_abn (operators/inplace_abn_op.cc): fused
    BN + activation. XLA fuses these anyway and buffers are immutable,
    so this is batch_norm + act — same math, no aliasing."""
    out = F.batch_norm(input, **bn_kwargs) if bn_kwargs else \
        _nn.BatchNorm2D(int(as_tensor(input).shape[1]))(input)
    if act:
        out = getattr(F, act)(out)
    return out


def similarity_focus(input, axis, indexes, name=None):
    """similarity_focus_op.cc: build a focus mask — select slices along
    `axis` (1, 2, or 3 of the 4-D input) at `indexes`; in each selected
    slice mark the max position per row and per column; broadcast the
    union mask back over the selected axis."""
    import jax.numpy as jnp
    from ..core.autograd import run_op
    x = as_tensor(input)
    if axis not in (1, 2, 3):
        raise ValueError(f"similarity_focus axis must be 1, 2 or 3, "
                         f"got {axis}")
    dim = int(x.shape[axis])
    bad = [i for i in indexes if not 0 <= int(i) < dim]
    if bad:
        raise ValueError(f"similarity_focus indexes {bad} out of range "
                         f"for axis {axis} (size {dim})")

    def fn(a):
        # move the selected axis to position 1; rows/cols are the two
        # remaining trailing dims
        perm = {1: (0, 1, 2, 3), 2: (0, 2, 1, 3), 3: (0, 3, 1, 2)}[axis]
        at = a.transpose(perm)
        N = at.shape[0]
        H, W = at.shape[2], at.shape[3]
        sel = at[:, jnp.asarray(indexes)]

        def one_image(img_sel):
            m = jnp.zeros((H, W), a.dtype)
            for k in range(len(indexes)):
                fm = img_sel[k]
                row_best = jnp.argmax(fm, axis=1)      # per row
                col_best = jnp.argmax(fm, axis=0)      # per col
                m = m.at[jnp.arange(H), row_best].set(1.0)
                m = m.at[col_best, jnp.arange(W)].set(1.0)
            return m
        masks = jnp.stack([one_image(sel[i]) for i in range(N)])
        full = jnp.broadcast_to(masks[:, None], at.shape)
        inv = tuple(np.argsort(perm))
        return full.transpose(inv)
    return run_op('similarity_focus', fn, [x])


# -- learning-rate decay bridge (fluid.layers.learning_rate_scheduler) --
# The fluid decay fns appended lr-computation ops to the startup
# program; under the one-jit Executor the schedule lives host-side in
# the optimizer, so each returns the MODERN scheduler object preloaded
# with the same formula (optimizer.set_lr_scheduler consumes it).

def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    from ..optimizer import lr as _lr
    return _lr.NoamDecay(d_model, warmup_steps,
                         learning_rate=learning_rate)


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr as _lr

    def fn(epoch):
        e = (epoch // decay_steps) if staircase else (epoch
                                                     / decay_steps)
        return decay_rate ** e
    return _lr.LambdaDecay(learning_rate, fn)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    from ..optimizer import lr as _lr

    def fn(epoch):
        e = (epoch // decay_steps) if staircase else (epoch
                                                     / decay_steps)
        return float(np.exp(-decay_rate * e))
    return _lr.LambdaDecay(learning_rate, fn)


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    from ..optimizer import lr as _lr

    def fn(epoch):
        e = (epoch // decay_steps) if staircase else (epoch
                                                     / decay_steps)
        return 1.0 / (1.0 + decay_rate * e)
    return _lr.LambdaDecay(learning_rate, fn)


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=1e-4,
                     power=1.0, cycle=False):
    from ..optimizer import lr as _lr
    return _lr.PolynomialDecay(learning_rate, decay_steps,
                               end_lr=end_learning_rate, power=power,
                               cycle=cycle)


def piecewise_decay(boundaries, values):
    from ..optimizer import lr as _lr
    return _lr.PiecewiseDecay(boundaries, values)


def cosine_decay(learning_rate, step_each_epoch, epochs):
    from ..optimizer import lr as _lr
    return _lr.CosineAnnealingDecay(learning_rate,
                                    T_max=step_each_epoch * epochs)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    from ..optimizer import lr as _lr
    return _lr.LinearWarmup(learning_rate, warmup_steps, start_lr,
                            end_lr)


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """fluid.layers.rnn — functional driver over a cell (rnn.py:~440)."""
    runner = _nn.RNN(cell, is_reverse=is_reverse, time_major=time_major)
    return runner(inputs, initial_states, sequence_length)


def birnn(cell_fw, cell_bw, inputs, initial_states=None,
          sequence_length=None, time_major=False, **kwargs):
    """fluid.layers.birnn — bidirectional functional driver."""
    runner = _nn.BiRNN(cell_fw, cell_bw, time_major=time_major)
    return runner(inputs, initial_states, sequence_length)


# ---------------------------------------------------------------------------
# remaining fluid.layers tail (wave 3)
# ---------------------------------------------------------------------------

def _mode_param(shape, dtype='float32'):
    """Create a parameter in whichever mode is active: a Program
    parameter under enable_static, an eagerly-initialized Tensor
    otherwise (Xavier-uniform like _make_param's default)."""
    from ..core.autograd import STATIC_RECORD_HOOK
    if STATIC_RECORD_HOOK is not None:
        from .nn import _make_param
        return _make_param(list(shape), dtype)
    import jax
    from ..core import rng as rng_mod
    fan_in = int(np.prod(shape[:-1])) or 1
    fan_out = int(shape[-1])
    limit = float(np.sqrt(6.0 / (fan_in + fan_out)))
    key = rng_mod.next_key()
    import jax.numpy as jnp
    t = Tensor(jax.random.uniform(key, tuple(int(d) for d in shape),
                                  jnp.dtype(dtype), -limit, limit))
    t.stop_gradient = False
    return t


def conv3d_transpose(input, num_filters, output_size=None, filter_size=None,
                     padding=0, stride=1, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, use_cudnn=True,
                     act=None, name=None, data_format='NCDHW'):
    """fluid.layers.conv3d_transpose — creates the IODHW weight/bias
    params and delegates to the shared functional kernel
    (ops/nn_ops.py conv3d_transpose, the single transpose-conv
    implementation)."""
    from ..ops.nn_ops import conv3d_transpose as _f_conv3dt
    x = as_tensor(input)
    cin = int(x.shape[1])
    if isinstance(filter_size, int):
        filter_size = [filter_size] * 3
    dt = str(x.dtype)
    w = _mode_param([cin, num_filters // groups] + list(filter_size), dt)
    b = None
    if bias_attr is not False:
        b = _mode_param([num_filters], dt)
    out = _f_conv3dt(x, w, b, stride=stride, padding=padding,
                     groups=groups, dilation=dilation,
                     output_size=output_size)
    if act:
        out = getattr(F, act)(out)
    return out


def _resize_nd(input, out_shape, scale, mode, align_corners,
               data_format):
    """1-D / 3-D separable linear interpolation with BOTH coordinate
    conventions: align_corners=True maps output i to i*(in-1)/(out-1)
    (the fluid default); False uses the half-pixel convention. Each
    spatial axis is one gather+lerp — XLA fuses the chain."""
    import jax.numpy as jnp
    from ..core.autograd import run_op
    x = as_tensor(input)
    nd = len(x.shape) - 2
    if out_shape is None:
        sf = scale if isinstance(scale, (list, tuple)) else [scale] * nd
        out_shape = [int(int(d) * s)
                     for d, s in zip(x.shape[2:], sf)]
    out_shape = [int(v) for v in out_shape]

    def fn(a):
        out = a
        for ax in range(nd):
            axis = 2 + ax
            n_in = out.shape[axis]
            n_out = out_shape[ax]
            if n_in == n_out:
                continue
            i = jnp.arange(n_out, dtype=a.dtype)
            if align_corners and n_out > 1:
                t = i * (n_in - 1) / (n_out - 1)
            else:
                t = jnp.clip((i + 0.5) * n_in / n_out - 0.5, 0,
                             n_in - 1)
            lo = jnp.floor(t).astype(jnp.int32)
            hi = jnp.clip(lo + 1, 0, n_in - 1)
            w = (t - lo).reshape((-1,) + (1,) * (out.ndim - axis - 1))
            lo_v = jnp.take(out, lo, axis=axis)
            hi_v = jnp.take(out, hi, axis=axis)
            out = lo_v * (1 - w) + hi_v * w
        return out
    return run_op('resize_nd', fn, [x])


def resize_linear(input, out_shape=None, scale=None, name=None,
                  align_corners=True, align_mode=1,
                  data_format='NCW'):
    """fluid.layers.resize_linear — 1-D linear interpolation
    [N, C, W]."""
    return _resize_nd(input, out_shape, scale, 'linear', align_corners,
                      data_format)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True,
                     align_mode=1, data_format='NCDHW'):
    """fluid.layers.resize_trilinear — 3-D interpolation
    [N, C, D, H, W]."""
    return _resize_nd(input, out_shape, scale, 'trilinear',
                      align_corners, data_format)


def image_resize_short(input, out_short_len, resample='BILINEAR'):
    """fluid.layers.image_resize_short: scale so the SHORT side equals
    out_short_len, keeping aspect ratio."""
    x = as_tensor(input)
    h, w = int(x.shape[2]), int(x.shape[3])
    short, long_ = (h, w) if h < w else (w, h)
    ratio = out_short_len / float(short)
    oh, ow = int(round(h * ratio)), int(round(w * ratio))
    return F.interpolate(x, size=[oh, ow],
                         mode='bilinear' if resample == 'BILINEAR'
                         else 'nearest')


# -- fluid RNN-op wrappers (param-creating, over the modern cells) ----------

def gru_unit(input, hidden, size, param_attr=None, bias_attr=None,
             activation='tanh', gate_activation='sigmoid',
             origin_mode=False):
    """fluid.layers.gru_unit (operators/gru_unit_op.cc): one GRU step
    exposing the fluid op's full output triple —
    (updated_hidden [B, D], reset_hidden_pre [B, D] = r * h_prev,
    gate [B, 3D] = [u, r, c-hat] after activations). origin_mode picks
    h = u*h_prev + (1-u)*c vs h = (1-u)*h_prev + u*c."""
    import jax
    import jax.numpy as jnp
    from ..core.autograd import run_op
    hidden_dim = size // 3
    x = as_tensor(input)
    h = as_tensor(hidden, ref=x)
    dt = str(x.dtype)
    wi = _mode_param([int(x.shape[-1]), size], dt)
    wh = _mode_param([hidden_dim, size], dt)
    bias = _mode_param([size], dt)
    act = {'tanh': jnp.tanh, 'sigmoid': jax.nn.sigmoid,
           'relu': jax.nn.relu, 'identity': (lambda v: v)}[activation]
    gact = {'sigmoid': jax.nn.sigmoid, 'tanh': jnp.tanh,
            'relu': jax.nn.relu,
            'identity': (lambda v: v)}[gate_activation]

    def fn(xa, ha, wia, wha, ba):
        g = xa @ wia + ba
        hg = ha @ wha
        gu = gact(g[:, :hidden_dim] + hg[:, :hidden_dim])
        gr = gact(g[:, hidden_dim:2 * hidden_dim]
                  + hg[:, hidden_dim:2 * hidden_dim])
        rhp = gr * ha
        c = act(g[:, 2 * hidden_dim:]
                + rhp @ wha[:, 2 * hidden_dim:])
        if origin_mode:
            nh = gu * ha + (1 - gu) * c
        else:
            nh = (1 - gu) * ha + gu * c
        gate = jnp.concatenate([gu, gr, c], axis=-1)
        return nh, rhp, gate
    return run_op('gru_unit', fn, [x, h, wi, wh, bias])


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """fluid.layers.lstm_unit (operators/lstm_unit_op.cc): one LSTM
    step. Returns (hidden_t, cell_t)."""
    from ..nn import LSTMCell
    cell = LSTMCell(int(as_tensor(x_t).shape[-1]),
                    int(as_tensor(hidden_t_prev).shape[-1]))
    _, (h, c) = cell(x_t, (hidden_t_prev, cell_t_prev))
    return h, c


def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation='sigmoid', cell_activation='tanh',
                 candidate_activation='tanh', dtype='float32',
                 name=None):
    """fluid.layers.dynamic_lstm (operators/lstm_op.cc): full-sequence
    LSTM over dense padded input [B, T, D]; `size` = 4 * hidden_dim.
    Returns (hidden_seq [B, T, H], cell_seq [B, T, H]) — BOTH sequences
    like the reference. Peepholes are not modeled (documented
    deviation: XLA fuses the plain gates)."""
    from ..nn import LSTMCell
    hidden = size // 4
    x = as_tensor(input)
    cell = LSTMCell(int(x.shape[-1]), hidden)
    B, T = int(x.shape[0]), int(x.shape[1])
    h = as_tensor(h_0) if h_0 is not None else \
        Tensor(np.zeros((B, hidden), np.float32))
    c = as_tensor(c_0) if c_0 is not None else \
        Tensor(np.zeros((B, hidden), np.float32))
    hs, cs = [], []
    order = range(T - 1, -1, -1) if is_reverse else range(T)
    from ..ops import manip as _mp
    for t in order:
        step = _mp.slice(x, [1], [t], [t + 1])
        step = _mp.reshape(step, [B, int(x.shape[-1])])
        _, (h, c) = cell(step, (h, c))
        hs.append(h)
        cs.append(c)
    if is_reverse:
        hs, cs = hs[::-1], cs[::-1]
    out_h = _mp.stack(hs, axis=1)
    out_c = _mp.stack(cs, axis=1)
    return out_h, out_c


def dynamic_lstmp(input, size, proj_size, **kwargs):
    """fluid.layers.dynamic_lstmp (operators/lstmp_op.cc): LSTM with a
    learned projection of the hidden state. Returns (projected_seq,
    cell_seq)."""
    out, cell_seq = dynamic_lstm(input, size, **kwargs)
    w = _mode_param([size // 4, proj_size], str(as_tensor(input).dtype))
    proj = M.matmul(out, w)
    return proj, cell_seq


def dynamic_gru(input, size, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation='sigmoid',
                candidate_activation='tanh', h_0=None,
                origin_mode=False):
    """fluid.layers.dynamic_gru (operators/gru_op.cc): full-sequence GRU
    over dense padded input. `size` is the hidden dim; fluid feeds
    pre-multiplied input [B, T, 3*size]; here the raw features work
    directly (the cell owns its input projection)."""
    from ..nn import GRU
    x = as_tensor(input)
    m = GRU(int(x.shape[-1]), size,
            direction='backward' if is_reverse else 'forward')
    init = None
    if h_0 is not None:
        init = Tensor(as_tensor(h_0).data[None])
    out, _ = m(input, init)
    return out


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """fluid.layers.lstm (operators/cudnn_lstm_op.cc): multi-layer
    (optionally bidirectional) LSTM. Returns (out, last_h, last_c)."""
    from ..nn import LSTM
    x = as_tensor(input)
    m = LSTM(int(x.shape[-1]), hidden_size, num_layers=num_layers,
             direction='bidirect' if is_bidirec else 'forward',
             dropout=dropout_prob)
    out, (h, c) = m(input, (init_h, init_c))
    return out, h, c


def beam_search_decode(ids, parents, beam_size=None, end_id=None,
                       scores=None, name=None):
    """fluid.layers.beam_search_decode (beam_search_decode_op.cc):
    backtrace per-step beam selections into full sequences. Dense
    LoD-free contract: ids AND parent beam indices [T, B, W] (the
    reference packs parents into the ids LoD; here they are an explicit
    tensor — `nn.BeamSearchDecoder` and `ops.sequence.beam_search`
    already emit them). Returns (sequences [T, B, W], scores
    passthrough)."""
    from ..ops.contrib import gather_tree
    seqs = gather_tree(ids, parents)
    return seqs, scores


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    """fluid.layers.chunk_eval (operators/chunk_eval_op.cc): chunk-level
    precision/recall/F1 for sequence labeling under IOB/IOE/IOBES/plain
    schemes. Host-side metric (python chunk extraction, like the
    reference's CPU-only kernel). Returns (precision, recall, f1,
    num_infer_chunks, num_label_chunks, num_correct_chunks)."""
    import jax.numpy as jnp
    inf = np.asarray(as_tensor(input).data).reshape(
        np.asarray(as_tensor(input).data).shape[0], -1)
    lab = np.asarray(as_tensor(label).data).reshape(inf.shape)
    excluded = set(excluded_chunk_types or [])
    if seq_length is not None:
        lens = np.asarray(as_tensor(seq_length).data).reshape(-1)
    else:
        lens = np.full(inf.shape[0], inf.shape[1])

    def extract(row, n):
        """tag id -> (type, pos); chunks per scheme."""
        chunks = []
        cur_type, cur_start = None, None
        for i in range(int(n)):
            t = int(row[i])
            if chunk_scheme == 'plain':
                typ = t
                if typ in excluded or typ < 0:
                    if cur_type is not None:
                        chunks.append((cur_type, cur_start, i - 1))
                        cur_type = None
                    continue
                if cur_type != typ:
                    if cur_type is not None:
                        chunks.append((cur_type, cur_start, i - 1))
                    cur_type, cur_start = typ, i
                continue
            n_pos = {'IOB': 2, 'IOE': 2, 'IOBES': 4}[chunk_scheme]
            if t == num_chunk_types * n_pos:      # the O tag
                if cur_type is not None:
                    chunks.append((cur_type, cur_start, i - 1))
                    cur_type = None
                continue
            typ, pos = t // n_pos, t % n_pos
            if typ in excluded:
                continue
            if chunk_scheme == 'IOB':
                begin = pos == 0
            elif chunk_scheme == 'IOE':
                begin = cur_type != typ
            else:                                  # IOBES
                begin = pos in (0, 3)              # B or S
            if begin or cur_type != typ:
                if cur_type is not None:
                    chunks.append((cur_type, cur_start, i - 1))
                cur_type, cur_start = typ, i
            if chunk_scheme == 'IOE' and pos == 1:  # E closes
                chunks.append((cur_type, cur_start, i))
                cur_type = None
            if chunk_scheme == 'IOBES' and pos in (2, 3):  # E/S close
                chunks.append((cur_type, cur_start, i))
                cur_type = None
        if cur_type is not None:
            chunks.append((cur_type, cur_start, int(n) - 1))
        return set(chunks)

    n_inf = n_lab = n_cor = 0
    for b in range(inf.shape[0]):
        ci = extract(inf[b], lens[b])
        cl = extract(lab[b], lens[b])
        n_inf += len(ci)
        n_lab += len(cl)
        n_cor += len(ci & cl)
    prec = n_cor / n_inf if n_inf else 0.0
    rec = n_cor / n_lab if n_lab else 0.0
    f1 = 2 * prec * rec / (prec + rec) if prec + rec else 0.0
    mk = lambda v, dt=jnp.float32: Tensor(jnp.asarray(v, dt))
    return (mk(prec), mk(rec), mk(f1), mk(n_inf, jnp.int32),
            mk(n_lab, jnp.int32), mk(n_cor, jnp.int32))


# -- legacy tiers that do not map to this backend (loud, documented) --------

def _lod_legacy(name_, hint):
    def raiser(*a, **k):
        raise NotImplementedError(
            f"fluid.layers.{name_} operates on LoD metadata, which this "
            f"framework drops by design (SURVEY N11: dense padded "
            f"tensors + lengths vectors). {hint}")
    raiser.__name__ = name_
    raiser.__doc__ = (f"fluid.layers.{name_} — LoD-era API, "
                      f"unsupported by design. {hint}")
    return raiser


lod_append = _lod_legacy('lod_append', "Carry a lengths tensor instead.")
lod_reset = _lod_legacy('lod_reset', "Carry a lengths tensor instead.")
reorder_lod_tensor_by_rank = _lod_legacy(
    'reorder_lod_tensor_by_rank',
    "Sort dense rows with paddle.argsort + gather.")
get_tensor_from_selected_rows = _lod_legacy(
    'get_tensor_from_selected_rows',
    "SelectedRows does not exist here; gradients are dense or handled "
    "by the PS sparse tables.")
merge_selected_rows = _lod_legacy(
    'merge_selected_rows',
    "SelectedRows does not exist here; use segment_sum over ids.")


def _reader_legacy(name_):
    def raiser(*a, **k):
        raise NotImplementedError(
            f"fluid.layers.{name_} belongs to the fluid reader stack, "
            "superseded by paddle.io.DataLoader (multiprocess workers, "
            "see io/__init__.py) — feed arrays through "
            "Executor.run(feed=...) or DataLoader instead.")
    raiser.__name__ = name_
    raiser.__doc__ = (f"fluid.layers.{name_} — legacy reader API, "
                      "superseded by paddle.io.DataLoader.")
    return raiser


py_reader = _reader_legacy('py_reader')
read_file = _reader_legacy('read_file')
double_buffer = _reader_legacy('double_buffer')
create_py_reader_by_data = _reader_legacy('create_py_reader_by_data')


# -- TensorArray tier (dygraph-functional; LoDTensorArray analogue) ---------

class TensorArray(list):
    """Dense TensorArray (the LoDTensorArray analogue — a python list of
    Tensors). Works eagerly and inside dy2static-traced code via
    convert_call; a RECORDED static while loop should carry a stacked
    tensor instead (lax.scan discipline), so array ops raise there."""


def _no_static_array(name_):
    from ..core.autograd import STATIC_RECORD_HOOK
    if STATIC_RECORD_HOOK is not None:
        raise NotImplementedError(
            f"fluid.layers.{name_} inside a recorded static program: "
            "dynamic-length arrays don't trace — carry a pre-allocated "
            "stacked tensor through static.nn.while_loop instead")


def create_array(dtype='float32', initialized_list=None):
    """fluid.layers.create_array."""
    _no_static_array('create_array')
    arr = TensorArray()
    for v in (initialized_list or []):
        arr.append(as_tensor(v))
    return arr


def array_write(x, i, array=None):
    """fluid.layers.array_write — write x at index i (extends like the
    reference when i == len)."""
    _no_static_array('array_write')
    if array is None:
        array = TensorArray()
    idx = int(np.asarray(as_tensor(i).data).reshape(()))
    x = as_tensor(x)
    if idx == len(array):
        array.append(x)
    elif idx < len(array):
        array[idx] = x
    else:
        raise IndexError(
            f"array_write index {idx} beyond array length {len(array)}")
    return array


def array_read(array, i):
    """fluid.layers.array_read."""
    _no_static_array('array_read')
    idx = int(np.asarray(as_tensor(i).data).reshape(()))
    return array[idx]


def array_length(array):
    """fluid.layers.array_length."""
    import jax.numpy as jnp
    return Tensor(jnp.asarray(len(array), jnp.int64))


def tensor_array_to_tensor(input, axis=1, use_stack=False):
    """fluid.layers.tensor_array_to_tensor — concat (or stack) the
    array's tensors along `axis`; also returns each entry's size along
    that axis (the LoD-free replacement for the packed index)."""
    import jax.numpy as jnp
    _no_static_array('tensor_array_to_tensor')
    arrs = [as_tensor(t).data for t in input]
    if use_stack:
        out = jnp.stack(arrs, axis=axis)
        sizes = np.ones(len(arrs), np.int32)
    else:
        out = jnp.concatenate(arrs, axis=axis)
        sizes = np.asarray([a.shape[axis] for a in arrs], np.int32)
    return Tensor(out), Tensor(jnp.asarray(sizes))


# -- debug ops --------------------------------------------------------------

def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_layout=True,
          print_tensor_lod=False, print_phase='both'):
    """fluid.layers.Print (operators/print_op.cc) — pass-through that
    prints the tensor. Eager: host print. Recorded static: jax.debug
    .print on backends with callback support (CPU, standard TPU); on
    the axon dev tunnel the op records as identity (send/recv
    unavailable — same platform note as py_func)."""
    from ..core.autograd import STATIC_RECORD_HOOK, run_op
    msg = message or ''
    if STATIC_RECORD_HOOK is None:
        a = np.asarray(as_tensor(input).data)
        flat = a.reshape(-1)[:summarize]
        print(f"{msg} shape={a.shape} dtype={a.dtype} "
              f"values={flat.tolist()}")
        return as_tensor(input)

    import jax

    def fn(a):
        try:
            jax.debug.print(msg + " {x}", x=a)
        except Exception:
            pass                      # callback-less platform: identity
        return a
    return run_op('print', fn, [as_tensor(input)])


def Assert(cond, data=None, summarize=20, name=None):
    """fluid.layers.Assert (operators/assert_op.cc). Eager: raises
    ValueError when the condition is false. Recorded static programs:
    raises NotImplementedError — in-graph assertions need host
    callbacks; gate input data eagerly or use FLAGS_check_nan_inf for
    numeric guards."""
    from ..core.autograd import STATIC_RECORD_HOOK
    if STATIC_RECORD_HOOK is not None:
        raise NotImplementedError(
            "fluid.layers.Assert inside a recorded static program is "
            "not supported (XLA programs cannot raise) — check the "
            "condition eagerly before feeding, or use "
            "FLAGS_check_nan_inf for numeric guards")
    ok = bool(np.asarray(as_tensor(cond).data).all())
    if not ok:
        extra = ''
        if data is not None:
            vals = [np.asarray(as_tensor(d).data).reshape(-1)[:summarize]
                    for d in (data if isinstance(data, (list, tuple))
                              else [data])]
            extra = f' data={[v.tolist() for v in vals]}'
        raise ValueError(f"Assert failed{extra}")
    return True


# -- imperative control-flow classes (functional forms are the path) --------

def _imperative_cf(name_, modern, example):
    class _Raiser:
        def __init__(self, *a, **k):
            raise NotImplementedError(
                f"fluid.layers.{name_} builds blocks by mutating "
                f"variables in place, which an XLA-traced program "
                f"cannot express — use the functional form "
                f"{modern} (e.g. {example}); dy2static converts "
                f"python `while`/`if` to it automatically")
    _Raiser.__name__ = name_
    _Raiser.__doc__ = (f"fluid.layers.{name_} — imperative block API "
                       f"superseded by {modern}.")
    return _Raiser


While = _imperative_cf(
    'While', 'static.nn.while_loop',
    "while_loop(lambda i: i < n, lambda i: i + 1, [i0])")
Switch = _imperative_cf(
    'Switch', 'static.nn.case/switch_case',
    "case([(cond1, fn1), (cond2, fn2)], default=fn3)")
IfElse = _imperative_cf(
    'IfElse', 'static.nn.cond',
    "cond(pred, true_fn, false_fn)")
StaticRNN = _imperative_cf(
    'StaticRNN', 'paddle.nn.RNN / fluid_layers.rnn',
    "rnn(cell, inputs, initial_states)")
DynamicRNN = _imperative_cf(
    'DynamicRNN', 'paddle.nn.RNN + sequence lengths',
    "rnn(cell, inputs, sequence_length=lens)")
