"""Inference export/load — the TPU-native save_inference_model.

Reference parity: save_inference_model / AnalysisPredictor
(api/analysis_predictor.h:82, N36). TPU-native: the deployable artifact is a
serialized StableHLO executable (jax.export) + the parameter state — the AOT
analogue of the reference's pruned ProgramDesc + params; loading rebuilds a
callable predictor with no Python model code required.
"""
import os
import io as _io
import json
import zipfile

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def export_layer(path_prefix, layer, example_inputs):
    """Export an eager nn.Layer as an AOT predictor artifact (the
    paddle.jit.save(TranslatedLayer) role — distinct from
    paddle.static.save_inference_model, which serializes a PROGRAM).

    Produces <prefix>.stablehlo (portable serialized module) and
    <prefix>.pdexec (weights/buffers/input specs).
    """
    from jax import export as jax_export
    from ..jit import functional_call, get_params, get_buffers

    params = get_params(layer)
    buffers = get_buffers(layer)
    was_training = layer.training
    layer.eval()

    def fwd(params, buffers, *args):
        out, _ = functional_call(layer, params, args, buffers)
        return out

    arg_arrays = tuple(a.data if isinstance(a, Tensor) else jnp.asarray(a)
                       for a in example_inputs)
    exported = jax_export.export(jax.jit(fwd))(params, buffers, *arg_arrays)
    blob = exported.serialize()
    with open(path_prefix + '.stablehlo', 'wb') as f:
        f.write(blob)
    # data-only container (zip: json specs + npz arrays) — loading an
    # untrusted .pdexec cannot execute code (same rationale as
    # serialization.py's ProgramDesc container)
    arrays = {}
    for k, v in params.items():
        arrays['p:' + k] = np.asarray(jax.device_get(v))
    for k, v in buffers.items():
        arrays['b:' + k] = np.asarray(jax.device_get(v))
    npz = _io.BytesIO()
    np.savez(npz, **arrays)
    meta = {'input_specs': [[list(a.shape), str(a.dtype)]
                            for a in arg_arrays]}
    with zipfile.ZipFile(path_prefix + '.pdexec', 'w') as z:
        z.writestr('meta.json', json.dumps(meta))
        z.writestr('arrays.npz', npz.getvalue())
    if was_training:
        layer.train()
    return path_prefix


class Predictor:
    """Parity: the AnalysisPredictor role — load + run, no model code."""

    def __init__(self, path_prefix):
        from jax import export as jax_export
        with open(path_prefix + '.stablehlo', 'rb') as f:
            self._exported = jax_export.deserialize(f.read())
        with zipfile.ZipFile(path_prefix + '.pdexec') as z:
            meta = json.loads(z.read('meta.json'))
            loaded = np.load(_io.BytesIO(z.read('arrays.npz')),
                             allow_pickle=False)
            arrays = {k: loaded[k] for k in loaded.files}
        self._params = {k[2:]: jnp.asarray(v)
                        for k, v in arrays.items() if k.startswith('p:')}
        self._buffers = {k[2:]: jnp.asarray(v)
                         for k, v in arrays.items() if k.startswith('b:')}
        self.input_specs = [(tuple(sh), dt)
                            for sh, dt in meta['input_specs']]
        # output arity is known statically from the exported module, so
        # serving code can enumerate output names before the first run()
        # (the reference Predictor exposes fetch targets at load)
        try:
            self.n_outputs = int(self._exported.out_tree.num_leaves)
        except Exception:
            self.n_outputs = None

    def run(self, *inputs):
        arrays = tuple(i.data if isinstance(i, Tensor) else jnp.asarray(i)
                       for i in inputs)
        out = self._exported.call(self._params, self._buffers, *arrays)
        return jax.tree_util.tree_map(np.asarray, out)


def load_predictor(path_prefix):
    return Predictor(path_prefix)
