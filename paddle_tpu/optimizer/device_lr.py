"""On-device LR schedules (ISSUE 13, docs/performance.md#async-dispatch).

The compiled engines historically fed `optimizer.get_lr()` to the device
as a fresh fp32 scalar every step — a per-step host compute + H2D feed
in the dispatch hot path. For the common schedulers (constant, linear
warmup+decay, cosine, inverse-sqrt/Noam, polynomial/exponential decays)
the schedule is a pure function of the step index, so it traces directly
into the compiled step as `lr = fn(step_counter)` where the counter is a
device-resident int32 carried (and incremented) by the step itself — no
per-step host work at all.

`device_lr_fn(schedule)` returns that traceable fn, or None for
schedules whose value depends on host-side state (ReduceOnPlateau,
LambdaDecay, user subclasses...) — those keep the legacy scalar-feed
path. Exact-type checks on purpose: a subclass overriding `get_lr()`
must fall back to the host feed, not silently trace the parent's rule.

The host mirror: `get_lr()` keeps reporting the host scheduler's value
(the user still drives `scheduler.step()`); the device counter starts
from `scheduler.last_epoch` at engine build / `set_state_dict`, so both
agree whenever the loop steps the scheduler once per train step (the
opt-in contract — see core/async_step.resolve_device_lr).
"""
import math

from .lr import (LRScheduler, NoamDecay, CosineAnnealingDecay,
                 PolynomialDecay, LinearWarmup, InverseTimeDecay,
                 ExponentialDecay, NaturalExpDecay, StepDecay,
                 MultiStepDecay)


def lr_epoch(schedule):
    """The device counter's start value: the host scheduler's current
    epoch (schedulers step() once at init, so a fresh one sits at 0)."""
    return max(int(getattr(schedule, 'last_epoch', 0)), 0)


class LrFeed:
    """Dispatch-side LR plumbing shared by the three compiled engines.

    Resolves the on-device-LR knob against the optimizer's schedule and
    then serves the lr slot's dispatch argument with zero per-step host
    work: the device int32 step counter under on-device LR (`fn` set;
    the compiled step returns it incremented — engines write it back to
    `carry`), else a cached device scalar re-placed only when
    `get_lr()` changed (feed-on-change — a constant lr feeds exactly
    once). `place` is the engine's device-placement callable (mesh
    engines replicate via their `_place`; the single-program step uses
    plain `jnp.asarray`).
    """

    def __init__(self, optimizer, flag=None, place=None):
        from ..core.async_step import resolve_device_lr
        self._optimizer = optimizer
        self._place = place
        sched = optimizer._learning_rate
        self.fn = None
        if isinstance(sched, LRScheduler) and resolve_device_lr(flag):
            self.fn = device_lr_fn(sched)
        self.carry = None       # device int32 step counter (device LR)
        self._host = None       # feed-on-change cache (legacy path)
        self._dev = None

    def _put(self, value, dtype):
        import numpy as np
        import jax.numpy as jnp
        arr = np.asarray(value, dtype)
        return self._place(arr) if self._place is not None \
            else jnp.asarray(arr)

    def arg(self):
        import numpy as np
        if self.fn is not None:
            if self.carry is None:
                self.reset_carry()
            return self.carry
        v = float(self._optimizer.get_lr())
        if self._dev is None or v != self._host:
            self._host = v
            self._dev = self._put(v, np.float32)
        return self._dev

    def reset_carry(self):
        """(Re)sync the device step counter to the host scheduler's
        current epoch (engine build, set_state_dict) — resume
        mid-schedule lands on the lr the host path would feed next."""
        import numpy as np
        self.carry = self._put(lr_epoch(self._optimizer._learning_rate),
                               np.int32)


def describe(schedule):
    if isinstance(schedule, (int, float)):
        return 'constant'
    return type(schedule).__name__


def device_lr_fn(schedule):
    """Traceable fp32 `fn(step_int32) -> lr` for `schedule`, or None.

    All math runs in fp32 jnp ops, so the value is deterministic across
    dispatches (the windowed-vs-sync bit-identity bar); it matches the
    host's float64 compute to fp32 rounding (~1e-7 rel), which is the
    documented equivalence, not bit equality.
    """
    import jax.numpy as jnp

    if isinstance(schedule, (int, float)):
        v = float(schedule)

        def const_fn(step):
            return jnp.full((), v, jnp.float32)
        return const_fn

    if not isinstance(schedule, LRScheduler):
        return None

    t = type(schedule)
    if t is NoamDecay:
        base = float(schedule.base_lr)
        d = float(schedule.d_model)
        warm = float(schedule.warmup_steps)

        def noam_fn(step):
            s = step.astype(jnp.float32)
            a = jnp.where(s > 0, s, 1.0) ** -0.5
            b = warm ** -1.5 * s
            lr = base * (d ** -0.5) * jnp.minimum(a, b)
            return jnp.where(s == 0, 0.0, lr).astype(jnp.float32)
        return noam_fn

    if t is CosineAnnealingDecay:
        base = float(schedule.base_lr)
        eta = float(schedule.eta_min)
        tmax = float(schedule.T_max)

        def cos_fn(step):
            s = step.astype(jnp.float32)
            return (eta + (base - eta)
                    * (1.0 + jnp.cos(math.pi * s / tmax)) / 2.0) \
                .astype(jnp.float32)
        return cos_fn

    if t is PolynomialDecay:
        base = float(schedule.base_lr)
        end = float(schedule.end_lr)
        decay = float(schedule.decay_steps)
        power = float(schedule.power)
        cycle = bool(schedule.cycle)

        def poly_fn(step):
            s = step.astype(jnp.float32)
            if cycle:
                div = jnp.where(s > 0, jnp.ceil(s / decay), 1.0)
                ds = decay * jnp.maximum(div, 1.0)
            else:
                ds = jnp.full((), decay, jnp.float32)
                s = jnp.minimum(s, ds)
            return ((base - end) * (1.0 - s / ds) ** power + end) \
                .astype(jnp.float32)
        return poly_fn

    if t is InverseTimeDecay:
        base = float(schedule.base_lr)
        gamma = float(schedule.gamma)

        def inv_fn(step):
            s = step.astype(jnp.float32)
            return (base / (1.0 + gamma * s)).astype(jnp.float32)
        return inv_fn

    if t is ExponentialDecay:
        base = float(schedule.base_lr)
        gamma = float(schedule.gamma)

        def exp_fn(step):
            s = step.astype(jnp.float32)
            return (base * gamma ** s).astype(jnp.float32)
        return exp_fn

    if t is NaturalExpDecay:
        base = float(schedule.base_lr)
        gamma = float(schedule.gamma)

        def nexp_fn(step):
            s = step.astype(jnp.float32)
            return (base * jnp.exp(-gamma * s)).astype(jnp.float32)
        return nexp_fn

    if t is StepDecay:
        base = float(schedule.base_lr)
        gamma = float(schedule.gamma)
        size = int(schedule.step_size)

        def stepdecay_fn(step):
            n = (step // size).astype(jnp.float32)
            return (base * gamma ** n).astype(jnp.float32)
        return stepdecay_fn

    if t is MultiStepDecay:
        base = float(schedule.base_lr)
        gamma = float(schedule.gamma)
        miles = [int(m) for m in schedule.milestones]

        def multistep_fn(step):
            n = sum((step >= m).astype(jnp.float32) for m in miles)
            return (base * gamma ** n).astype(jnp.float32)
        return multistep_fn

    if t is LinearWarmup:
        # linear warmup into a constant or any traceable inner schedule
        # (the "linear warmup + decay" composition)
        inner = device_lr_fn(schedule.lr)
        if inner is None:
            return None
        warm = int(schedule.warmup_steps)
        start = float(schedule.start_lr)
        end = float(schedule.end_lr)

        def warmup_fn(step):
            s = step.astype(jnp.float32)
            ramp = (end - start) * s / max(warm, 1) + start
            after = inner(jnp.maximum(step - warm, 0))
            return jnp.where(step < warm, ramp, after) \
                .astype(jnp.float32)
        return warmup_fn

    return None
