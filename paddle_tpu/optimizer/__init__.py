"""paddle_tpu.optimizer — parity: python/paddle/optimizer."""
from . import lr
from .optimizer import (Optimizer, SGD, Momentum, Adagrad, RMSProp, Adam,
                        AdamW, Adamax, Lamb, Lars, LarsMomentum,
                        DGCMomentumOptimizer, Adadelta, DecayedAdagrad,
                        Ftrl)
