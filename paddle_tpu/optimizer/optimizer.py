"""Optimizers.

Reference parity: python/paddle/optimizer (Adam/AdamW/SGD/Momentum/Adagrad/
RMSProp/Adamax/Lamb) whose update formulas live in C++
operators/optimizers/*_op (SURVEY.md N25). TPU-native design: each optimizer
exposes (a) the eager `step()` path updating param.data in place, and (b) a
pure functional `init_state(params)` / `apply(params, grads, state, lr)` pair
used by jitted train steps and the distributed engines — the whole update is
one fused XLA program, not per-param kernel launches.

Master-weight (fp32) handling mirrors operators/optimizers' multi-precision
mode: when a param is bf16/fp16, state (and the update) is kept in fp32 and the
param is re-cast after the update.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..core import dtypes
from ..core.tensor import Tensor
from ..core.autograd import no_grad
from .lr import LRScheduler


class Optimizer:
    # True when `update` is strictly per-element (no per-PARAMETER
    # norms/quantiles), so the bucketed/sharded flat update paths
    # (core/bucketing.py) are bit-equivalent to per-param application.
    # Lamb/LARS/DGC override to False and keep the per-param path.
    _elementwise = False
    # True when `update` is additionally pure jnp elementwise code with
    # only SCALAR side states (beta powers), so the fused one-pass
    # Pallas optimizer-step kernel (ops/pallas/fused_optimizer.py) can
    # trace the rule directly into its body. Untagged optimizers keep
    # the XLA op chain (counted as a fallback route).
    _pallas_fusible = False

    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=True):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None \
            else None
        self._grad_clip = grad_clip
        if isinstance(weight_decay, (int, float)) and weight_decay is not None:
            self._weight_decay = float(weight_decay)
        else:
            self._weight_decay = weight_decay if weight_decay is None \
                else float(getattr(weight_decay, '_coeff', 0.0))
        self._multi_precision = multi_precision
        self._accumulators = {}   # param id -> dict of state arrays
        self._master_weights = {}  # param id -> fp32 jax array
        self._step_count = 0

    # -- lr ------------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        if isinstance(self._learning_rate, LRScheduler):
            raise RuntimeError("can't set_lr when using an LRScheduler")
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state ----------------------------------------------------------------
    def _param_key(self, p):
        return p.name or str(id(p))

    def _get_master(self, p):
        if not self._multi_precision or p.dtype == jnp.float32:
            return p.data
        key = self._param_key(p)
        if key not in self._master_weights:
            self._master_weights[key] = p.data.astype(jnp.float32)
        return self._master_weights[key]

    def _set_param(self, p, new_master):
        if not self._multi_precision or p.dtype == jnp.float32:
            # the update math runs fp32; never let it upcast a
            # low-precision param's storage (bf16 params with
            # multi_precision=False is the memory-tight config
            # moment_dtype exists for — an fp32 write-back would double
            # param HBM and retrace dtype-keyed jits)
            p.data = new_master if new_master.dtype == p.dtype \
                else new_master.astype(p.dtype)
        else:
            self._master_weights[self._param_key(p)] = new_master
            p.data = new_master.astype(p.dtype)

    # -- functional API --------------------------------------------------------
    def init_state(self, param):
        """Return a dict of per-param state arrays (fp32)."""
        return {}

    def update(self, param, grad, state, lr):
        """Pure: (fp32 param, fp32 grad, state, lr) -> (new_param, new_state)."""
        raise NotImplementedError

    def functional_apply(self, params, grads, states, lr):
        """Pure whole-model update over {name: array} pytrees — the jitted
        path used by TrainStep and the distributed engines. Applies global
        grad clip and weight decay, then the per-param `update` rule; the
        entire thing fuses into the caller's XLA program."""
        if self._grad_clip is not None:
            from ..nn.clip import ClipGradByGlobalNorm, ClipGradByNorm, \
                ClipGradByValue
            if isinstance(self._grad_clip, ClipGradByGlobalNorm):
                sq = sum(jnp.sum(g.astype(jnp.float32) ** 2)
                         for g in grads.values())
                gn = jnp.sqrt(sq)
                factor = self._grad_clip.clip_norm / jnp.maximum(
                    gn, self._grad_clip.clip_norm)
                grads = {n: g * factor.astype(g.dtype)
                         for n, g in grads.items()}
            elif isinstance(self._grad_clip, ClipGradByNorm):
                cn = self._grad_clip.clip_norm
                def _clip1(g):
                    n = jnp.sqrt(jnp.sum(g.astype(jnp.float32) ** 2))
                    return g * jnp.minimum(cn / jnp.maximum(n, 1e-12),
                                           1.0).astype(g.dtype)
                grads = {n: _clip1(g) for n, g in grads.items()}
            elif isinstance(self._grad_clip, ClipGradByValue):
                grads = {n: jnp.clip(g, self._grad_clip.min,
                                     self._grad_clip.max)
                         for n, g in grads.items()}
        new_params, new_states = {}, {}
        for n, p in params.items():
            g = grads.get(n)
            if g is None:
                new_params[n] = p
                new_states[n] = states.get(n, {})
                continue
            st = dict(states.get(n) or {})
            low_precision = p.dtype != jnp.float32
            if low_precision and self._multi_precision:
                # fp32 master weight rides in the optimizer state
                # (parity: multi-precision mode of operators/optimizers/*).
                p32 = st.pop('master', None)
                if p32 is None:
                    p32 = p.astype(jnp.float32)
            else:
                p32 = p.astype(jnp.float32) if low_precision else p
            g32 = g.astype(jnp.float32) if g.dtype != jnp.float32 else g
            if self._weight_decay and self._decay_into_grad():
                g32 = g32 + self._weight_decay * p32
            if not st:
                st = self.init_state(Tensor(p32))
            np_, ns = self.update(p32, g32, st, lr)
            if low_precision and self._multi_precision:
                ns = dict(ns)
                ns['master'] = np_
            new_params[n] = np_.astype(p.dtype)
            new_states[n] = ns
        return new_params, new_states

    # -- eager step -------------------------------------------------------------
    @no_grad()
    def step(self):
        params = self._parameter_list
        if params is None:
            raise ValueError("optimizer created without a parameter list")
        from .. import profiler as _prof
        from ..core.monitor import counter
        counter('ptpu_optimizer_steps_total',
                help='eager optimizer.step() calls',
                labelnames=('optimizer',)).inc(
                    1, optimizer=type(self).__name__)
        from ..core import memory as _mem
        with _prof.RecordEvent('optimizer::step', event_type='optimizer'), \
                _mem.oom_guard('optimizer.step'), \
                _mem.phase('optimizer.step'):
            params_grads = [(p, p.grad) for p in params
                            if not p.stop_gradient and p.grad is not None]
            self._numerics_boundary(params_grads)
            self._apply_params_grads(params_grads)

    def _numerics_boundary(self, params_grads):
        """Numerics-observatory step boundary: flush the eager
        NaN/Inf guard (its one deferred host sync — BEFORE the update,
        so a poisoned grad is caught before it corrupts params) and,
        with FLAGS_tensor_stats, publish per-param grad stats + the
        global grad norm as ptpu_num_* gauges (one batched sync)."""
        from ..core import numerics as _num
        if _num.guard().has_pending():
            _num.flush(site='optimizer.step', step=self._step_count)
        from ..core.flags import flag as _flag
        if _flag('FLAGS_tensor_stats') and params_grads:
            named = {}
            for i, (p, g) in enumerate(params_grads):
                if g is None:
                    continue
                named[getattr(p, 'name', None) or f'param_{i}'] = g.data
            if named:
                stats = _num.collect(named)
                gn = float(np.sqrt(sum(s.l2_norm ** 2
                                       for s in stats.values())))
                _num.publish_stats(stats, kind='grad', global_norm=gn)

    def _apply_params_grads(self, params_grads):
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        self._step_count += 1
        for p, g in params_grads:
            if g is None:
                continue
            key = self._param_key(p)
            if key not in self._accumulators:
                self._accumulators[key] = self.init_state(p)
            state = self._accumulators[key]
            master = self._get_master(p)
            garr = g.data.astype(jnp.float32) if g.data.dtype != jnp.float32 \
                else g.data
            plr = lr * getattr(p, 'optimize_attr',
                               {'learning_rate': 1.0})['learning_rate']
            reg = getattr(p, 'regularizer', None)
            if reg is not None:
                # per-param regularizer (ParamAttr.regularizer) takes
                # precedence over the optimizer-level weight_decay, matching
                # the reference's append_regularization_ops rule
                garr = garr + reg(master)
            elif self._weight_decay and self._decay_into_grad():
                garr = garr + self._weight_decay * master
            new_p, new_state = self.update(master, garr, state, plr)
            self._accumulators[key] = new_state
            self._set_param(p, new_p)

    def _decay_into_grad(self):
        """L2-regularization style decay (SGD/Momentum/Adam). AdamW overrides
        to decouple."""
        return True

    def clear_grad(self, set_to_zero=True):
        if self._parameter_list:
            for p in self._parameter_list:
                p.grad = None

    clear_gradients = clear_grad

    def minimize(self, loss, startup_program=None, parameters=None,
                 no_grad_set=None):
        from ..static import program as sprog
        if sprog.in_static_mode():
            # Static path (parity: Optimizer.minimize = append_backward +
            # apply_gradients appending one optimize op per parameter,
            # fluid/optimizer.py _append_optimize_op). Real Optimize-role
            # ops land in the Program so distributed rewrites (sharding
            # prune, pipeline split) can move/delete them like the
            # reference passes do.
            from ..static.backward import append_backward
            params_grads = append_backward(loss, parameter_list=parameters)
            prog = loss.block.program
            prog._optimizer = self
            self._append_optimize_ops(prog, params_grads)
            return [], params_grads
        loss.backward()
        self.step()
        return [], []

    def _append_optimize_ops(self, prog, params_grads):
        """Record Optimize-role ops into a static Program: an optional
        global-norm clip op over all grads, then one `<optimizer>` op per
        parameter whose state is threaded through persistable vars named
        `<param>_<opt>_<state>_0` (reference: accumulator naming of
        fluid/optimizer.py _add_accumulator)."""
        from ..static.program import Variable, Operator, OpRole
        from ..nn.clip import (ClipGradByGlobalNorm, ClipGradByNorm,
                               ClipGradByValue)
        block = prog.global_block()
        if '@LR' not in block.vars:
            block.vars['@LR'] = Variable(block, '@LR', [], 'float32',
                                         persistable=True)
        op_type = type(self).__name__.lower()

        grads = [g for _, g in params_grads if g is not None]
        if isinstance(self._grad_clip, ClipGradByGlobalNorm) and grads:
            cn = float(self._grad_clip.clip_norm)

            def clip_fn(*gs, _cn=cn):
                sq = sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in gs)
                factor = _cn / jnp.maximum(jnp.sqrt(sq), _cn)
                return tuple(g * factor.astype(g.dtype) for g in gs)
            cop = Operator('clip_by_global_norm', clip_fn,
                           [g.name for g in grads],
                           [g.name for g in grads],
                           {'clip_norm': cn}, op_role=OpRole.Optimize)
            cop.multi_out = True
            cop.op_device = 'all'   # spans stages, like the reference's
            block.append_op(cop)    # global-clip reduction ops (gpu:all)

        for p, g in params_grads:
            if g is None:
                continue
            st_tmpl = self.init_state(
                Tensor(jnp.zeros(tuple(p.shape), jnp.float32)))
            low = jnp.dtype(p.dtype) != jnp.float32
            if low and self._multi_precision:
                st_tmpl['master'] = None   # placeholder; init from param
            skeys = sorted(st_tmpl.keys())
            svars = []
            for k in skeys:
                sname = f"{p.name}_{op_type}_{k}_0"
                if sname not in block.vars:
                    arr = st_tmpl[k]
                    if arr is None:   # fp32 master weight
                        sv = Variable(block, sname, list(p.shape),
                                      'float32', persistable=True)
                        sv._init_from = p.name
                    else:
                        sv = Variable(block, sname, list(arr.shape),
                                      str(arr.dtype), persistable=True)
                        sv.initializer = (
                            lambda shape, dtype, _a=arr: jnp.asarray(_a))
                    block.vars[sname] = sv
                    prog.startup_ops.append(sv)
                svars.append(sname)

            per_clip = self._grad_clip if isinstance(
                self._grad_clip, (ClipGradByNorm, ClipGradByValue)) else None
            plr_scale = getattr(p, 'optimize_attr',
                                {'learning_rate': 1.0})['learning_rate']
            decay_fun = getattr(self, '_apply_decay_param_fun', None)
            decay_on = decay_fun is None or bool(decay_fun(p.name))

            def opt_fn(p_arr, g_arr, lr_arr, *state_arrs,
                       _keys=tuple(skeys), _clip=per_clip, _s=plr_scale,
                       _decay=decay_on):
                st = dict(zip(_keys, state_arrs))
                master = st.pop('master', None)
                g32 = g_arr.astype(jnp.float32)
                if isinstance(_clip, ClipGradByNorm):
                    n = jnp.sqrt(jnp.sum(g32 ** 2))
                    g32 = g32 * jnp.minimum(
                        _clip.clip_norm / jnp.maximum(n, 1e-12), 1.0)
                elif isinstance(_clip, ClipGradByValue):
                    g32 = jnp.clip(g32, _clip.min, _clip.max)
                p32 = master if master is not None \
                    else p_arr.astype(jnp.float32)
                if self._weight_decay and self._decay_into_grad():
                    g32 = g32 + self._weight_decay * p32
                saved_decay = getattr(type(self), '_cur_decay', None)
                if saved_decay is not None:   # AdamW per-param exclusion
                    self._cur_decay = _decay
                try:
                    np_, ns = self.update(p32, g32, st, lr_arr * _s)
                finally:
                    if saved_decay is not None:
                        self._cur_decay = saved_decay
                ns = dict(ns)
                if master is not None:
                    ns['master'] = np_
                return (np_.astype(p_arr.dtype),) + tuple(
                    ns[k] for k in _keys)

            op = Operator(op_type, opt_fn, [p.name, g.name, '@LR'] + svars,
                          [p.name] + svars, {'param': p.name},
                          op_role=OpRole.Optimize)
            op.multi_out = True
            block.append_op(op)

    # -- checkpoint ---------------------------------------------------------------
    def state_dict(self):
        sd = {}
        for key, state in self._accumulators.items():
            for name, arr in state.items():
                sd[f"{key}.{name}"] = Tensor(arr)
        for key, arr in self._master_weights.items():
            sd[f"master.{key}"] = Tensor(arr)
        if isinstance(self._learning_rate, LRScheduler):
            sd['LR_Scheduler'] = self._learning_rate.state_dict()
        sd['@step'] = self._step_count
        return sd

    def set_state_dict(self, state_dict):
        for k, v in state_dict.items():
            if k == 'LR_Scheduler':
                if isinstance(self._learning_rate, LRScheduler):
                    self._learning_rate.set_state_dict(v)
                continue
            if k == '@step':
                self._step_count = int(v if not isinstance(v, Tensor)
                                       else v.item())
                continue
            arr = v.data if isinstance(v, Tensor) else jnp.asarray(v)
            if k.startswith('master.'):
                self._master_weights[k[len('master.'):]] = arr
            else:
                key, name = k.rsplit('.', 1)
                self._accumulators.setdefault(key, {})[name] = arr

    set_dict = set_state_dict


class SGD(Optimizer):
    """Parity: operators/optimizers/sgd_op."""

    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate=0.001, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=True,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._multi_precision = multi_precision

    def update(self, param, grad, state, lr):
        return param - lr * grad, state


class Momentum(Optimizer):
    """Parity: operators/optimizers/momentum_op (use_nesterov supported)."""

    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=True, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._use_nesterov = use_nesterov

    def init_state(self, param):
        return {'velocity': jnp.zeros(param.data.shape, jnp.float32)}

    def update(self, param, grad, state, lr):
        v = self._momentum * state['velocity'] + grad
        if self._use_nesterov:
            new_p = param - lr * (grad + self._momentum * v)
        else:
            new_p = param - lr * v
        return new_p, {'velocity': v}


class DGCMomentumOptimizer(Momentum):
    """Parity: fluid.optimizer.DGCMomentumOptimizer:1453 + dgc_op.cc
    (Deep Gradient Compression): momentum-corrected gradients are top-k
    sparsified before application/communication, with the residual
    accumulated locally (u/v buffers) until it crosses the threshold.
    On TPU the win is DCN-only (ICI is fast); rampup delays compression
    like the reference (`rampup_begin_step`)."""

    _elementwise = False   # top-k quantile is per-parameter

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 rampup_begin_step=0, rampup_step=1, sparsity=(0.999,),
                 parameters=None, use_nesterov=False, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, momentum, parameters, use_nesterov,
                         weight_decay, grad_clip, name=name)
        self._rampup_begin_step = float(rampup_begin_step)
        self._rampup_step = max(1.0, float(rampup_step))
        if not isinstance(sparsity, (list, tuple)):
            sparsity = [sparsity]
        self._sparsity_schedule = tuple(float(s) for s in sparsity)

    def init_state(self, param):
        z = jnp.zeros(param.data.shape, jnp.float32)
        return {'u': z, 'v': z, 'step': jnp.zeros((), jnp.float32)}

    def update(self, param, grad, state, lr):
        step = state['step']
        u = self._momentum * state['u'] + grad       # momentum correction
        corrected = grad + self._momentum * u if self._use_nesterov else u
        v = state['v'] + corrected
        # rampup sparsity schedule (dgc paper / reference warm-up):
        # sparsity steps through the list once every rampup_step steps
        sched = jnp.asarray(self._sparsity_schedule, jnp.float32)
        idx = jnp.clip(((step - self._rampup_begin_step)
                        / self._rampup_step).astype(jnp.int32),
                       0, len(self._sparsity_schedule) - 1)
        sp = sched[idx]
        thr = jnp.quantile(jnp.abs(v.reshape(-1)), sp)
        mask = (jnp.abs(v) >= thr).astype(v.dtype)
        ramping = step >= self._rampup_begin_step
        mask = jnp.where(ramping, mask, jnp.ones_like(mask))
        enc = v * mask                               # the communicated part
        new_p = param - lr * enc
        return new_p, {'u': u * (1 - mask), 'v': v * (1 - mask),
                       'step': step + 1}


class Adagrad(Optimizer):
    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate, epsilon=1e-06, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def init_state(self, param):
        return {'moment': jnp.full(param.data.shape, self._init_acc,
                                   jnp.float32)}

    def update(self, param, grad, state, lr):
        m = state['moment'] + grad * grad
        new_p = param - lr * grad / (jnp.sqrt(m) + self._epsilon)
        return new_p, {'moment': m}


class RMSProp(Optimizer):
    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate, rho=0.95, epsilon=1e-06, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def init_state(self, param):
        s = {'mean_square': jnp.zeros(param.data.shape, jnp.float32),
             'momentum': jnp.zeros(param.data.shape, jnp.float32)}
        if self._centered:
            s['mean_grad'] = jnp.zeros(param.data.shape, jnp.float32)
        return s

    def update(self, param, grad, state, lr):
        ms = self._rho * state['mean_square'] + (1 - self._rho) * grad * grad
        new_state = {'mean_square': ms}
        if self._centered:
            mg = self._rho * state['mean_grad'] + (1 - self._rho) * grad
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
            new_state['mean_grad'] = mg
        else:
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * state['momentum'] + lr * grad / denom
        new_state['momentum'] = mom
        return param - mom, new_state


class Adam(Optimizer):
    """Parity: operators/optimizers/adam_op (with beta-power accumulators)."""

    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, lazy_mode=False, multi_precision=True,
                 name=None, moment_dtype=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        # moment_dtype='bfloat16' halves optimizer-state HBM: moments are
        # STORED low-precision but the update math always runs in fp32
        # (casts fuse into the update kernel, so the fp32 round-trip costs
        # registers, not bandwidth). This is how 1.3B-param Adam state fits
        # one 16G v5e chip (fp32 moments alone would be 10.4G).
        self._moment_dtype = jnp.dtype(moment_dtype) if moment_dtype \
            else jnp.float32

    def init_state(self, param):
        return {'moment1': jnp.zeros(param.data.shape, self._moment_dtype),
                'moment2': jnp.zeros(param.data.shape, self._moment_dtype),
                'beta1_pow': jnp.asarray(1.0, jnp.float32),
                'beta2_pow': jnp.asarray(1.0, jnp.float32)}

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        mdt = state['moment1'].dtype
        m1 = b1 * state['moment1'].astype(jnp.float32) + (1 - b1) * grad
        m2 = b2 * state['moment2'].astype(jnp.float32) \
            + (1 - b2) * grad * grad
        b1p = state['beta1_pow'] * b1
        b2p = state['beta2_pow'] * b2
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        new_p = param - lr_t * m1 / (jnp.sqrt(m2) + eps)
        return new_p, {'moment1': m1.astype(mdt), 'moment2': m2.astype(mdt),
                       'beta1_pow': b1p, 'beta2_pow': b2p}


class AdamW(Adam):
    """Parity: operators/optimizers/adamw_op — decoupled weight decay."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=0.01,
                 lr_ratio=None, apply_decay_param_fun=None, grad_clip=None,
                 lazy_mode=False, multi_precision=True, name=None,
                 moment_dtype=None, **kwargs):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         None, grad_clip, lazy_mode, multi_precision, name,
                         moment_dtype=moment_dtype)
        self._coeff = float(weight_decay) if not hasattr(weight_decay,
                                                         '_coeff') \
            else weight_decay._coeff
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decay_into_grad(self):
        return False

    def update(self, param, grad, state, lr):
        decayed = param * (1.0 - lr * self._coeff) if self._cur_decay \
            else param
        new_p, new_state = super().update(decayed, grad, state, lr)
        return new_p, new_state

    _cur_decay = True

    def _apply_params_grads(self, params_grads):
        if self._apply_decay_param_fun is None:
            self._cur_decay = True
            super()._apply_params_grads(params_grads)
            return
        for p, g in params_grads:
            self._cur_decay = bool(self._apply_decay_param_fun(p.name))
            super()._apply_params_grads([(p, g)])


class Adamax(Optimizer):
    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-08, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon

    def init_state(self, param):
        return {'moment': jnp.zeros(param.data.shape, jnp.float32),
                'inf_norm': jnp.zeros(param.data.shape, jnp.float32),
                'beta1_pow': jnp.asarray(1.0, jnp.float32)}

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m = b1 * state['moment'] + (1 - b1) * grad
        u = jnp.maximum(b2 * state['inf_norm'], jnp.abs(grad))
        b1p = state['beta1_pow'] * b1
        new_p = param - lr / (1 - b1p) * m / (u + eps)
        return new_p, {'moment': m, 'inf_norm': u, 'beta1_pow': b1p}


class Lamb(Optimizer):
    """Parity: operators/optimizers/lamb_op — layerwise trust ratio."""

    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01,
                 beta1=0.9, beta2=0.999, epsilon=1e-06, parameters=None,
                 grad_clip=None, exclude_from_weight_decay_fn=None,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, None, grad_clip, name)
        self._beta1, self._beta2, self._epsilon = beta1, beta2, epsilon
        self._lamb_decay = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn

    def init_state(self, param):
        return {'moment1': jnp.zeros(param.data.shape, jnp.float32),
                'moment2': jnp.zeros(param.data.shape, jnp.float32),
                'beta1_pow': jnp.asarray(1.0, jnp.float32),
                'beta2_pow': jnp.asarray(1.0, jnp.float32)}

    def update(self, param, grad, state, lr):
        b1, b2, eps = self._beta1, self._beta2, self._epsilon
        m1 = b1 * state['moment1'] + (1 - b1) * grad
        m2 = b2 * state['moment2'] + (1 - b2) * grad * grad
        b1p = state['beta1_pow'] * b1
        b2p = state['beta2_pow'] * b2
        m1_hat = m1 / (1 - b1p)
        m2_hat = m2 / (1 - b2p)
        r = m1_hat / (jnp.sqrt(m2_hat) + eps)
        decay = self._lamb_decay
        if self._exclude_fn is not None and self._cur_param_name is not None \
                and self._exclude_fn(self._cur_param_name):
            decay = 0.0
        update_ = r + decay * param
        w_norm = jnp.sqrt(jnp.sum(param * param))
        u_norm = jnp.sqrt(jnp.sum(update_ * update_))
        trust = jnp.where((w_norm > 0) & (u_norm > 0), w_norm / u_norm, 1.0)
        new_p = param - lr * trust * update_
        return new_p, {'moment1': m1, 'moment2': m2, 'beta1_pow': b1p,
                       'beta2_pow': b2p}

    _cur_param_name = None

    def _apply_params_grads(self, params_grads):
        for p, g in params_grads:
            self._cur_param_name = p.name
            super()._apply_params_grads([(p, g)])
        self._cur_param_name = None


class Adadelta(Optimizer):
    """Parity: operators/optimizers/adadelta_op — accumulated-gradient /
    accumulated-update RMS ratio rule."""

    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate=0.001, epsilon=1e-06, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._epsilon = epsilon
        self._rho = rho

    def init_state(self, param):
        return {'avg_squared_grad': jnp.zeros(param.data.shape,
                                              jnp.float32),
                'avg_squared_update': jnp.zeros(param.data.shape,
                                                jnp.float32)}

    def update(self, param, grad, state, lr):
        rho, eps = self._rho, self._epsilon
        g2 = rho * state['avg_squared_grad'] + (1 - rho) * grad * grad
        upd = grad * jnp.sqrt(state['avg_squared_update'] + eps) \
            / jnp.sqrt(g2 + eps)
        u2 = rho * state['avg_squared_update'] + (1 - rho) * upd * upd
        return param - lr * upd, {'avg_squared_grad': g2,
                                  'avg_squared_update': u2}


class DecayedAdagrad(Optimizer):
    """Parity: operators/optimizers/decayed_adagrad_op."""

    _elementwise = True
    _pallas_fusible = True

    def __init__(self, learning_rate, decay=0.95, epsilon=1e-06,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._decay = decay
        self._epsilon = epsilon

    def init_state(self, param):
        return {'moment': jnp.zeros(param.data.shape, jnp.float32)}

    def update(self, param, grad, state, lr):
        m = self._decay * state['moment'] \
            + (1 - self._decay) * grad * grad
        return param - lr * grad / (jnp.sqrt(m) + self._epsilon), \
            {'moment': m}


class Ftrl(Optimizer):
    """Parity: operators/optimizers/ftrl_op — follow-the-regularized-
    leader (McMahan et al.), the classic sparse-LR CTR optimizer."""

    _elementwise = True

    def __init__(self, learning_rate, l1=0.0, l2=0.0, lr_power=-0.5,
                 parameters=None, weight_decay=None, grad_clip=None,
                 name=None, **kwargs):
        super().__init__(learning_rate, parameters, weight_decay,
                         grad_clip, name)
        self._l1, self._l2, self._lr_power = l1, l2, lr_power

    def init_state(self, param):
        return {'squared': jnp.zeros(param.data.shape, jnp.float32),
                'linear': jnp.zeros(param.data.shape, jnp.float32)}

    def update(self, param, grad, state, lr):
        l1, l2, p = self._l1, self._l2, self._lr_power
        n, z = state['squared'], state['linear']
        n_new = n + grad * grad
        sigma = (jnp.power(n_new, -p) - jnp.power(n, -p)) / lr
        z_new = z + grad - sigma * param
        new_p = jnp.where(
            jnp.abs(z_new) <= l1,
            jnp.zeros_like(param),
            (jnp.sign(z_new) * l1 - z_new)
            / (jnp.power(n_new, -p) / lr + 2 * l2))
        return new_p, {'squared': n_new, 'linear': z_new}


class Lars(Momentum):
    """Parity: operators/optimizers/lars_momentum_op."""

    _elementwise = False   # layerwise trust ratio is per-parameter

    def __init__(self, learning_rate=0.001, momentum=0.9,
                 lars_coeff=0.001, lars_weight_decay=0.0005, parameters=None,
                 grad_clip=None, exclude_from_weight_decay=None, epsilon=0,
                 name=None, **kwargs):
        super().__init__(learning_rate, momentum, parameters, False, None,
                         grad_clip, name=name)
        self._lars_coeff = lars_coeff
        self._lars_decay = lars_weight_decay
        self._lars_eps = epsilon

    def update(self, param, grad, state, lr):
        w_norm = jnp.sqrt(jnp.sum(param * param))
        g_norm = jnp.sqrt(jnp.sum(grad * grad))
        local_lr = jnp.where(
            (w_norm > 0) & (g_norm > 0),
            self._lars_coeff * w_norm /
            (g_norm + self._lars_decay * w_norm + self._lars_eps), 1.0)
        g = grad + self._lars_decay * param
        v = self._momentum * state['velocity'] + lr * local_lr * g
        return param - v, {'velocity': v}


LarsMomentum = Lars
