"""paddle_tpu.device — device queries.

Reference parity: python/paddle/device + platform/device_context (N1). Device
lifetime is owned by PJRT through jax; this module exposes the paddle-shaped
query surface.
"""
import jax

from ..framework import set_device, get_device, CPUPlace, CUDAPlace, TPUPlace


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return ['tpu']


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return get_available_device()


def device_count():
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()


def is_compiled_with_cinn():
    return False


def XPUPlace(idx=0):
    return TPUPlace(idx)


class cuda:
    """paddle.device.cuda namespace compat (maps to the TPU device)."""

    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        for d in jax.live_arrays():
            d.block_until_ready()

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get('peak_bytes_in_use', 0)

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get('bytes_in_use', 0)


def synchronize():
    cuda.synchronize()
