"""paddle_tpu.device — device queries.

Reference parity: python/paddle/device + platform/device_context (N1). Device
lifetime is owned by PJRT through jax; this module exposes the paddle-shaped
query surface.
"""
import jax

from ..framework import set_device, get_device, CPUPlace, CUDAPlace, TPUPlace


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def get_all_custom_device_type():
    return ['tpu']


def get_available_device():
    return [f"{d.platform}:{d.id}" for d in jax.devices()]


def get_available_custom_device():
    return get_available_device()


def device_count():
    return jax.device_count()


def local_device_count():
    return jax.local_device_count()


def is_compiled_with_cinn():
    return False


def XPUPlace(idx=0):
    return TPUPlace(idx)


class cuda:
    """paddle.device.cuda namespace compat (maps to the TPU device)."""

    @staticmethod
    def device_count():
        return jax.device_count()

    @staticmethod
    def synchronize(device=None):
        import jax
        for d in jax.live_arrays():
            d.block_until_ready()

    @staticmethod
    def max_memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get('peak_bytes_in_use', 0)

    @staticmethod
    def memory_allocated(device=None):
        stats = jax.devices()[0].memory_stats() or {}
        return stats.get('bytes_in_use', 0)


def synchronize():
    cuda.synchronize()


class Stream:
    """Parity: paddle.device.Stream / cuda.Stream — XLA owns ordering on
    TPU (one compute stream per core; programs are totally ordered), so
    streams are recorded-no-op handles whose sync points map to
    block_until_ready."""

    def __init__(self, device=None, priority=2):
        self.device = device
        self.priority = priority

    def synchronize(self):
        synchronize()

    def wait_event(self, event):
        event.synchronize()

    def wait_stream(self, stream):
        stream.synchronize()

    def record_event(self, event=None):
        event = event or Event()
        event.record(self)
        return event

    def query(self):
        return True


class Event:
    """Parity: paddle.device.Event — timestamps via host clock (device
    programs are serially ordered under XLA, so host timing at sync
    points is the faithful analogue)."""

    def __init__(self, enable_timing=False, blocking=False,
                 interprocess=False):
        self.enable_timing = enable_timing
        self._t = None

    def record(self, stream=None):
        import time as _time
        synchronize()
        self._t = _time.perf_counter()

    def synchronize(self):
        synchronize()

    def query(self):
        return True

    def elapsed_time(self, end_event):
        if self._t is None or end_event._t is None:
            raise RuntimeError("Event.record() not called")
        return (end_event._t - self._t) * 1000.0


def current_stream(device=None):
    return Stream(device)


def set_stream(stream):
    return stream


cuda.Stream = Stream
cuda.Event = Event
cuda.current_stream = staticmethod(current_stream)
cuda.stream_guard = None


class _StreamGuard:
    def __init__(self, stream):
        self.stream = stream

    def __enter__(self):
        return self.stream

    def __exit__(self, *a):
        return False


def stream_guard(stream):
    """Parity: paddle.device.stream_guard (no-op scheduling scope)."""
    return _StreamGuard(stream)


cuda.stream_guard = staticmethod(stream_guard)
