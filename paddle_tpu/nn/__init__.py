"""paddle_tpu.nn — layers + functional.

Reference parity: python/paddle/nn/__init__.py surface.
"""
from . import functional
from . import initializer
from .layer.base import Layer, ParamAttr
from .layer.common import (Linear, Embedding, Dropout, Dropout2D,
                           AlphaDropout, Flatten, Identity, Pad1D, Pad2D,
                           Pad3D, Upsample, UpsamplingBilinear2D,
                           UpsamplingNearest2D, Bilinear, CosineSimilarity,
                           Unfold, PixelShuffle, PixelUnshuffle,
                           ChannelShuffle, Fold, GLU, ZeroPad2D)
from .layer.container import (Sequential, LayerList, LayerDict,
                              ParameterList)
from .layer.conv import (Conv1D, Conv2D, Conv3D, Conv2DTranspose,
                         Conv1DTranspose)
from .layer.norm import (BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D,
                         SyncBatchNorm, LayerNorm, GroupNorm, InstanceNorm1D,
                         InstanceNorm2D, InstanceNorm3D, LocalResponseNorm,
                         SpectralNorm)
from .layer.activation import (ReLU, ReLU6, Sigmoid, Tanh, GELU, ELU, SELU,
                               CELU, Silu, Swish, Mish, Hardswish,
                               Hardsigmoid, Hardshrink, Hardtanh, Softshrink,
                               Softplus, Softsign, Tanhshrink,
                               ThresholdedReLU, LogSigmoid, Maxout, LeakyReLU,
                               PReLU, Softmax, LogSoftmax)
from .layer.pooling import (AvgPool1D, AvgPool2D, MaxPool1D, MaxPool2D,
                            AdaptiveAvgPool1D, AdaptiveAvgPool2D,
                            AdaptiveMaxPool2D)
from .layer.loss import (CrossEntropyLoss, MSELoss, L1Loss, NLLLoss, BCELoss,
                         BCEWithLogitsLoss, KLDivLoss, SmoothL1Loss,
                         MarginRankingLoss, TripletMarginLoss,
                         CosineEmbeddingLoss, SoftMarginLoss,
                         MultiMarginLoss, CTCLoss)
from .layer.transformer import (MultiHeadAttention, TransformerEncoderLayer,
                                TransformerEncoder, TransformerDecoderLayer,
                                TransformerDecoder, Transformer)
from .layer.rnn import (RNNCellBase, SimpleRNNCell, LSTMCell, GRUCell, RNN,
                        SimpleRNN, LSTM, GRU, BiRNN)
from .clip import (ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm,
                   clip_grad_norm_)

from .decode import (Decoder, BeamSearchDecoder, dynamic_decode,  # noqa
                     DecodeHelper, TrainingHelper,
                     GreedyEmbeddingHelper, SampleEmbeddingHelper,
                     BasicDecoder)

from .layer.pooling import (MaxPool3D, AvgPool3D, AdaptiveAvgPool3D,  # noqa
                            AdaptiveMaxPool1D, AdaptiveMaxPool3D)
from .layer.conv import Conv3DTranspose  # noqa
from .layer.common import Dropout3D, PairwiseDistance  # noqa
from .layer.loss import HSigmoidLoss  # noqa
