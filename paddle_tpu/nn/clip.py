"""Gradient clipping.

Reference parity: python/paddle/fluid/clip.py — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm; applied by optimizers over
params_grads before the update (optimizer.py _create_optimization_pass).
"""
import jax.numpy as jnp

from ..core.tensor import Tensor


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g.data.astype(jnp.float32) ** 2))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                 1.0)
            out.append((p, Tensor((g.data * factor).astype(g.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Parity: fluid/clip.py GradientClipByGlobalNorm. The hybrid-parallel
    variant (TP/PP-aware partial norms + cross-mesh allreduce, reference
    hybrid_parallel_optimizer.py:32) lives in
    distributed/fleet/meta_optimizers/dygraph_optimizer."""

    def __init__(self, clip_norm=1.0, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def global_norm(self, params_grads):
        sq = 0.0
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                continue
            sq = sq + jnp.sum(g.data.astype(jnp.float32) ** 2)
        return jnp.sqrt(sq)

    def __call__(self, params_grads):
        gn = self.global_norm(params_grads)
        factor = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g.data.astype(jnp.float32) * factor)
                                  .astype(g.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    if norm_type == float('inf'):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g.data)) for g in grads]))
    else:
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(g.data.astype(jnp.float32)),
                                  norm_type)) for g in grads),
            1.0 / norm_type)
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad.data = (p.grad.data * factor).astype(p.grad.dtype)
    return Tensor(total)
