"""Gradient clipping.

Reference parity: python/paddle/fluid/clip.py — ClipGradByValue,
ClipGradByNorm, ClipGradByGlobalNorm; applied by optimizers over
params_grads before the update (optimizer.py _create_optimization_pass).
"""
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor


def _publish_preclip_norm(norm, site):
    """Numerics observatory: the pre-clip global grad norm is the
    canonical training-health signal — publish it whenever it is a
    concrete value (never under a jit trace) and stats are asked for."""
    if isinstance(norm, jax.core.Tracer):
        return None
    from ..core.flags import flag
    if not (flag('FLAGS_tensor_stats') or flag('FLAGS_check_nan_inf')):
        return None
    if flag('FLAGS_tensor_stats'):
        # inside optimizer.step the numerics boundary already published
        # this step's pre-clip global norm from its batched sync —
        # publishing again here would add a SECOND host sync per step
        from ..core import memory as _mem
        if _mem.accountant().current_phase() == 'optimizer.step':
            return None
    val = float(norm)       # the one host sync this publication costs
    from ..core import monitor as _m
    _m.gauge('ptpu_num_grad_norm_global',
             help='global (all-parameter) gradient l2 norm').set(val)
    _m.gauge('ptpu_num_grad_norm_preclip',
             help='pre-clip global gradient norm per clip site',
             labelnames=('site',)).set(val, site=site)
    return val


class ClipGradBase:
    def __call__(self, params_grads):
        raise NotImplementedError


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g.data, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def __call__(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(g.data.astype(jnp.float32) ** 2))
            factor = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12),
                                 1.0)
            out.append((p, Tensor((g.data * factor).astype(g.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    """Parity: fluid/clip.py GradientClipByGlobalNorm. The hybrid-parallel
    variant (TP/PP-aware partial norms + cross-mesh allreduce, reference
    hybrid_parallel_optimizer.py:32) lives in
    distributed/fleet/meta_optimizers/dygraph_optimizer."""

    def __init__(self, clip_norm=1.0, group_name="default_group"):
        self.clip_norm = float(clip_norm)
        self.group_name = group_name

    def global_norm(self, params_grads):
        sq = 0.0
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                continue
            sq = sq + jnp.sum(g.data.astype(jnp.float32) ** 2)
        return jnp.sqrt(sq)

    def __call__(self, params_grads):
        gn = self.global_norm(params_grads)
        _publish_preclip_norm(gn, 'global_norm_clip')
        factor = self.clip_norm / jnp.maximum(gn, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None or not getattr(p, 'need_clip', True):
                out.append((p, g))
                continue
            out.append((p, Tensor((g.data.astype(jnp.float32) * factor)
                                  .astype(g.dtype))))
        return out


def clip_grad_norm_(parameters, max_norm, norm_type=2.0,
                    error_if_nonfinite=False):
    """Parity: paddle.nn.utils.clip_grad_norm_ — in-place global-norm
    clip returning the pre-clip total norm. With `error_if_nonfinite`
    a NaN/Inf total norm raises instead of silently scaling every grad
    to NaN (paddle 2.x behavior).

    Bucketed (ISSUE 4): the norm reduces over the flat gradient
    buckets (core/bucketing.py) — a handful of fused reductions
    instead of one per parameter; the nonfinite check is the single
    host sync, routed through the numerics fetch hook, and the
    publication below keeps the PR-3 dedup against the optimizer-step
    boundary."""
    if isinstance(parameters, Tensor):
        parameters = [parameters]
    grads = [p.grad for p in parameters if p.grad is not None]
    if not grads:
        return Tensor(jnp.asarray(0.0))
    from ..core import bucketing as B
    _, flats = B.flatten_grad_list(grads)
    if norm_type == float('inf'):
        total = jnp.max(jnp.stack(
            [jnp.max(jnp.abs(f.astype(jnp.float32))) for f in flats]))
    else:
        # bucket padding is exactly 0 and |0|^p contributes nothing
        total = jnp.power(
            sum(jnp.sum(jnp.power(jnp.abs(f.astype(jnp.float32)),
                                  norm_type)) for f in flats),
            1.0 / norm_type)
    if error_if_nonfinite and not isinstance(total, jax.core.Tracer):
        from ..core import numerics as _num
        if not bool(_num._host_fetch(jnp.isfinite(total))):
            raise RuntimeError(
                f"The total norm of order {norm_type} for gradients from "
                "`parameters` is non-finite, so it cannot be clipped. To "
                "disable this error and scale the gradients by the "
                "non-finite norm anyway, set `error_if_nonfinite=False`")
    _publish_preclip_norm(total, 'clip_grad_norm_')
    factor = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p.grad is not None:
            p.grad.data = (p.grad.data * factor).astype(p.grad.dtype)
    return Tensor(total)
