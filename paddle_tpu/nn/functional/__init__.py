"""paddle_tpu.nn.functional — functional API.

Reference parity: python/paddle/nn/functional (11 modules re-exported flat).
"""
from ...ops.nn_ops import *  # noqa
from ...ops.nn_ops import (  # explicit for linters
    relu, relu6, gelu, elu, selu, celu, silu, swish, mish, leaky_relu, prelu,
    softplus, softsign, hardsigmoid, hardswish, hardtanh, hardshrink,
    softshrink, tanhshrink, thresholded_relu, log_sigmoid, maxout, softmax,
    log_softmax, gumbel_softmax, layer_norm, batch_norm, group_norm,
    instance_norm, local_response_norm, normalize, linear, conv1d, conv2d,
    conv3d, conv2d_transpose, avg_pool1d, avg_pool2d, max_pool1d, max_pool2d,
    adaptive_avg_pool2d, adaptive_max_pool2d, unfold, dropout, dropout2d,
    alpha_dropout, embedding, softmax_with_cross_entropy, cross_entropy,
    nll_loss, mse_loss, l1_loss, smooth_l1_loss, binary_cross_entropy,
    binary_cross_entropy_with_logits, sigmoid_cross_entropy_with_logits,
    kl_div, hinge_loss, margin_ranking_loss, log_loss, square_error_cost,
    cosine_similarity, label_smooth, interpolate, upsample, grid_sample,
    affine_grid, fused_softmax_mask_upper_triangle, temporal_shift,
    npair_loss, one_hot, sequence_mask,
)
from ...ops.nn_ops import (  # noqa
    triplet_margin_loss, cosine_embedding_loss, soft_margin_loss,
    multi_margin_loss, ctc_loss, glu, pairwise_distance, pixel_unshuffle,
    channel_shuffle, fold)
from ...ops.nn_ops import bias_gelu, dropout_add  # noqa — fused Pallas
# primitives (docs/performance.md#fused-primitives): transformer blocks
# route through these so the bias+GELU / dropout+residual fusions engage
# on TPU without model changes
from ...ops.math import sigmoid, tanh  # noqa
from ...ops.manip import pad, pixel_shuffle  # noqa


def diag_embed(*a, **k):
    from ...ops.math import diag_embed as _d
    return _d(*a, **k)


def gather_tree(ids, parents):
    from ...ops.contrib import gather_tree as _gt
    return _gt(ids, parents)

from ...ops.nn_ops import (  # noqa — r4 sheet remainder
    max_pool3d, avg_pool3d, adaptive_avg_pool1d, adaptive_max_pool1d,
    adaptive_avg_pool3d, adaptive_max_pool3d, conv1d_transpose,
    conv3d_transpose, bilinear, dropout3d, dice_loss,
    sigmoid_focal_loss, relu_, softmax_)
from ...ops.contrib import hsigmoid_loss  # noqa


def tanh_(x, name=None):
    # single source of the in-place contract: the top-level spelling
    from ...api_tail import tanh_ as _impl
    return _impl(x, name=name)
