"""Weight initializers.

Reference parity: python/paddle/nn/initializer + fluid/initializer.py
(Constant/Uniform/Normal/TruncatedNormal/Xavier/KaimingMSRA/Assign).
Initializers are callables: (shape, dtype) -> jax array, drawing from the
global RNG stream (core/rng.py).
"""
import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core import rng, dtypes
from ..core.tensor import Tensor


class Initializer:
    def __call__(self, shape, dtype=jnp.float32):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        return jnp.full(tuple(shape), self.value, dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0):
        self.low, self.high = low, high

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.uniform(rng.next_key(), tuple(shape), dtype,
                                  self.low, self.high)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        return jax.random.normal(rng.next_key(), tuple(shape), dtype) \
            * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0):
        self.mean, self.std = mean, std

    def __call__(self, shape, dtype=jnp.float32):
        out = jax.random.truncated_normal(rng.next_key(), -2.0, 2.0,
                                          tuple(shape), dtype)
        return out * self.std + self.mean


def _fan_in_out(shape):
    shape = tuple(shape)
    if len(shape) == 2:
        fan_in, fan_out = shape[0], shape[1]
    elif len(shape) >= 3:
        rf = int(np.prod(shape[2:]))
        fan_in, fan_out = shape[1] * rf, shape[0] * rf
    else:
        fan_in = fan_out = int(np.prod(shape)) if shape else 1
    return fan_in, fan_out


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(rng.next_key(), tuple(shape), dtype,
                                  -limit, limit)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def __call__(self, shape, dtype=jnp.float32):
        fi, fo = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return jax.random.normal(rng.next_key(), tuple(shape), dtype) * std


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in = fan_in
        self.negative_slope = negative_slope

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        limit = math.sqrt(6.0 / fi)
        return jax.random.uniform(rng.next_key(), tuple(shape), dtype,
                                  -limit, limit)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity='relu'):
        self.fan_in = fan_in

    def __call__(self, shape, dtype=jnp.float32):
        fi, _ = _fan_in_out(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        std = math.sqrt(2.0 / fi)
        return jax.random.normal(rng.next_key(), tuple(shape), dtype) * std


class Assign(Initializer):
    def __init__(self, value):
        self.value = value

    def __call__(self, shape, dtype=jnp.float32):
        v = self.value
        if isinstance(v, Tensor):
            v = v.data
        return jnp.asarray(np.asarray(v), dtype).reshape(tuple(shape))


class Orthogonal(Initializer):
    def __init__(self, gain=1.0):
        self.gain = gain

    def __call__(self, shape, dtype=jnp.float32):
        return jax.nn.initializers.orthogonal(scale=self.gain)(
            rng.next_key(), tuple(shape), dtype)


class Dirac(Initializer):
    def __init__(self, groups=1):
        self.groups = groups

    def __call__(self, shape, dtype=jnp.float32):
        out = np.zeros(shape, dtype=np.float32)
        oc, ic = shape[0], shape[1]
        centers = [s // 2 for s in shape[2:]]
        for i in range(min(oc, ic)):
            out[(i, i) + tuple(centers)] = 1.0
        return jnp.asarray(out, dtype)


# Default initializer used by layers when weight_attr is None — matches
# fluid's default XavierInitializer for weights, Constant(0) for bias.
def _default_weight_init():
    return XavierUniform()


def _default_bias_init():
    return Constant(0.0)


def calculate_gain(nonlinearity, param=None):
    gains = {'sigmoid': 1.0, 'linear': 1.0, 'conv2d': 1.0, 'tanh': 5.0 / 3,
             'relu': math.sqrt(2.0), 'selu': 3.0 / 4}
    if nonlinearity == 'leaky_relu':
        a = param if param is not None else 0.01
        return math.sqrt(2.0 / (1 + a ** 2))
    return gains.get(nonlinearity, 1.0)
