"""Seq2seq decoding: Decoder protocol, BeamSearchDecoder, dynamic_decode.

Reference parity: python/paddle/fluid/layers/rnn.py (Decoder:~1040,
BeamSearchDecoder:~1190, dynamic_decode:~1720) / paddle.nn.dynamic_decode.

TPU-native shape discipline: beams live as one flattened [B*W, ...]
batch through the cell (one matmul batch, no per-beam loop); the step
loop runs eagerly with early stop on all-finished — the compiled
one-dispatch analogue for generation-heavy serving is
models.gpt.generate_scan (PARITY.md decode section), while this class
mirrors the reference's modular decoder contract for seq2seq models.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..ops.common import as_tensor


class Decoder:
    """The reference's decoder contract: initialize() ->
    (initial_inputs, initial_states, initial_finished); step() ->
    (outputs, next_states, next_inputs, finished); optional
    finalize()."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        raise NotImplementedError

    @property
    def tracks_own_finished(self):
        return False


class BeamSearchDecoder(Decoder):
    """Beam search over a single-step RNN cell (fluid/layers/rnn.py
    BeamSearchDecoder). `cell(inputs, states) -> (outputs, states)`;
    `embedding_fn` maps token ids -> cell inputs; `output_fn` maps cell
    outputs -> vocab logits.

    Finished beams are frozen: they can only emit `end_token` at
    log-prob 0, so their cumulative score stops changing (the
    reference's _mask_probs). Finalize backtraces parent pointers with
    gather_tree."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    # -- beam/batch reshaping helpers (merge_batch_beams etc.) ----------
    def _merge(self, x):
        a = x.data if isinstance(x, Tensor) else x
        return Tensor(a.reshape((-1,) + tuple(a.shape[2:])))

    def _split(self, x):
        a = x.data if isinstance(x, Tensor) else x
        return Tensor(a.reshape((-1, self.beam_size)
                                + tuple(a.shape[1:])))

    def _expand_to_beams(self, x):
        a = x.data if isinstance(x, Tensor) else jnp.asarray(x)
        tiled = jnp.repeat(a[:, None], self.beam_size, axis=1)
        return Tensor(tiled.reshape((-1,) + tuple(a.shape[1:])))

    def initialize(self, initial_cell_states):
        states = jax.tree_util.tree_map(
            self._expand_to_beams, initial_cell_states,
            is_leaf=lambda t: isinstance(t, Tensor))
        leaf = jax.tree_util.tree_leaves(states)[0]
        BW = leaf.data.shape[0] if isinstance(leaf, Tensor) \
            else leaf.shape[0]
        B = BW // self.beam_size
        self._batch = B
        tokens = jnp.full((BW,), self.start_token, jnp.int32)
        inputs = self.embedding_fn(Tensor(tokens)) \
            if self.embedding_fn else Tensor(tokens)
        # beam 0 starts live, the rest at -inf so step 1 fans out from
        # a single hypothesis per example
        lp = jnp.full((B, self.beam_size), -1e9, jnp.float32)
        lp = lp.at[:, 0].set(0.0)
        finished = jnp.zeros((B, self.beam_size), bool)
        return inputs, {'cell': states, 'log_probs': lp,
                        'finished': finished,
                        'lengths': jnp.zeros((B, self.beam_size),
                                             jnp.int32)}, finished

    def step(self, time, inputs, states, **kwargs):
        B, W = self._batch, self.beam_size
        cell_out, next_cell = self.cell(inputs, states['cell'])
        logits = self.output_fn(cell_out) if self.output_fn else cell_out
        logits = logits.data if isinstance(logits, Tensor) else logits
        V = logits.shape[-1]
        step_lp = jax.nn.log_softmax(logits, axis=-1).reshape(B, W, V)

        finished = states['finished']
        # frozen finished beams: only end_token, at log-prob 0
        frozen = jnp.full((V,), -1e9, step_lp.dtype) \
            .at[self.end_token].set(0.0)
        step_lp = jnp.where(finished[..., None], frozen[None, None, :],
                            step_lp)
        total = states['log_probs'][..., None] + step_lp     # [B, W, V]
        flat = total.reshape(B, W * V)
        scores, idx = jax.lax.top_k(flat, W)                 # [B, W]
        parent = (idx // V).astype(jnp.int32)
        token = (idx % V).astype(jnp.int32)

        # reorder beam state by surviving parents
        gather = (jnp.arange(B)[:, None] * W + parent).reshape(-1)

        def pick(t):
            a = t.data if isinstance(t, Tensor) else t
            return Tensor(a[gather])
        next_cell = jax.tree_util.tree_map(
            pick, next_cell, is_leaf=lambda t: isinstance(t, Tensor))
        was_done = jnp.take_along_axis(finished, parent, axis=1)
        now_done = was_done | (token == self.end_token)
        lengths = jnp.take_along_axis(states['lengths'], parent, axis=1)
        lengths = jnp.where(was_done, lengths, lengths + 1)

        next_inputs = self.embedding_fn(Tensor(token.reshape(-1))) \
            if self.embedding_fn else Tensor(token.reshape(-1))
        outputs = {'scores': Tensor(scores), 'predicted_ids':
                   Tensor(token), 'parent_ids': Tensor(parent)}
        next_states = {'cell': next_cell, 'log_probs': scores,
                       'finished': now_done, 'lengths': lengths}
        return outputs, next_states, next_inputs, now_done

    def finalize(self, outputs, final_states, sequence_lengths):
        from ..ops.contrib import gather_tree
        ids = outputs['predicted_ids']          # [T, B, W]
        parents = outputs['parent_ids']
        seqs = gather_tree(ids, parents)
        return {'scores': outputs['scores'], 'predicted_ids': seqs}, \
            final_states

    @property
    def tracks_own_finished(self):
        return True


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """fluid/layers/rnn.py dynamic_decode: drive decoder.step until
    every sequence finishes or `max_step_num`; stack per-step outputs
    along time and run decoder.finalize. Returns (outputs, final_states
    [, sequence_lengths])."""
    inputs, states, finished = decoder.initialize(inits)
    outputs_per_step = []
    step = 0
    max_steps = int(max_step_num) if max_step_num is not None else 256
    fin = finished.data if isinstance(finished, Tensor) else finished
    while step < max_steps and not bool(jnp.all(fin)):
        out, states, inputs, fin = decoder.step(step, inputs, states,
                                                **kwargs)
        fin = fin.data if isinstance(fin, Tensor) else fin
        outputs_per_step.append(out)
        step += 1

    def stack(*leaves):
        arrs = [l.data if isinstance(l, Tensor) else l for l in leaves]
        return Tensor(jnp.stack(arrs, axis=0))     # time-major [T, ...]
    outputs = jax.tree_util.tree_map(
        stack, *outputs_per_step,
        is_leaf=lambda t: isinstance(t, Tensor)) \
        if outputs_per_step else {}

    seq_len = states.get('lengths') if isinstance(states, dict) else None
    try:
        outputs, final_states = decoder.finalize(outputs, states,
                                                 seq_len)
    except NotImplementedError:
        final_states = states

    if not output_time_major:
        def to_batch_major(t):
            a = t.data if isinstance(t, Tensor) else t
            if a.ndim >= 2:
                perm = (1, 0) + tuple(range(2, a.ndim))
                return Tensor(a.transpose(perm))
            return Tensor(a)
        outputs = jax.tree_util.tree_map(
            to_batch_major, outputs,
            is_leaf=lambda t: isinstance(t, Tensor))
    if return_length:
        return outputs, final_states, Tensor(seq_len) \
            if seq_len is not None else None
    return outputs, final_states


class DecodeHelper:
    """Helper contract for BasicDecoder (fluid/layers/rnn.py
    DecodeHelper): initialize() -> (initial_inputs, initial_finished);
    sample(time, outputs, states) -> sample_ids;
    next_inputs(time, outputs, states, sample_ids) ->
    (finished, next_inputs, next_states)."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing: read the next input from the ground-truth
    sequence (fluid/layers/rnn.py TrainingHelper)."""

    def __init__(self, inputs, sequence_length, time_major=False):
        self.inputs = as_tensor(inputs)
        self.sequence_length = as_tensor(sequence_length)
        self.time_major = time_major
        a = self.inputs.data
        self._seq = a if time_major else jnp.swapaxes(a, 0, 1)  # [T,B,..]
        self._T = self._seq.shape[0]

    def initialize(self):
        lens = self.sequence_length.data.reshape(-1)
        finished = lens <= 0
        return Tensor(self._seq[0]), finished

    def sample(self, time, outputs, states):
        o = outputs.data if isinstance(outputs, Tensor) else outputs
        return Tensor(jnp.argmax(o, axis=-1).astype(jnp.int32))

    def next_inputs(self, time, outputs, states, sample_ids):
        nxt_t = min(time + 1, self._T - 1)
        lens = self.sequence_length.data.reshape(-1)
        finished = (time + 1) >= jnp.minimum(lens, self._T)
        return finished, Tensor(self._seq[nxt_t]), states


class GreedyEmbeddingHelper(DecodeHelper):
    """Inference-time argmax feeding (fluid/layers/rnn.py
    GreedyEmbeddingHelper)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = as_tensor(start_tokens)
        self.end_token = int(end_token)

    def initialize(self):
        toks = self.start_tokens.data.reshape(-1).astype(jnp.int32)
        finished = jnp.zeros(toks.shape, bool)
        return self.embedding_fn(Tensor(toks)), finished

    def sample(self, time, outputs, states):
        o = outputs.data if isinstance(outputs, Tensor) else outputs
        return Tensor(jnp.argmax(o, axis=-1).astype(jnp.int32))

    def next_inputs(self, time, outputs, states, sample_ids):
        ids = sample_ids.data if isinstance(sample_ids, Tensor) \
            else sample_ids
        finished = ids == self.end_token
        return finished, self.embedding_fn(Tensor(ids)), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Multinomial sampling feeding (fluid/layers/rnn.py
    SampleEmbeddingHelper)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        self.seed = seed

    def sample(self, time, outputs, states):
        from ..core import rng as rng_mod
        o = outputs.data if isinstance(outputs, Tensor) else outputs
        if self.temperature is not None:
            o = o / self.temperature
        key = rng_mod.next_key() if self.seed is None else \
            jax.random.fold_in(jax.random.PRNGKey(self.seed), time)
        return Tensor(jax.random.categorical(key, o,
                                             axis=-1).astype(jnp.int32))


class BasicDecoder(Decoder):
    """Cell + helper -> Decoder (fluid/layers/rnn.py BasicDecoder):
    each step runs the cell, lets the helper sample ids and produce the
    next inputs. Outputs dict: cell_outputs + sample_ids."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        inputs, finished = self.helper.initialize()
        return inputs, initial_cell_states, finished

    def step(self, time, inputs, states, **kwargs):
        cell_out, next_states = self.cell(inputs, states)
        if self.output_fn is not None:
            cell_out = self.output_fn(cell_out)
        sample_ids = self.helper.sample(time, cell_out, next_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, cell_out, next_states, sample_ids)
        outputs = {'cell_outputs': cell_out
                   if isinstance(cell_out, Tensor) else Tensor(cell_out),
                   'sample_ids': sample_ids}
        return outputs, next_states, next_inputs, finished

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states
