"""Recurrent layers.

Reference parity: python/paddle/nn/layer/rnn.py (SimpleRNNCell, LSTMCell,
GRUCell, RNN, BiRNN, SimpleRNN/LSTM/GRU multi-layer stacks) over
operators/rnn_op. TPU-native design: the whole time loop is ONE traced op
built on `jax.lax.scan` — compiler-friendly static control flow instead of the
reference's per-step kernel launches; grads flow through scan via jax.vjp.
Gate order matches paddle: i, f, c(g), o for LSTM; r, z(u), c for GRU.
"""
import math

import jax
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.autograd import run_op
from ...ops import math as M
from ...ops import nn_ops as F
from .. import initializer as I
from .base import Layer
from .container import LayerList


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype='float32',
                           init_value=0.0, batch_dim_idx=0):
        batch = batch_ref.shape[batch_dim_idx]
        shape = shape or self.state_shape
        if isinstance(shape, (list, tuple)) and isinstance(shape[0], (list, tuple)):
            return tuple(Tensor(jnp.full((batch,) + tuple(s), init_value))
                         for s in shape)
        return Tensor(jnp.full((batch,) + tuple(shape), init_value))


def _std_uniform(hidden_size):
    std = 1.0 / math.sqrt(hidden_size)
    return I.Uniform(-std, std)


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        self.weight_ih = self.create_parameter([hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu

        def fn(x, h, wi, wh, bi, bh):
            out = act(x @ wi.T + bi + h @ wh.T + bh)
            return out, out
        out, h = run_op('rnn_cell', fn, [inputs, states, self.weight_ih,
                                         self.weight_hh, self.bias_ih,
                                         self.bias_hh])
        return out, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.weight_ih = self.create_parameter([4 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([4 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([4 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([4 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    @property
    def state_shape(self):
        return ((self.hidden_size,), (self.hidden_size,))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h, c = states

        def fn(x, h0, c0, wi, wh, bi, bh):
            gates = x @ wi.T + bi + h0 @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            c1 = f * c0 + i * jnp.tanh(g)
            h1 = o * jnp.tanh(c1)
            return h1, h1, c1
        out, h1, c1 = run_op('lstm_cell', fn,
                             [inputs, h, c, self.weight_ih, self.weight_hh,
                              self.bias_ih, self.bias_hh])
        return out, (h1, c1)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        init = _std_uniform(hidden_size)
        self.input_size, self.hidden_size = input_size, hidden_size
        self.weight_ih = self.create_parameter([3 * hidden_size, input_size],
                                               weight_ih_attr,
                                               default_initializer=init)
        self.weight_hh = self.create_parameter([3 * hidden_size, hidden_size],
                                               weight_hh_attr,
                                               default_initializer=init)
        self.bias_ih = self.create_parameter([3 * hidden_size], bias_ih_attr,
                                             is_bias=True,
                                             default_initializer=init)
        self.bias_hh = self.create_parameter([3 * hidden_size], bias_hh_attr,
                                             is_bias=True,
                                             default_initializer=init)

    @property
    def state_shape(self):
        return (self.hidden_size,)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)

        def fn(x, h0, wi, wh, bi, bh):
            xg = x @ wi.T + bi
            hg = h0 @ wh.T + bh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            c = jnp.tanh(xc + r * hc)
            h1 = (1 - z) * c + z * h0
            return h1, h1
        out, h1 = run_op('gru_cell', fn,
                         [inputs, states, self.weight_ih, self.weight_hh,
                          self.bias_ih, self.bias_hh])
        return out, h1


def _scan_layer(mode, x, h0, c0, wi, wh, bi, bh, reverse=False):
    """One direction of one recurrent layer as a lax.scan (jax-level fn)."""
    xs = jnp.swapaxes(x, 0, 1)  # T, B, C

    if mode == 'LSTM':
        def step(carry, xt):
            h, c = carry
            gates = xt @ wi.T + bi + h @ wh.T + bh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            c1 = f * c + i * jnp.tanh(g)
            h1 = o * jnp.tanh(c1)
            return (h1, c1), h1
        (hT, cT), ys = jax.lax.scan(step, (h0, c0), xs, reverse=reverse)
    elif mode == 'GRU':
        def step(h, xt):
            xg = xt @ wi.T + bi
            hg = h @ wh.T + bh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            c = jnp.tanh(xc + r * hc)
            h1 = (1 - z) * c + z * h
            return h1, h1
        hT, ys = jax.lax.scan(step, h0, xs, reverse=reverse)
        cT = None
    else:
        act = jnp.tanh if mode == 'RNN_TANH' else jax.nn.relu

        def step(h, xt):
            h1 = act(xt @ wi.T + bi + h @ wh.T + bh)
            return h1, h1
        hT, ys = jax.lax.scan(step, h0, xs, reverse=reverse)
        cT = None
    return jnp.swapaxes(ys, 0, 1), hT, cT


class _RNNBase(Layer):
    """Multi-layer (bi)directional recurrent stack; parity nn.LSTM/GRU/
    SimpleRNN with time_major=False default."""

    def __init__(self, mode, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.mode = mode
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.direction = direction
        self.time_major = time_major
        self.dropout = dropout
        self.num_directions = 2 if direction in ("bidirect",
                                                 "bidirectional") else 1
        g = {'LSTM': 4, 'GRU': 3}.get(mode, 1)
        init = _std_uniform(hidden_size)
        self._all_weights = []
        for layer in range(num_layers):
            for d in range(self.num_directions):
                in_sz = input_size if layer == 0 \
                    else hidden_size * self.num_directions
                suffix = f"_l{layer}" + ("_reverse" if d else "")
                wi = self.create_parameter([g * hidden_size, in_sz],
                                           weight_ih_attr,
                                           default_initializer=init)
                wh = self.create_parameter([g * hidden_size, hidden_size],
                                           weight_hh_attr,
                                           default_initializer=init)
                bi = self.create_parameter([g * hidden_size], bias_ih_attr,
                                           is_bias=True,
                                           default_initializer=init)
                bh = self.create_parameter([g * hidden_size], bias_hh_attr,
                                           is_bias=True,
                                           default_initializer=init)
                self.add_parameter(f"weight_ih{suffix}", wi)
                self.add_parameter(f"weight_hh{suffix}", wh)
                self.add_parameter(f"bias_ih{suffix}", bi)
                self.add_parameter(f"bias_hh{suffix}", bh)
                self._all_weights.append((wi, wh, bi, bh))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        x = inputs
        if self.time_major:
            from ...ops import manip
            x = manip.transpose(x, [1, 0, 2])
        batch = x.shape[0]
        nd, nl, hs = self.num_directions, self.num_layers, self.hidden_size
        is_lstm = self.mode == 'LSTM'

        if initial_states is None:
            z = Tensor(jnp.zeros([nl * nd, batch, hs], x.dtype))
            initial_states = (z, Tensor(jnp.zeros_like(z.data))) if is_lstm else z
        h0s = initial_states[0] if is_lstm else initial_states
        c0s = initial_states[1] if is_lstm else None

        mode = self.mode
        weights = self._all_weights

        tensors = [x, h0s] + ([c0s] if is_lstm else [])
        for w in weights:
            tensors.extend(w)

        def fn(xa, h0a, *rest):
            if is_lstm:
                c0a, flat = rest[0], rest[1:]
            else:
                c0a, flat = None, rest
            out = xa
            hTs, cTs = [], []
            for layer in range(nl):
                ys = []
                for d in range(nd):
                    i = layer * nd + d
                    wi, wh, bi, bh = flat[4 * i: 4 * i + 4]
                    h0 = h0a[i]
                    c0 = c0a[i] if is_lstm else None
                    y, hT, cT = _scan_layer(mode, out, h0, c0, wi, wh, bi, bh,
                                            reverse=(d == 1))
                    ys.append(y)
                    hTs.append(hT)
                    if is_lstm:
                        cTs.append(cT)
                out = ys[0] if nd == 1 else jnp.concatenate(ys, axis=-1)
            if is_lstm:
                return out, jnp.stack(hTs), jnp.stack(cTs)
            return out, jnp.stack(hTs)

        outs = run_op(f'rnn_{mode.lower()}', fn, tensors)
        if is_lstm:
            y, hT, cT = outs
            if self.time_major:
                from ...ops import manip
                y = manip.transpose(y, [1, 0, 2])
            return y, (hT, cT)
        y, hT = outs
        if self.time_major:
            from ...ops import manip
            y = manip.transpose(y, [1, 0, 2])
        return y, hT


class SimpleRNN(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", *args, **kwargs):
        mode = 'RNN_TANH' if activation == 'tanh' else 'RNN_RELU'
        super().__init__(mode, input_size, hidden_size, num_layers, direction,
                         time_major, dropout, *args, **kwargs)


class LSTM(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, *args,
                 **kwargs):
        super().__init__('LSTM', input_size, hidden_size, num_layers,
                         direction, time_major, dropout, *args, **kwargs)


class GRU(_RNNBase):
    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0, *args,
                 **kwargs):
        super().__init__('GRU', input_size, hidden_size, num_layers,
                         direction, time_major, dropout, *args, **kwargs)


class RNN(Layer):
    """Wrap a cell into a scan over time (parity: nn.RNN wrapper)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ...ops import manip
        x = inputs if not self.time_major else manip.transpose(inputs,
                                                               [1, 0, 2])
        steps = x.shape[1]
        states = initial_states
        outputs = []
        time_ids = range(steps - 1, -1, -1) if self.is_reverse \
            else range(steps)
        for t in time_ids:
            xt = x[:, t]
            out, states = self.cell(xt, states)
            outputs.append(out)
        if self.is_reverse:
            outputs = outputs[::-1]
        y = manip.stack(outputs, axis=1 if not self.time_major else 0)
        return y, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ...ops import manip
        if initial_states is None:
            sf = sb = None
        else:
            sf, sb = initial_states
        yf, stf = self.rnn_fw(inputs, sf, sequence_length)
        yb, stb = self.rnn_bw(inputs, sb, sequence_length)
        return manip.concat([yf, yb], axis=-1), (stf, stb)
