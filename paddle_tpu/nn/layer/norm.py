"""Normalization layers.

Reference parity: python/paddle/nn/layer/norm.py (BatchNorm1D/2D/3D, LayerNorm,
GroupNorm, InstanceNorm, SyncBatchNorm). SyncBatchNorm's cross-replica moments
ride a psum over the data-parallel mesh axis when running inside shard_map
(reference: operators/sync_batch_norm_op.cu → here XLA collectives).
"""
import numpy as np
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops import nn_ops as F
from .. import initializer as I
from .base import Layer


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        self.register_buffer('_mean', Tensor(jnp.zeros([num_features])))
        self.register_buffer('_variance', Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight,
                            self.bias, training=self.training,
                            momentum=self._momentum, epsilon=self._epsilon,
                            data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Old-style fluid.dygraph.BatchNorm (acts on any rank)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-05,
                 param_attr=None, bias_attr=None, dtype='float32',
                 data_layout='NCHW', in_place=False, use_global_stats=False,
                 **kwargs):
        super().__init__(num_channels, momentum, epsilon, param_attr,
                         bias_attr, data_layout,
                         use_global_stats or None)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == 'relu':
            out = F.relu(out)
        elif self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN (parity: operators/sync_batch_norm_op.cu). Inside an
    SPMD region, batch moments are psum-averaged over the data axes before
    normalization, so every replica normalizes with GLOBAL statistics;
    eagerly (one device) it degrades to local BN like the reference at
    nranks==1."""

    def forward(self, x):
        from ...distributed import collective as C
        if not (self.training and C.in_spmd_region()):
            return super().forward(x)
        from jax import lax
        import jax.numpy as jnp
        from ...core.autograd import run_op
        axes = tuple(a for a in C.current_spmd_axes()
                     if a in ('dp', 'sharding', 'sp'))
        if not axes:
            return super().forward(x)
        eps = self._epsilon
        ch_axis = 1 if self._data_format.startswith('NC') else x.ndim - 1
        reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
        tensors = [x]
        has_w = self.weight is not None
        has_b = self.bias is not None
        if has_w:
            tensors.append(self.weight)
        if has_b:
            tensors.append(self.bias)

        def fn(a, *wb):
            af = a.astype(jnp.float32)
            cnt = 1.0
            for i in reduce_axes:
                cnt = cnt * a.shape[i]
            s1 = lax.psum(jnp.sum(af, axis=reduce_axes), axes)
            s2 = lax.psum(jnp.sum(af * af, axis=reduce_axes), axes)
            n = lax.psum(cnt, axes)
            mean = s1 / n
            var = s2 / n - mean * mean
            shape = [1] * a.ndim
            shape[ch_axis] = a.shape[ch_axis]
            out = (af - mean.reshape(shape)) * lax.rsqrt(
                var.reshape(shape) + eps)
            out = out.astype(a.dtype)
            i = 0
            if has_w:
                out = out * wb[i].reshape(shape)
                i += 1
            if has_b:
                out = out + wb[i].reshape(shape)
            return out, mean, var
        out, mean, var = run_op('sync_batch_norm', fn, tensors)
        # running stats track the GLOBAL moments (reference
        # sync_batch_norm_op updates them with the cross-replica values);
        # under TrainStep the buffer thread carries these, elsewhere
        # bind_arrays restores originals.
        m = self._momentum
        self._mean.set_value(m * self._mean.data + (1 - m) * mean.data)
        self._variance.set_value(m * self._variance.data
                                 + (1 - m) * var.data)
        return out

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        out = layer
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, cls):
            out = cls(layer._num_features, layer._momentum, layer._epsilon,
                      None, None, layer._data_format)
            if layer.weight is not None:
                out.weight.set_value(layer.weight)
            if layer.bias is not None:
                out.bias.set_value(layer.bias)
            out._mean.set_value(layer._mean)
            out._variance.set_value(layer._variance)
        for name, sub in list(layer._sub_layers.items()):
            new_sub = cls.convert_sync_batchnorm(sub)
            if new_sub is not sub:
                layer.add_sublayer(name, new_sub)
        return out


class LayerNorm(Layer):
    """Parity: nn.LayerNorm → operators/layer_norm_op."""

    def __init__(self, normalized_shape, epsilon=1e-05, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = [normalized_shape]
        self._normalized_shape = list(normalized_shape)
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                self._normalized_shape, attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter(self._normalized_shape,
                                              attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-05,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_channels], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_channels], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-05, momentum=0.9,
                 weight_attr=None, bias_attr=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr,
                default_initializer=I.Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=0.0001, beta=0.75, k=1.0,
                 data_format='NCHW', name=None):
        super().__init__()
        self.args = (size, alpha, beta, k)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    """Parity: operators/spectral_norm_op — power-iteration normalization."""

    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12,
                 name=None, dtype='float32'):
        super().__init__()
        self._dim = dim
        self._power_iters = power_iters
        self._eps = eps
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        self.weight_u = self.create_parameter(
            [h], default_initializer=I.Normal(0, 1))
        self.weight_u.stop_gradient = True
        self.weight_v = self.create_parameter(
            [w], default_initializer=I.Normal(0, 1))
        self.weight_v.stop_gradient = True

    def forward(self, weight):
        from ...core.autograd import run_op
        dim, eps, iters = self._dim, self._eps, self._power_iters
        u0, v0 = self.weight_u.data, self.weight_v.data

        def fn(w):
            mat = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(iters):
                v = mat.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = mat @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ mat @ v
            return w / sigma
        return run_op('spectral_norm', fn, [weight])
