"""Loss layers. Reference parity: python/paddle/nn/layer/loss.py."""
from ...ops import nn_ops as F
from .base import Layer


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean',
                 soft_label=False, axis=-1, use_softmax=True, name=None):
        super().__init__()
        self._weight = weight
        self._ignore_index = ignore_index
        self._reduction = reduction
        self._soft_label = soft_label
        self._axis = axis
        self._use_softmax = use_softmax

    def forward(self, input, label):
        return F.cross_entropy(input, label, weight=self._weight,
                               ignore_index=self._ignore_index,
                               reduction=self._reduction,
                               soft_label=self._soft_label, axis=self._axis,
                               use_softmax=self._use_softmax)


class MSELoss(Layer):
    def __init__(self, reduction='mean'):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, reduction=self._reduction)


class L1Loss(Layer):
    def __init__(self, reduction='mean', name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, reduction=self._reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction='mean',
                 name=None):
        super().__init__()
        self._args = (weight, ignore_index, reduction)

    def forward(self, input, label):
        w, ig, red = self._args
        return F.nll_loss(input, label, weight=w, ignore_index=ig,
                          reduction=red)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction='mean', name=None):
        super().__init__()
        self._weight, self._reduction = weight, reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, weight=self._weight,
                                      reduction=self._reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction='mean', pos_weight=None,
                 name=None):
        super().__init__()
        self._args = (weight, reduction, pos_weight)

    def forward(self, logit, label):
        w, red, pw = self._args
        return F.binary_cross_entropy_with_logits(logit, label, weight=w,
                                                  reduction=red,
                                                  pos_weight=pw)


class KLDivLoss(Layer):
    def __init__(self, reduction='mean'):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.kl_div(input, label, reduction=self._reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction='mean', delta=1.0, name=None):
        super().__init__()
        self._reduction, self._delta = reduction, delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, reduction=self._reduction,
                                delta=self._delta)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self._margin,
                                     self._reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction='mean', name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        m, p, e, s, r = self.args
        return F.triplet_margin_loss(input, positive, negative, margin=m,
                                     p=p, epsilon=e, swap=s, reduction=r)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction='mean', name=None):
        super().__init__()
        self._margin, self._reduction = margin, reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label,
                                       margin=self._margin,
                                       reduction=self._reduction)


class SoftMarginLoss(Layer):
    def __init__(self, reduction='mean', name=None):
        super().__init__()
        self._reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, reduction=self._reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction='mean',
                 name=None):
        super().__init__()
        self.args = (p, margin, weight, reduction)

    def forward(self, input, label):
        p, m, w, r = self.args
        return F.multi_margin_loss(input, label, p=p, margin=m, weight=w,
                                   reduction=r)


class CTCLoss(Layer):
    def __init__(self, blank=0, reduction='mean'):
        super().__init__()
        self._blank, self._reduction = blank, reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self._blank, reduction=self._reduction)


class HSigmoidLoss(Layer):
    """paddle.nn.HSigmoidLoss — hierarchical sigmoid over a complete
    binary tree (operators/hierarchical_sigmoid_op.cc)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        if is_custom:
            raise NotImplementedError(
                "HSigmoidLoss custom trees: pass path codes through "
                "ops.contrib.hsigmoid_loss directly")
        self.num_classes = num_classes
        self.weight = self.create_parameter(
            [num_classes - 1, feature_size], weight_attr)
        self.bias = None
        if bias_attr is not False:
            self.bias = self.create_parameter(
                [num_classes - 1], bias_attr, is_bias=True)

    def forward(self, input, label):
        from ...ops.contrib import hsigmoid_loss
        return hsigmoid_loss(input, label, self.num_classes,
                             self.weight, self.bias)
