"""Pooling layers. Reference parity: python/paddle/nn/layer/pooling.py."""
from ...ops import nn_ops as F
from .base import Layer


class AvgPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, exclusive=True,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, exclusive, ceil_mode)

    def forward(self, x):
        k, s, p, ex, cm = self.args
        return F.avg_pool1d(x, k, s, p, exclusive=ex, ceil_mode=cm)


class AvgPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format='NCHW',
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override)

    def forward(self, x):
        k, s, p, cm, ex, dv = self.args
        return F.avg_pool2d(x, k, s, p, ceil_mode=cm, exclusive=ex,
                            divisor_override=dv)


class MaxPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode)

    def forward(self, x):
        k, s, p, rm, cm = self.args
        return F.max_pool1d(x, k, s, p, return_mask=rm, ceil_mode=cm)


class MaxPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, return_mask=False,
                 ceil_mode=False, data_format='NCHW', name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, return_mask, ceil_mode)

    def forward(self, x):
        k, s, p, rm, cm = self.args
        return F.max_pool2d(x, k, s, p, return_mask=rm, ceil_mode=cm)


class AdaptiveAvgPool2D(Layer):
    def __init__(self, output_size, data_format='NCHW', name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool2d(x, self._output_size)


class AdaptiveMaxPool2D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self._output_size = output_size
        self._return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool2d(x, self._output_size, self._return_mask)


class AdaptiveAvgPool1D(Layer):
    def __init__(self, output_size, name=None):
        super().__init__()
        self._output_size = output_size

    def forward(self, x):
        import jax.numpy as jnp
        from ...core.autograd import run_op
        x4 = run_op('unsqueeze2', lambda a: jnp.expand_dims(a, -1), [x])
        out = F.adaptive_avg_pool2d(x4, (self._output_size, 1))
        return run_op('squeeze2', lambda a: jnp.squeeze(a, -1), [out])


class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 return_mask=False, ceil_mode=False,
                 data_format='NCDHW', name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode)

    def forward(self, x):
        k, s, p, cm = self.args
        return F.max_pool3d(x, k, s, p, ceil_mode=cm)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 ceil_mode=False, exclusive=True, divisor_override=None,
                 data_format='NCDHW', name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override)

    def forward(self, x):
        k, s, p, cm, ex, dv = self.args
        return F.avg_pool3d(x, k, s, p, ceil_mode=cm, exclusive=ex,
                            divisor_override=dv)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format='NCDHW', name=None):
        super().__init__()
        self.output_size = output_size

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool1d(x, self.output_size,
                                     return_mask=self.return_mask)


class AdaptiveMaxPool3D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        return F.adaptive_max_pool3d(x, self.output_size,
                                     return_mask=self.return_mask)
