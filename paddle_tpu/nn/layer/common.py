"""Common layers: Linear, Embedding, Dropout, Flatten, padding, upsample.

Reference parity: python/paddle/nn/layer/common.py.
"""
import jax.numpy as jnp

from ...core.tensor import Tensor
from ...ops import nn_ops as F
from ...ops import manip
from .. import initializer as I
from .base import Layer, ParamAttr


class Linear(Layer):
    """Parity: nn.Linear (python/paddle/nn/layer/common.py:Linear) —
    y = x @ W + b with W shape [in, out] (paddle layout, feeds the MXU
    directly with no transpose)."""

    def __init__(self, in_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.in_features = in_features
        self.out_features = out_features
        self.weight = self.create_parameter(
            [in_features, out_features], attr=weight_attr,
            default_initializer=I.XavierUniform())
        self.bias = self.create_parameter(
            [out_features], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self.in_features}, out_features={self.out_features}"


class Embedding(Layer):
    """Parity: nn.Embedding → lookup_table_v2."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = padding_idx
        self.weight = self.create_parameter(
            [num_embeddings, embedding_dim], attr=weight_attr,
            default_initializer=I.XavierUniform())
        if padding_idx is not None:
            self.weight.data = self.weight.data.at[padding_idx].set(0.0)

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode='upscale_in_train', name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, p=self.p, axis=self.axis, training=self.training,
                         mode=self.mode)

    def extra_repr(self):
        return f"p={self.p}"


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format='NCHW', name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, p=self.p, training=self.training,
                           data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, p=self.p, training=self.training)


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        return manip.flatten(x, self.start_axis, self.stop_axis)


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Pad1D(Layer):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCL',
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value

    def forward(self, x):
        return manip.pad(x, self.padding, mode=self.mode, value=self.value)


class Pad2D(Layer):
    def __init__(self, padding, mode='constant', value=0.0, data_format='NCHW',
                 name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value

    def forward(self, x):
        return manip.pad(x, self.padding, mode=self.mode, value=self.value)


class Pad3D(Layer):
    def __init__(self, padding, mode='constant', value=0.0,
                 data_format='NCDHW', name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value

    def forward(self, x):
        return manip.pad(x, self.padding, mode=self.mode, value=self.value)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode='nearest',
                 align_corners=False, align_mode=0, data_format='NCHW',
                 name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners = mode, align_corners

    def forward(self, x):
        return F.interpolate(x, size=self.size, scale_factor=self.scale_factor,
                             mode=self.mode, align_corners=self.align_corners)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW',
                 name=None):
        super().__init__(size, scale_factor, mode='bilinear',
                         align_corners=True)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format='NCHW',
                 name=None):
        super().__init__(size, scale_factor, mode='nearest')


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features,
                 weight_attr=None, bias_attr=None, name=None):
        super().__init__()
        from ...ops import linalg
        self._linalg = linalg
        self.weight = self.create_parameter(
            [out_features, in1_features, in2_features], attr=weight_attr)
        self.bias = self.create_parameter([1, out_features], attr=bias_attr,
                                          is_bias=True)

    def forward(self, x1, x2):
        return self._linalg.bilinear_tensor_product(x1, x2, self.weight,
                                                    self.bias)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, axis=self.axis, eps=self.eps)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1,
                 name=None):
        super().__init__()
        self.args = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.args)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format='NCHW', name=None):
        super().__init__()
        self._factor = upscale_factor

    def forward(self, x):
        return manip.pixel_shuffle(x, self._factor)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format='NCHW', name=None):
        super().__init__()
        self._factor = downscale_factor

    def forward(self, x):
        return F.pixel_unshuffle(x, self._factor)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format='NCHW', name=None):
        super().__init__()
        self._groups = groups

    def forward(self, x):
        return F.channel_shuffle(x, self._groups)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings,
                     dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class GLU(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.glu(x, axis=self._axis)


class ZeroPad2D(Layer):
    def __init__(self, padding, data_format='NCHW', name=None):
        super().__init__()
        self._padding = padding

    def forward(self, x):
        return manip.pad(x, self._padding, mode='constant', value=0.0)


class Dropout3D(Layer):
    """paddle.nn.Dropout3D — channel dropout over 5-D input."""

    def __init__(self, p=0.5, data_format='NCDHW', name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training)


class PairwiseDistance(Layer):
    """paddle.nn.PairwiseDistance — p-norm distance between rows."""

    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.args = (p, epsilon, keepdim)

    def forward(self, x, y):
        from ...ops.nn_ops import pairwise_distance
        p, eps, kd = self.args
        return pairwise_distance(x, y, p=p, epsilon=eps, keepdim=kd)
