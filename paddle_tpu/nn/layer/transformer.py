"""Transformer stack.

Reference parity: python/paddle/nn/layer/transformer.py — MultiHeadAttention
(:109, with Cache/StaticCache for decoding), TransformerEncoderLayer(:437),
TransformerEncoder(:622), TransformerDecoderLayer(:731), TransformerDecoder
(:969), Transformer(:1112). Attention math stays as large batched matmuls so
XLA tiles it onto the MXU; the Pallas flash-attention kernel
(paddle_tpu/ops/pallas/flash_attention.py) is used automatically for long
sequences when no additive mask is provided.
"""
import collections

import jax.numpy as jnp

from ...core.tensor import Tensor
from ...core.autograd import run_op
from ...ops import nn_ops as F
from ...ops import math as M
from ...ops import manip
from .base import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList


def _convert_attention_mask(attn_mask, dtype):
    if attn_mask is None:
        return None
    if attn_mask.dtype == jnp.bool_:
        return Tensor(jnp.where(attn_mask.data, 0.0, -1e9).astype(dtype))
    return attn_mask


def _as_key_bias(attn_mask):
    """Reduce an additive attention mask to a [B, L_k] key-padding bias if
    it has that structure, else None (caller falls back to the dense path).

    Only the [B|1, 1, 1, L_k] form qualifies: per paddle broadcast
    semantics a 2-D mask is [L_q, L_k] (e.g. the causal mask from
    Transformer.generate_square_subsequent_mask) and a 3-D mask's leading
    dim broadcasts against heads — neither is expressible as a per-key
    bias."""
    a = attn_mask.data if isinstance(attn_mask, Tensor) else attn_mask
    if a.ndim == 4 and a.shape[1] == 1 and a.shape[2] == 1:
        return a[:, 0, 0, :]              # [B|1, L_k]
    return None


class MultiHeadAttention(Layer):
    """Parity: nn/layer/transformer.py:109."""

    Cache = collections.namedtuple("Cache", ["k", "v"])
    StaticCache = collections.namedtuple("StaticCache", ["k", "v"])

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None,
                 vdim=None, need_weights=False, weight_attr=None,
                 bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.kdim = kdim or embed_dim
        self.vdim = vdim or embed_dim
        self.num_heads = num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        self.head_dim = embed_dim // num_heads
        assert self.head_dim * num_heads == embed_dim

        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(self.kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(self.vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _prepare_qkv(self, query, key, value, cache=None):
        q = self.q_proj(query)
        q = manip.reshape(q, [0, 0, self.num_heads, self.head_dim])
        q = manip.transpose(q, [0, 2, 1, 3])
        if isinstance(cache, self.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self.k_proj(key)
            v = self.v_proj(value)
            k = manip.reshape(k, [0, 0, self.num_heads, self.head_dim])
            k = manip.transpose(k, [0, 2, 1, 3])
            v = manip.reshape(v, [0, 0, self.num_heads, self.head_dim])
            v = manip.transpose(v, [0, 2, 1, 3])
        if isinstance(cache, self.Cache):
            k = manip.concat([cache.k, k], axis=2)
            v = manip.concat([cache.v, v], axis=2)
            cache = self.Cache(k, v)
        return (q, k, v) if cache is None else (q, k, v, cache)

    def gen_cache(self, key, value=None, type=Cache):
        if type == MultiHeadAttention.StaticCache:
            k = self.k_proj(key)
            v = self.v_proj(value if value is not None else key)
            k = manip.transpose(
                manip.reshape(k, [0, 0, self.num_heads, self.head_dim]),
                [0, 2, 1, 3])
            v = manip.transpose(
                manip.reshape(v, [0, 0, self.num_heads, self.head_dim]),
                [0, 2, 1, 3])
            return self.StaticCache(k, v)
        if value is None:
            batch = key.shape[0]
            k = Tensor(jnp.zeros([batch, self.num_heads, 0, self.head_dim],
                                 key.dtype))
            v = Tensor(jnp.zeros([batch, self.num_heads, 0, self.head_dim],
                                 key.dtype))
            return self.Cache(k, v)
        return self.Cache(key, value)

    def core_attention(self, q, k, v, attn_mask=None):
        flash = self._try_flash(q, k, v, attn_mask)
        if flash is not None:
            return flash, None
        from ...ops.pallas import scaffold as _scaffold
        _scaffold.record_route('flash_attention', False)
        scale = self.head_dim ** -0.5
        product = M.matmul(M.scale(q, scale), k, transpose_y=True)
        if attn_mask is not None:
            attn_mask = _convert_attention_mask(attn_mask, product.dtype)
            product = M.add(product, attn_mask)
        weights = F.softmax(product)
        if self.dropout:
            weights = F.dropout(weights, self.dropout, training=self.training)
        out = M.matmul(weights, v)
        return out, weights

    def _flash_eligible(self, B, Lq, Lk, attn_mask):
        """Shared eligibility + mask reduction for both flash routes:
        self-attention-shaped (L_q == L_k, tile-aligned, above the
        tunable FLAGS_flash_min_seq crossover vs XLA's fused dense
        attention), no attention-weight output, no active attention
        dropout, MXU-lane-shaped head_dim, and a mask that is None or
        reduces to a key-padding bias. Returns (ok, bias)."""
        from ...core import flags
        if not flags.flag('FLAGS_use_flash_attention', True):
            return False, None
        if self.need_weights or (self.dropout and self.training):
            return False, None
        min_seq = flags.flag('FLAGS_flash_min_seq', 1024)
        min_seq = 1024 if min_seq is None else int(min_seq)
        if Lq != Lk or Lq < min_seq or Lq % 256 != 0:
            return False, None
        if self.head_dim not in (64, 128, 256):
            return False, None
        bias = None
        if attn_mask is not None:
            attn_mask = _convert_attention_mask(attn_mask, jnp.float32)
            bias = _as_key_bias(attn_mask)
            if bias is None:
                return False, None
            if bias.shape[0] == 1 and B > 1:
                bias = jnp.broadcast_to(bias, (B, bias.shape[1]))
            if bias.shape[-1] != Lk:
                return False, None
        return True, bias

    def _try_flash(self, q, k, v, attn_mask):
        """[B, nh, L, hd] flash route (dense-path layout). Returns the
        context or None to fall back."""
        ok, bias = self._flash_eligible(q.shape[0], q.shape[2],
                                        k.shape[2], attn_mask)
        if not ok:
            return None
        from ...ops.pallas.flash_attention import mha_flash_attention
        return mha_flash_attention(q, k, v, key_bias=bias, causal=False)

    def _try_flash_blhd(self, q4, k4, v4, attn_mask):
        """Transpose-free flash route: q4/k4/v4 in the natural projection
        layout [B, L, nh, hd] (the [B, nh, L, hd] physical transpose XLA
        would materialize costs ~14% of a BERT step); the packed kernel
        runs every head over static column slices. Returns the
        [B, L, nh, hd] context or None to fall back."""
        from ...core import flags
        if not flags.flag('FLAGS_flash_packed_mha', True):
            return None                 # A/B: fall to the BHLD route
        ok, bias = self._flash_eligible(q4.shape[0], q4.shape[1],
                                        k4.shape[1], attn_mask)
        if not ok:
            return None
        from ...ops.pallas.flash_attention import mha_flash_attention_blhd
        return mha_flash_attention_blhd(q4, k4, v4, key_bias=bias,
                                        causal=False)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        if cache is None:
            # project + split heads WITHOUT transposing; the flash route
            # consumes this layout directly, the dense path transposes
            q4 = manip.reshape(self.q_proj(query),
                               [0, 0, self.num_heads, self.head_dim])
            k4 = manip.reshape(self.k_proj(key),
                               [0, 0, self.num_heads, self.head_dim])
            v4 = manip.reshape(self.v_proj(value),
                               [0, 0, self.num_heads, self.head_dim])
            ctx = self._try_flash_blhd(q4, k4, v4, attn_mask)
            if ctx is not None:
                out = manip.reshape(ctx, [0, 0, self.embed_dim])
                return self.out_proj(out)
            q = manip.transpose(q4, [0, 2, 1, 3])
            k = manip.transpose(k4, [0, 2, 1, 3])
            v = manip.transpose(v4, [0, 2, 1, 3])
        else:
            q, k, v, cache = self._prepare_qkv(query, key, value, cache)

        out, weights = self.core_attention(q, k, v, attn_mask)
        out = manip.transpose(out, [0, 2, 1, 3])
        out = manip.reshape(out, [0, 0, self.embed_dim])
        out = self.out_proj(out)

        outs = [out]
        if self.need_weights:
            outs.append(weights)
        if cache is not None:
            outs.append(cache)
        return out if len(outs) == 1 else tuple(outs)


class TransformerEncoderLayer(Layer):
    """Parity: nn/layer/transformer.py:437."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead,
                                            dropout=attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is None:
            src = self.self_attn(src, src, src, src_mask)
        else:
            src, incremental_cache = self.self_attn(src, src, src, src_mask,
                                                    cache)
        # remat boundary tag (docs/performance.md#remat-policy): the
        # attention output is a contraction boundary — saved under the
        # attn_mlp_boundaries policy, the joins/norms recompute
        from ...distributed.fleet.utils.recompute import (
            tag_tensor as _remat_tag)
        src = _remat_tag(src, 'attn_out')
        # residual joins and the FFN bias+GELU route through the fused
        # Pallas primitives (ops/pallas/fused_elementwise.py): same ops
        # and RNG stream as dropout-then-add / linear-then-gelu on the
        # reference route, one kernel pass each on TPU
        src = F.dropout_add(src, residual, p=self.dropout1.p,
                            training=self.training,
                            mode=self.dropout1.mode)
        if not self.normalize_before:
            src = self.norm1(src)

        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        if self.activation is F.gelu and self.linear1.bias is not None:
            h = F.bias_gelu(
                _remat_tag(F.linear(src, self.linear1.weight),
                           'mlp_fc1'),
                self.linear1.bias)
        else:
            h = self.activation(
                _remat_tag(self.linear1(src), 'mlp_fc1'))
        src = _remat_tag(self.linear2(self.dropout(h)), 'mlp_out')
        src = F.dropout_add(src, residual, p=self.dropout2.p,
                            training=self.training,
                            mode=self.dropout2.mode)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, incremental_cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    """Parity: nn/layer/transformer.py:622."""

    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] + [
            type(encoder_layer)(**_layer_config(encoder_layer))
            for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        output = src
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, src_mask=src_mask)
            else:
                output, new_cache = mod(output, src_mask=src_mask,
                                        cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, src):
        return [layer.gen_cache(src) for layer in self.layers]


def _layer_config(layer):
    if isinstance(layer, TransformerEncoderLayer):
        return dict(d_model=layer.self_attn.embed_dim,
                    nhead=layer.self_attn.num_heads,
                    dim_feedforward=layer.linear1.out_features,
                    dropout=layer.dropout1.p,
                    activation=layer.activation.__name__,
                    attn_dropout=layer.self_attn.dropout,
                    act_dropout=layer.dropout.p,
                    normalize_before=layer.normalize_before)
    if isinstance(layer, TransformerDecoderLayer):
        return dict(d_model=layer.self_attn.embed_dim,
                    nhead=layer.self_attn.num_heads,
                    dim_feedforward=layer.linear1.out_features,
                    dropout=layer.dropout1.p,
                    activation=layer.activation.__name__,
                    normalize_before=layer.normalize_before)
    raise TypeError(type(layer))


class TransformerDecoderLayer(Layer):
    """Parity: nn/layer/transformer.py:731."""

    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr,
                                            bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr,
                                             bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.dropout = Dropout(act_dropout, mode="upscale_in_train")
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model)
        self.norm2 = LayerNorm(d_model)
        self.norm3 = LayerNorm(d_model)
        self.dropout1 = Dropout(dropout, mode="upscale_in_train")
        self.dropout2 = Dropout(dropout, mode="upscale_in_train")
        self.dropout3 = Dropout(dropout, mode="upscale_in_train")
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            tgt = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            tgt, incremental_cache = self.self_attn(tgt, tgt, tgt, tgt_mask,
                                                    cache[0])
        tgt = M.add(residual, self.dropout1(tgt))
        if not self.normalize_before:
            tgt = self.norm1(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            tgt = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            tgt, static_cache = tgt if isinstance(tgt, tuple) else (tgt, cache[1])
        tgt = M.add(residual, self.dropout2(tgt))
        if not self.normalize_before:
            tgt = self.norm2(tgt)

        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.dropout(self.activation(self.linear1(tgt))))
        tgt = M.add(residual, self.dropout3(tgt))
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (incremental_cache,
                                                static_cache))

    def gen_cache(self, memory):
        incremental_cache = self.self_attn.gen_cache(memory)
        static_cache = self.cross_attn.gen_cache(
            memory, memory, type=MultiHeadAttention.StaticCache)
        return incremental_cache, static_cache


class TransformerDecoder(Layer):
    """Parity: nn/layer/transformer.py:969."""

    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        self.layers = LayerList([decoder_layer] + [
            type(decoder_layer)(**_layer_config(decoder_layer))
            for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None,
                cache=None):
        output = tgt
        new_caches = []
        for i, mod in enumerate(self.layers):
            if cache is None:
                output = mod(output, memory, tgt_mask=tgt_mask,
                             memory_mask=memory_mask)
            else:
                output, new_cache = mod(output, memory, tgt_mask=tgt_mask,
                                        memory_mask=memory_mask,
                                        cache=cache[i])
                new_caches.append(new_cache)
        if self.norm is not None:
            output = self.norm(output)
        return output if cache is None else (output, new_caches)

    def gen_cache(self, memory, do_zip=False):
        cache = [layer.gen_cache(memory) for layer in self.layers]
        if do_zip:
            cache = list(zip(*cache))
        return cache


class Transformer(Layer):
    """Parity: nn/layer/transformer.py:1112."""

    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            encoder_layer = TransformerEncoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            encoder_norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(encoder_layer,
                                              num_encoder_layers, encoder_norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            decoder_layer = TransformerDecoderLayer(
                d_model, nhead, dim_feedforward, dropout, activation,
                attn_dropout, act_dropout, normalize_before, weight_attr,
                bias_attr)
            decoder_norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(decoder_layer,
                                              num_decoder_layers, decoder_norm)
        self.d_model = d_model
        self.nhead = nhead

    def forward(self, src, tgt, src_mask=None, tgt_mask=None,
                memory_mask=None):
        memory = self.encoder(src, src_mask=src_mask)
        output = self.decoder(tgt, memory, tgt_mask=tgt_mask,
                              memory_mask=memory_mask)
        return output

    def generate_square_subsequent_mask(self, length):
        return Tensor(jnp.tril(jnp.ones([length, length])) * 0
                      + jnp.where(jnp.tril(jnp.ones([length, length],
                                                    bool)), 0.0, -jnp.inf))
