"""Activation layers. Reference parity: python/paddle/nn/layer/activation.py."""
from ...ops import nn_ops as F
from .. import initializer as I
from .base import Layer


def _simple(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, name=None, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **{k: v for k, v in kwargs.items()
                                           if k != 'name'}}

        def forward(self, x):
            return fn(x, **self._kwargs)
    _Act.__name__ = name
    _Act.__qualname__ = name
    return _Act


def _sigmoid(x):
    from ...ops import math as M
    return M.sigmoid(x)


def _tanh(x):
    from ...ops import math as M
    return M.tanh(x)


ReLU = _simple('ReLU', lambda x: F.relu(x))
ReLU6 = _simple('ReLU6', lambda x: F.relu6(x))
Sigmoid = _simple('Sigmoid', _sigmoid)
Tanh = _simple('Tanh', _tanh)
GELU = _simple('GELU', F.gelu)
ELU = _simple('ELU', F.elu, alpha=1.0)
SELU = _simple('SELU', F.selu)
CELU = _simple('CELU', F.celu, alpha=1.0)
Silu = _simple('Silu', lambda x: F.silu(x))
Swish = _simple('Swish', lambda x: F.swish(x))
Mish = _simple('Mish', lambda x: F.mish(x))
Hardswish = _simple('Hardswish', lambda x: F.hardswish(x))
Hardsigmoid = _simple('Hardsigmoid', lambda x: F.hardsigmoid(x))
Hardshrink = _simple('Hardshrink', F.hardshrink, threshold=0.5)
Hardtanh = _simple('Hardtanh', F.hardtanh, min=-1.0, max=1.0)
Softshrink = _simple('Softshrink', F.softshrink, threshold=0.5)
Softplus = _simple('Softplus', F.softplus, beta=1.0, threshold=20.0)
Softsign = _simple('Softsign', lambda x: F.softsign(x))
Tanhshrink = _simple('Tanhshrink', lambda x: F.tanhshrink(x))
ThresholdedReLU = _simple('ThresholdedReLU', F.thresholded_relu, threshold=1.0)
LogSigmoid = _simple('LogSigmoid', F.log_sigmoid)
Maxout = _simple('Maxout', F.maxout, groups=1)


class LeakyReLU(Layer):
    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._negative_slope = negative_slope

    def forward(self, x):
        return F.leaky_relu(x, self._negative_slope)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format='NCHW', name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter(
            [num_parameters], attr=weight_attr,
            default_initializer=I.Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class Softmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.softmax(x, axis=self._axis)


class LogSoftmax(Layer):
    def __init__(self, axis=-1, name=None):
        super().__init__()
        self._axis = axis

    def forward(self, x):
        return F.log_softmax(x, axis=self._axis)
