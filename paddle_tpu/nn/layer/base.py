"""nn.Layer — the module base class.

Reference parity: python/paddle/fluid/dygraph/layers.py (`Layer`): parameter /
buffer / sublayer registries via __setattr__, named_* iterators, state_dict /
set_state_dict, train/eval propagation, forward pre/post hooks, apply, to.
Parameters are Tensors with stop_gradient=False created through ParamAttr +
initializers (fluid/param_attr.py).
"""
import collections

import numpy as np
import jax.numpy as jnp

from ...core import dtypes
from ...core.tensor import Tensor
from .. import initializer as init_mod


class ParamAttr:
    """Parity: fluid/param_attr.py ParamAttr."""

    def __init__(self, name=None, initializer=None, learning_rate=1.0,
                 regularizer=None, trainable=True, do_model_average=True,
                 need_clip=True):
        self.name = name
        self.initializer = initializer
        self.learning_rate = learning_rate
        self.regularizer = regularizer
        self.trainable = trainable
        self.need_clip = need_clip

    @staticmethod
    def _to_attr(attr):
        if attr is None:
            return ParamAttr()
        if isinstance(attr, ParamAttr):
            return attr
        if isinstance(attr, str):
            return ParamAttr(name=attr)
        if isinstance(attr, init_mod.Initializer):
            return ParamAttr(initializer=attr)
        if attr is False:
            return False
        return ParamAttr()


_name_counters = collections.defaultdict(int)


def _unique_name(prefix):
    _name_counters[prefix] += 1
    return f"{prefix}_{_name_counters[prefix] - 1}"


class Layer:
    def __init__(self, name_scope=None, dtype='float32'):
        self.training = True
        self._dtype = dtypes.convert_dtype(dtype) if dtype else jnp.float32
        self._parameters = collections.OrderedDict()
        self._buffers = collections.OrderedDict()
        self._sub_layers = collections.OrderedDict()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._full_name = _unique_name(
            name_scope or type(self).__name__.lower())

    # -- registration ------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get('_parameters')
        layers = self.__dict__.get('_sub_layers')
        buffers = self.__dict__.get('_buffers')
        if params is not None and isinstance(value, Tensor) \
                and not value.stop_gradient:
            params[name] = value
            for d in (layers, buffers):
                if d is not None and name in d:
                    del d[name]
            object.__setattr__(self, name, value)
        elif layers is not None and isinstance(value, Layer):
            layers[name] = value
            if params is not None and name in params:
                del params[name]
            object.__setattr__(self, name, value)
        else:
            if params is not None and name in params and not isinstance(value, Tensor):
                del params[name]
            if buffers is not None and name in buffers:
                if isinstance(value, Tensor):
                    buffers[name] = value
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        raise AttributeError(
            f"'{type(self).__name__}' object has no attribute {name!r}")

    # -- parameter creation ------------------------------------------------
    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Parity: Layer.create_parameter (dygraph/layers.py)."""
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        dtype = dtypes.convert_dtype(dtype) if dtype else self._dtype
        init = attr.initializer or default_initializer or (
            init_mod.Constant(0.0) if is_bias else init_mod.XavierUniform())
        data = init(shape, dtype)
        p = Tensor(data, stop_gradient=not attr.trainable)
        p.name = attr.name or _unique_name('param')
        p.persistable = True
        p.optimize_attr = {'learning_rate': attr.learning_rate}
        p.regularizer = attr.regularizer
        p.need_clip = attr.need_clip
        p.is_bias = is_bias
        p.trainable = attr.trainable
        return p

    def add_parameter(self, name, parameter):
        if parameter is not None:
            self._parameters[name] = parameter
        object.__setattr__(self, name, parameter)
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[name] = sublayer
        object.__setattr__(self, name, sublayer)
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if tensor is not None:
            tensor.persistable = persistable
        object.__setattr__(self, name, tensor)
        return tensor

    # -- iteration ---------------------------------------------------------
    def named_parameters(self, prefix='', include_sublayers=True):
        seen = set()
        for name, p in self._parameters.items():
            if p is not None and id(p) not in seen:
                seen.add(id(p))
                yield (prefix + ('.' if prefix else '') + name, p)
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + ('.' if prefix else '') + lname
                for n, p in layer.named_parameters(sub_prefix):
                    if id(p) not in seen:
                        seen.add(id(p))
                        yield (n, p)

    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_sublayers(self, prefix='', include_self=False):
        if include_self:
            yield prefix, self
        for name, layer in self._sub_layers.items():
            if layer is None:
                continue
            p = prefix + ('.' if prefix else '') + name
            yield p, layer
            yield from layer.named_sublayers(p)

    def sublayers(self, include_self=False):
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def children(self):
        return iter(l for l in self._sub_layers.values() if l is not None)

    def named_children(self):
        return iter((n, l) for n, l in self._sub_layers.items()
                    if l is not None)

    def named_buffers(self, prefix='', include_sublayers=True):
        for name, b in self._buffers.items():
            if b is not None:
                yield (prefix + ('.' if prefix else '') + name, b)
        if include_sublayers:
            for lname, layer in self._sub_layers.items():
                if layer is None:
                    continue
                sub_prefix = prefix + ('.' if prefix else '') + lname
                yield from layer.named_buffers(sub_prefix)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(
            include_sublayers=include_sublayers)]

    # -- mode --------------------------------------------------------------
    def train(self):
        self.training = True
        for l in self.sublayers():
            l.training = True
        return self

    def eval(self):
        self.training = False
        for l in self.sublayers():
            l.training = False
        return self

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            dtype = dtypes.convert_dtype(dtype)
            for p in self.parameters():
                if dtypes.is_floating(p.dtype):
                    p.data = p.data.astype(dtype)
            for b in self.buffers():
                if dtypes.is_floating(b.dtype):
                    b.data = b.data.astype(dtype)
        return self

    def astype(self, dtype):
        return self.to(dtype=dtype)

    def full_name(self):
        return self._full_name

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix='', use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters():
            dest[structured_name_prefix + name] = p
        for name, b in self.named_buffers():
            if b is not None and getattr(b, 'persistable', True):
                dest[structured_name_prefix + name] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Parity: Layer.set_state_dict — matches by structured name."""
        own = self.state_dict()
        missing, unexpected = [], []
        for k, v in state_dict.items():
            if k in own:
                tgt = own[k]
                arr = v.data if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                tgt.set_value(arr.astype(tgt.dtype)
                              if dtypes.is_floating(tgt.dtype) else arr)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- hooks ---------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        handle = HookRemoveHelper(self._forward_pre_hooks)
        self._forward_pre_hooks[handle.id] = hook
        return handle

    def register_forward_post_hook(self, hook):
        handle = HookRemoveHelper(self._forward_post_hooks)
        self._forward_post_hooks[handle.id] = hook
        return handle

    # -- call ----------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in self._forward_pre_hooks.values():
            result = hook(self, inputs)
            if result is not None:
                inputs = result if isinstance(result, tuple) else (result,)
        out = self.forward(*inputs, **kwargs)
        for hook in self._forward_post_hooks.values():
            result = hook(self, inputs, out)
            if result is not None:
                out = result
        return out

    def extra_repr(self):
        return ''

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, layer in self._sub_layers.items():
            body = repr(layer).split('\n')
            body = [body[0]] + ['  ' + b for b in body[1:]]
            lines.append(f"({name}): " + '\n'.join(body))
        main = f"{type(self).__name__}({extra}"
        if lines:
            main += '\n  ' + '\n  '.join(lines) + '\n'
        return main + ')'


class HookRemoveHelper:
    _next_id = 0

    def __init__(self, hooks):
        self._hooks = hooks
        self.id = HookRemoveHelper._next_id
        HookRemoveHelper._next_id += 1

    def remove(self):
        self._hooks.pop(self.id, None)
