"""Conv layers. Reference parity: python/paddle/nn/layer/conv.py."""
import numpy as np

from ...ops import nn_ops as F
from .. import initializer as I
from .base import Layer


class _ConvNd(Layer):
    def __init__(self, in_channels, out_channels, kernel_size, stride,
                 padding, dilation, groups, weight_attr, bias_attr,
                 data_format, nd=2, transpose=False, output_padding=0):
        super().__init__()
        self._in_channels = in_channels
        self._out_channels = out_channels
        k = kernel_size if isinstance(kernel_size, (list, tuple)) \
            else (kernel_size,) * nd
        self._kernel_size = tuple(k)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._data_format = data_format
        self._output_padding = output_padding
        if transpose:
            w_shape = [in_channels, out_channels // groups, *k]
        else:
            w_shape = [out_channels, in_channels // groups, *k]
        fan_in = (in_channels // groups) * int(np.prod(k))
        self.weight = self.create_parameter(
            w_shape, attr=weight_attr,
            default_initializer=I.XavierUniform(fan_in=fan_in))
        self.bias = self.create_parameter([out_channels], attr=bias_attr,
                                          is_bias=True)


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format='NCL'):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, nd=1)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups)


class Conv2D(_ConvNd):
    """Parity: nn.Conv2D → operators/conv_op (MXU via
    lax.conv_general_dilated)."""

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format='NCHW'):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, nd=2)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups,
                        self._data_format)

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode='zeros',
                 weight_attr=None, bias_attr=None, data_format='NCDHW'):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, nd=3)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride,
                        self._padding, self._dilation, self._groups)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format='NCHW'):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, nd=2, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._dilation, self._groups, output_size)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format='NCL'):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, nd=1, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        import jax.numpy as jnp
        from ...core.autograd import run_op
        x4 = run_op('unsqueeze2', lambda a: jnp.expand_dims(a, -1), [x])
        w4 = run_op('unsqueeze2', lambda a: jnp.expand_dims(a, -1),
                    [self.weight])
        s = self._stride if isinstance(self._stride, int) else self._stride[0]
        p = self._padding if isinstance(self._padding, int) else self._padding[0]
        out = F.conv2d_transpose(x4, w4, self.bias, (s, 1),
                                 [(p, p), (0, 0)], 0, 1, self._groups)
        return run_op('squeeze2', lambda a: jnp.squeeze(a, -1), [out])


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, dilation=1, groups=1,
                 weight_attr=None, bias_attr=None, data_format='NCDHW'):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, weight_attr, bias_attr,
                         data_format, nd=3, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias,
                                  self._stride, self._padding,
                                  self._output_padding, self._groups,
                                  self._dilation, output_size)
