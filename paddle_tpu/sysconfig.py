"""paddle.sysconfig — header/library paths (reference: sysconfig.py).
Points at the native C runtime this framework builds (csrc/), since
the op kernels themselves are XLA-compiled rather than shipped as .so
kernels."""
import os

_ROOT = os.path.dirname(os.path.abspath(__file__))


def get_include():
    """Directory of the C headers (csrc/)."""
    return os.path.join(os.path.dirname(_ROOT), 'csrc')


def get_lib():
    """Directory holding the built native library."""
    return os.path.join(os.path.dirname(_ROOT), 'csrc', 'build')
