"""Text datasets (parity: python/paddle/text/datasets — Imdb, Imikolov,
UCIHousing, WMT14, Conll05st). Zero-egress: loads from the local cache when
present, otherwise deterministic synthetic corpora keep the training paths
exercisable (same contract as the vision fallbacks)."""
import os

import numpy as np

from ..io import Dataset
from ..utils.download import DATA_HOME

_WORDS = ('the a of to and in is it you that he was for on are with as his '
          'they at be this have from or one had by word but not what all '
          'were we when your can said there use an each which she do how '
          'their if').split()


def _synth_text(seed, n):
    rng = np.random.RandomState(seed)
    docs = []
    for _ in range(n):
        ln = rng.randint(8, 64)
        docs.append([int(w) for w in rng.randint(0, len(_WORDS), ln)])
    return docs


class Imdb(Dataset):
    """Sentiment classification (parity: text/datasets/imdb.py)."""

    def __init__(self, data_file=None, mode='train', cutoff=150,
                 download=True):
        self.mode = mode
        n = 512 if mode == 'train' else 128
        self.docs = _synth_text(1 if mode == 'train' else 2, n)
        rng = np.random.RandomState(3)
        # label correlated with doc parity for learnability
        self.labels = np.array([sum(d) % 2 for d in self.docs], np.int64)
        self.word_idx = {w: i for i, w in enumerate(_WORDS)}

    def __getitem__(self, idx):
        return np.asarray(self.docs[idx], np.int64), self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imikolov(Dataset):
    """N-gram LM dataset (parity: text/datasets/imikolov.py)."""

    def __init__(self, data_file=None, data_type='NGRAM', window_size=5,
                 mode='train', min_word_freq=50, download=True):
        self.window_size = window_size
        # synthetic corpus follows a noisy deterministic chain so next-word
        # prediction is learnable (w_{t+1} = 3*w_t + 1 mod V, 10% noise)
        rng = np.random.RandomState(5 if mode == 'train' else 6)
        V = len(_WORDS)
        docs = []
        for _ in range(256 if mode == 'train' else 64):
            ln = rng.randint(16, 64)
            w = int(rng.randint(0, V))
            d = [w]
            for _ in range(ln - 1):
                if rng.rand() < 0.1:
                    w = int(rng.randint(0, V))
                else:
                    w = (3 * w + 1) % V
                d.append(w)
            docs.append(d)
        self.samples = []
        for d in docs:
            for i in range(len(d) - window_size + 1):
                self.samples.append(d[i:i + window_size])
        self.word_idx = {w: i for i, w in enumerate(_WORDS)}

    def __getitem__(self, idx):
        s = self.samples[idx]
        return tuple(np.asarray([t], np.int64) for t in s)

    def __len__(self):
        return len(self.samples)


class UCIHousing(Dataset):
    """Regression dataset (parity: text/datasets/uci_housing.py)."""

    def __init__(self, data_file=None, mode='train', download=True):
        path = data_file or os.path.join(DATA_HOME, 'uci_housing',
                                         'housing.data')
        if os.path.exists(path):
            data = np.loadtxt(path).astype('float32')
        else:
            rng = np.random.RandomState(7)
            x = rng.rand(506, 13).astype('float32')
            w = rng.randn(13, 1).astype('float32')
            y = x @ w + 0.1 * rng.randn(506, 1).astype('float32')
            data = np.concatenate([x, y], 1)
        x, y = data[:, :13], data[:, 13:]
        x = (x - x.mean(0)) / (x.std(0) + 1e-6)
        split = int(len(x) * 0.8)
        if mode == 'train':
            self.x, self.y = x[:split], y[:split]
        else:
            self.x, self.y = x[split:], y[split:]

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class WMT14(Dataset):
    """Translation pairs (parity: text/datasets/wmt14.py)."""

    def __init__(self, data_file=None, mode='train', dict_size=1000,
                 download=True):
        n = 256 if mode == 'train' else 64
        rng = np.random.RandomState(11 if mode == 'train' else 12)
        self.src, self.tgt = [], []
        for _ in range(n):
            ln = rng.randint(4, 20)
            s = rng.randint(2, dict_size, ln)
            self.src.append(s.astype(np.int64))
            self.tgt.append(((s + 1) % dict_size).astype(np.int64))

    def __getitem__(self, idx):
        src = self.src[idx]
        tgt = self.tgt[idx]
        return src, tgt[:-1], tgt[1:]

    def __len__(self):
        return len(self.src)


class Conll05st(Dataset):
    """SRL dataset shell (parity: text/datasets/conll05.py)."""

    def __init__(self, data_file=None, mode='train', download=True):
        n = 128
        rng = np.random.RandomState(13)
        self.sents = [rng.randint(0, 60, rng.randint(5, 30)).astype(np.int64)
                      for _ in range(n)]
        self.labels = [np.asarray([int(t) % 5 for t in s], np.int64)
                       for s in self.sents]

    def __getitem__(self, idx):
        return self.sents[idx], self.labels[idx]

    def __len__(self):
        return len(self.sents)


class WMT16(WMT14):
    """Parity: paddle.text.datasets.WMT16 — reference signature
    (src_dict_size, trg_dict_size, lang); same synthetic pair shape."""

    def __init__(self, data_file=None, mode='train', src_dict_size=1000,
                 trg_dict_size=1000, lang='en', download=True):
        super().__init__(data_file=data_file, mode=mode,
                         dict_size=min(src_dict_size, trg_dict_size),
                         download=download)
        self.lang = lang


class Movielens(Dataset):
    """Parity: paddle.text.datasets.Movielens — (user features, movie
    features, rating) triples; synthetic under zero egress."""

    def __init__(self, data_file=None, mode='train', test_ratio=0.1,
                 rand_seed=0):
        n = 2048 if mode == 'train' else 256
        rng = np.random.RandomState(rand_seed + (0 if mode == 'train'
                                                 else 1))
        self.user_id = rng.randint(1, 6041, n).astype(np.int64)
        self.gender = rng.randint(0, 2, n).astype(np.int64)
        self.age = rng.randint(0, 7, n).astype(np.int64)
        self.job = rng.randint(0, 21, n).astype(np.int64)
        self.movie_id = rng.randint(1, 3953, n).astype(np.int64)
        self.category = rng.randint(0, 18, (n, 3)).astype(np.int64)
        self.title = rng.randint(0, 5000, (n, 4)).astype(np.int64)
        self.rating = (rng.randint(1, 6, n)).astype(np.float32)

    def __getitem__(self, idx):
        return (self.user_id[idx], self.gender[idx], self.age[idx],
                self.job[idx], self.movie_id[idx], self.category[idx],
                self.title[idx],
                np.asarray([self.rating[idx]], np.float32))

    def __len__(self):
        return len(self.rating)
