"""paddle_tpu.text (parity: python/paddle/text — datasets + viterbi)."""
from . import datasets
from .datasets import (Imdb, Imikolov, UCIHousing, WMT14, WMT16,
                       Conll05st, Movielens)
from ..ops.sequence import (viterbi_decode, ViterbiDecoder,
                            linear_chain_crf, crf_decoding, beam_search)
from . import models  # noqa: F401,E402
from .models import LSTMSentiment, BoWClassifier  # noqa: F401,E402
