"""paddle_tpu.text (parity: python/paddle/text — datasets + viterbi)."""
from . import datasets
from .datasets import Imdb, Imikolov, UCIHousing, WMT14, Conll05st
