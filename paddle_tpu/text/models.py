"""Text model families.

Reference parity: the hapi/text example models the reference ships —
the BiLSTM sentiment classifier (hapi sentiment/imdb example: embedding →
(bi)LSTM → pooled FC head) and the bag-of-embeddings text classifier —
wired over paddle_tpu.nn's scan-based RNN stack (the fused-LSTM analogue
on TPU: the whole sequence loop is ONE lax.scan inside the jitted step,
which is what the reference's fused_lstm kernel buys on GPU).
"""
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..ops import math as M
from ..ops import manip


class LSTMSentiment(nn.Layer):
    """Embedding → LSTM (optionally bidirectional) → last-state FC head."""

    def __init__(self, vocab_size=10000, embed_dim=64, hidden=64,
                 num_classes=2, num_layers=1, direction='forward',
                 dropout=0.0, padding_idx=0):
        super().__init__()
        if dropout:
            raise NotImplementedError(
                "inter-layer RNN dropout is not applied by the scan-based "
                "LSTM stack yet; pass dropout=0")
        self.embedding = nn.Embedding(vocab_size, embed_dim,
                                      padding_idx=padding_idx)
        self.lstm = nn.LSTM(embed_dim, hidden, num_layers=num_layers,
                            direction=direction)
        n_dir = 2 if direction in ('bidirect', 'bidirectional') else 1
        self.head = nn.Linear(hidden * n_dir, num_classes)
        self.n_dir = n_dir
        self.padding_idx = padding_idx

    def forward(self, ids):
        x = self.embedding(ids)                   # [N, T, E]
        out, (h, c) = self.lstm(x)                # out [N, T, H*dir]
        # padding-robust mean-pool over valid positions (the last-state
        # read would fold trailing pad steps into the summary)
        mask = (ids != self.padding_idx).astype('float32')
        summed = M.sum(M.multiply(out, manip.unsqueeze(mask, [-1])),
                       axis=1)
        denom = manip.unsqueeze(
            M.maximum(M.sum(mask, axis=1), Tensor(jnp.asarray(1.0))),
            [-1])
        return self.head(M.divide(summed, denom))


class BoWClassifier(nn.Layer):
    """Bag-of-embeddings text classifier (the hapi bow example)."""

    def __init__(self, vocab_size=10000, embed_dim=64, num_classes=2,
                 padding_idx=0):
        super().__init__()
        self.embedding = nn.Embedding(vocab_size, embed_dim,
                                      padding_idx=padding_idx)
        self.fc = nn.Linear(embed_dim, num_classes)
        self.padding_idx = padding_idx

    def forward(self, ids):
        emb = self.embedding(ids)                 # [N, T, E]
        mask = (ids != self.padding_idx).astype('float32')
        summed = M.sum(M.multiply(emb, manip.unsqueeze(mask, [-1])),
                       axis=1)
        denom = manip.unsqueeze(
            M.maximum(M.sum(mask, axis=1), Tensor(jnp.asarray(1.0))),
            [-1])
        return self.fc(M.divide(summed, denom))
