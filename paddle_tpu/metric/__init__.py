"""paddle_tpu.metric — parity: python/paddle/metric (Accuracy, Precision,
Recall, Auc) + functional accuracy/auc ops (operators/metrics/)."""
import numpy as np

from ..core.tensor import Tensor


class Metric:
    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def name(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None, *args, **kwargs):
        self.topk = topk if isinstance(topk, (list, tuple)) else (topk,)
        self._name = name or 'acc'
        self.maxk = max(self.topk)
        self.reset()

    def compute(self, pred, label, *args):
        pred = np.asarray(pred.data if isinstance(pred, Tensor) else pred)
        label = np.asarray(label.data if isinstance(label, Tensor) else label)
        order = np.argsort(-pred, axis=-1)[..., :self.maxk]
        if label.ndim == pred.ndim:
            label = label.squeeze(-1)
        correct = (order == label[..., None])
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        correct = np.asarray(correct.data if isinstance(correct, Tensor)
                             else correct)
        accs = []
        for k in self.topk:
            num = correct[..., :k].sum()
            self.total[self.topk.index(k)] += num
            self.count[self.topk.index(k)] += correct.shape[0]
            accs.append(num / correct.shape[0])
        return accs[0] if len(accs) == 1 else accs

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def accumulate(self):
        res = [t / c if c > 0 else 0.0 for t, c in zip(self.total,
                                                       self.count)]
        return res[0] if len(res) == 1 else res

    def name(self):
        if len(self.topk) == 1:
            return self._name
        return [f"{self._name}_top{k}" for k in self.topk]


class Precision(Metric):
    def __init__(self, name='precision', *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.data if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.data if isinstance(labels, Tensor)
                            else labels)
        pred_pos = (preds.round() == 1)
        self.tp += int(((labels == 1) & pred_pos).sum())
        self.fp += int(((labels == 0) & pred_pos).sum())

    def reset(self):
        self.tp = 0
        self.fp = 0

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Recall(Metric):
    def __init__(self, name='recall', *args, **kwargs):
        self._name = name
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.data if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.data if isinstance(labels, Tensor)
                            else labels)
        pred_pos = (preds.round() == 1)
        self.tp += int(((labels == 1) & pred_pos).sum())
        self.fn += int(((labels == 1) & ~pred_pos).sum())

    def reset(self):
        self.tp = 0
        self.fn = 0

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0

    def name(self):
        return self._name


class Auc(Metric):
    """Parity: paddle.metric.Auc (threshold-bucketed trapezoid AUC,
    operators/metrics/auc_op)."""

    def __init__(self, curve='ROC', num_thresholds=4095, name='auc', *args,
                 **kwargs):
        self._name = name
        self.num_thresholds = num_thresholds
        self.reset()

    def update(self, preds, labels):
        preds = np.asarray(preds.data if isinstance(preds, Tensor) else preds)
        labels = np.asarray(labels.data if isinstance(labels, Tensor)
                            else labels)
        if preds.ndim == 2:
            preds = preds[:, 1]
        labels = labels.reshape(-1)
        idx = np.clip((preds * self.num_thresholds).astype(np.int64), 0,
                      self.num_thresholds)
        for i, l in zip(idx, labels):
            if l:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1, np.int64)
        self._stat_neg = np.zeros(self.num_thresholds + 1, np.int64)

    def accumulate(self):
        tot_pos = tot_neg = 0.0
        auc = 0.0
        for i in range(self.num_thresholds, -1, -1):
            new_pos = tot_pos + self._stat_pos[i]
            new_neg = tot_neg + self._stat_neg[i]
            auc += (new_pos + tot_pos) * (new_neg - tot_neg) / 2
            tot_pos, tot_neg = new_pos, new_neg
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        return auc / (tot_pos * tot_neg)

    def name(self):
        return self._name


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    """Functional accuracy (parity: operators/metrics/accuracy_op)."""
    pred = np.asarray(input.data)
    lab = np.asarray(label.data).reshape(-1)
    order = np.argsort(-pred, axis=-1)[:, :k]
    correct_ = (order == lab[:, None]).any(axis=1)
    return Tensor(np.asarray(correct_.mean(), dtype=np.float32))


def mean_iou(input, label, num_classes):
    """paddle.metric.mean_iou (operators/mean_iou_op.cc)."""
    from ..ops.contrib import mean_iou as _mi
    return _mi(input, label, num_classes)
