"""Profiler.

Reference parity: python/paddle/fluid/profiler.py (profiler:314 context
manager, RecordEvent markers) over platform/profiler.cc + device_tracer.cc
(N4). Host events go through the C++ recorder (csrc/profiler.cc, chrome-trace
export); device-side timing is delegated to jax.profiler (XLA xplane) —
`start_device_trace`/`stop_device_trace` wrap it so one API drives both, as
the reference's tracer correlates CUPTI with host events.
"""
import contextlib
import os

from .core.native import load_native


class RecordEvent:
    """Parity: paddle.profiler.RecordEvent / platform::RecordEvent RAII."""

    def __init__(self, name, event_type=None):
        self.name = name
        self._lib = load_native()
        self._start = None

    def begin(self):
        if self._lib is not None:
            self._start = self._lib.ptpu_profiler_now()

    def end(self):
        if self._lib is not None and self._start is not None:
            self._lib.ptpu_profiler_record(self.name.encode(), self._start,
                                           self._lib.ptpu_profiler_now())
            self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()
        return False


def start_profiler(state='All', tracer_option='Default'):
    lib = load_native()
    if lib is not None:
        lib.ptpu_profiler_enable(1)


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    lib = load_native()
    if lib is None:
        return
    lib.ptpu_profiler_enable(0)
    print(summary())
    if profile_path:
        export_chrome_tracing(profile_path + '.json')


def reset_profiler():
    lib = load_native()
    if lib is not None:
        lib.ptpu_profiler_clear()


def summary():
    lib = load_native()
    if lib is None:
        return ''
    import ctypes
    cap = 1 << 20
    buf = ctypes.create_string_buffer(cap)
    lib.ptpu_profiler_summary(buf, cap)
    return buf.value.decode()


def export_chrome_tracing(path):
    lib = load_native()
    if lib is not None:
        lib.ptpu_profiler_export(path.encode())
    return path


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    """Parity: fluid/profiler.py profiler:314 context manager."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


# ---- device-side (XLA) trace ------------------------------------------------
def start_device_trace(logdir='/tmp/paddle_tpu_trace'):
    """XLA/PJRT profiler (parity role: device_tracer.cc CUPTI capture)."""
    import jax
    jax.profiler.start_trace(logdir)
    return logdir


def stop_device_trace():
    import jax
    jax.profiler.stop_trace()


class Profiler:
    """paddle.profiler.Profiler-shaped wrapper (2.x API surface)."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False):
        self.timer_only = timer_only

    def start(self):
        start_profiler()

    def stop(self):
        stop_profiler(profile_path=None)

    def step(self):
        pass

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit='ms'):
        return summary()

    def export(self, path, format='json'):
        return export_chrome_tracing(path)

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False
