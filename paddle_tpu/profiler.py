"""Profiler v2 — unified host tracing + step telemetry.

Reference parity: python/paddle/profiler (Profiler:331, make_scheduler,
RecordEvent, export_chrome_tracing handlers) layered over the fluid-era
API (profiler:314 context manager) and platform/profiler.cc +
device_tracer.cc (N4). Two recorders share one API:

  * native fast path — csrc/profiler.cc via ctypes when
    libpaddle_tpu_native.so is present (the reference's C++ host-event
    tables; drives the legacy summary()/export_chrome_tracing());
  * pure-Python fallback — a thread-aware ring buffer of nested spans
    (parent ids, depth, categories, kwargs args) that the v2 Profiler
    always records into, so the chrome-trace/JSON exporters can emit
    nesting and metadata the flat native table can't hold.

Device-side timing is delegated to jax.profiler (XLA xplane), as the
reference's device_tracer correlates CUPTI with host events —
`Profiler(targets=[ProfilerTarget.TPU])` brackets the RECORD window
with jax.profiler.start_trace/stop_trace and stamps the logdir into the
exported trace metadata.

Step telemetry (`StepTelemetry`) aggregates examples/sec, tokens/sec,
compile seconds, compile-cache hit rates, live device memory and XLA
FLOP estimates into core.monitor gauges — consumed by the hapi
`StepTelemetry` callback and bench.py.
"""
import collections
import contextlib
import json
import os
import threading
import time

from .core.native import load_native
from .core import monitor as _monitor

_PID = os.getpid()


# ---------------------------------------------------------------------------
# recorder state
# ---------------------------------------------------------------------------
class _SpanBuffer:
    """Pure-Python ring buffer of completed spans (thread-safe)."""

    def __init__(self, capacity=200000):
        self._spans = collections.deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._next_id = 0

    def new_id(self):
        with self._lock:
            self._next_id += 1
            return self._next_id

    def append(self, span):
        with self._lock:
            self._spans.append(span)

    def drain(self):
        with self._lock:
            out = list(self._spans)
            self._spans.clear()
        return out

    def snapshot(self):
        with self._lock:
            return list(self._spans)

    def clear(self):
        with self._lock:
            self._spans.clear()

    def __len__(self):
        with self._lock:
            return len(self._spans)


_buffer = _SpanBuffer()
_tls = threading.local()                 # per-thread open-span stack
_legacy_on = False                       # fluid-era start/stop_profiler
_tracer_depth = 0                        # v2 Profiler RECORD windows
_force_python = os.environ.get(
    'PADDLE_TPU_PROFILER_FORCE_PYTHON', '0') == '1'


def _native_lib():
    if _force_python:
        return None
    return load_native()


def use_native_recorder(flag):
    """Force the pure-Python recorder off/on (tests exercise the
    fallback path this way even when the .so is present)."""
    global _force_python
    _force_python = not flag


def _tracing_on():
    return _legacy_on or _tracer_depth > 0


def _now_us():
    return time.perf_counter_ns() // 1000


def _stack():
    st = getattr(_tls, 'stack', None)
    if st is None:
        st = _tls.stack = []
    return st


# ---------------------------------------------------------------------------
# RecordEvent — nested, thread-aware span marker
# ---------------------------------------------------------------------------
class RecordEvent:
    """Parity: paddle.profiler.RecordEvent / platform::RecordEvent RAII.

    Extra kwargs are recorded as chrome-trace `args` on the span
    (byte counts, cache keys, shapes...). Usable as a context manager
    or via explicit begin()/end().
    """

    __slots__ = ('name', 'event_type', 'args', '_start', '_id', '_lib')

    def __init__(self, name, event_type=None, **kwargs):
        self.name = name
        self.event_type = event_type
        self.args = kwargs or None
        self._start = None
        self._id = None
        self._lib = None

    def begin(self):
        if not _tracing_on():
            return
        self._lib = _native_lib()
        self._start = _now_us()
        self._id = _buffer.new_id()
        _stack().append(self._id)

    def end(self):
        if self._start is None:
            return
        end_us = _now_us()
        st = _stack()
        if st and st[-1] == self._id:
            st.pop()
        parent = st[-1] if st else 0
        t = threading.current_thread()
        _buffer.append({
            'name': self.name, 'cat': self.event_type or 'python',
            'ts': self._start, 'dur': end_us - self._start,
            'tid': t.ident or 0, 'tname': t.name,
            'id': self._id, 'parent': parent, 'depth': len(st),
            'args': self.args,
        })
        if self._lib is not None and _legacy_on:
            # native fast path mirrors the flat record (legacy
            # summary()/export readers)
            self._lib.ptpu_profiler_record(self.name.encode(),
                                           self._start, end_us)
        self._start = None

    def __enter__(self):
        self.begin()
        return self

    def __exit__(self, *a):
        self.end()
        return False


@contextlib.contextmanager
def record_function(name, **kwargs):
    """Convenience alias (torch-style name) for RecordEvent."""
    with RecordEvent(name, **kwargs):
        yield


# ---------------------------------------------------------------------------
# legacy fluid-era API (kept verbatim in behavior)
# ---------------------------------------------------------------------------
def start_profiler(state='All', tracer_option='Default'):
    global _legacy_on
    _legacy_on = True
    lib = _native_lib()
    if lib is not None:
        lib.ptpu_profiler_enable(1)


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    global _legacy_on
    _legacy_on = False
    lib = _native_lib()
    if lib is not None:
        lib.ptpu_profiler_enable(0)
    print(summary())
    if profile_path:
        export_chrome_tracing(profile_path + '.json')


def reset_profiler():
    _buffer.clear()
    lib = _native_lib()
    if lib is not None:
        lib.ptpu_profiler_clear()


def summary():
    """Aggregated name → calls/total/avg/min/max table. Native table
    when the .so is present (fluid parity), else computed from the
    Python ring buffer."""
    lib = _native_lib()
    if lib is not None:
        import ctypes
        cap = 1 << 20
        buf = ctypes.create_string_buffer(cap)
        lib.ptpu_profiler_summary(buf, cap)
        return buf.value.decode()
    agg = {}
    for s in _buffer.snapshot():
        a = agg.setdefault(s['name'], [0, 0, float('inf'), 0])
        a[0] += 1
        a[1] += s['dur']
        a[2] = min(a[2], s['dur'])
        a[3] = max(a[3], s['dur'])
    lines = ['name\tcalls\ttotal_ms\tavg_us\tmin_us\tmax_us']
    for name in sorted(agg):
        c, tot, mn, mx = agg[name]
        lines.append(f'{name}\t{c}\t{tot / 1000.0:.3f}\t{tot / c:.1f}'
                     f'\t{mn}\t{mx}')
    return '\n'.join(lines) + '\n'


def export_chrome_tracing(path):
    """Legacy flat export: native recorder's events when present, else
    the Python buffer rendered to the same chrome-trace shape."""
    lib = _native_lib()
    if lib is not None:
        lib.ptpu_profiler_export(path.encode())
        return path
    _write_chrome_trace(path, _buffer.snapshot())
    return path


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    """Parity: fluid/profiler.py profiler:314 context manager."""
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


def native_dropped_events():
    """Events the native ring buffer discarded since the last clear
    (csrc/profiler.cc caps at ~1M events so a forgotten-enabled
    profiler can't grow without bound)."""
    lib = _native_lib()
    if lib is None or not hasattr(lib, 'ptpu_profiler_dropped'):
        return 0
    return int(lib.ptpu_profiler_dropped())


# ---- device-side (XLA) trace ------------------------------------------------
def start_device_trace(logdir='/tmp/paddle_tpu_trace'):
    """XLA/PJRT profiler (parity role: device_tracer.cc CUPTI capture)."""
    import jax
    jax.profiler.start_trace(logdir)
    return logdir


def stop_device_trace():
    import jax
    jax.profiler.stop_trace()


# ---------------------------------------------------------------------------
# chrome-trace / JSON writers
# ---------------------------------------------------------------------------
def _chrome_events(spans, metadata=None):
    # spans may carry an explicit 'pid'/'pname' (synthetic track
    # groups — the serving request tracer puts each request on its own
    # virtual thread of a 'serving requests' pseudo-process so request
    # tracks render as a group beside the host's engine spans)
    events = []
    threads = {}
    procs = {_PID: 'paddle_tpu host'}
    for s in spans:
        pid = s.get('pid', _PID)
        if s.get('pname'):
            procs[pid] = s['pname']
        elif pid not in procs:
            procs[pid] = f'paddle_tpu pid {pid}'
        threads.setdefault((pid, s.get('tid', 0)), s.get('tname', ''))
        ev = {'name': s['name'], 'ph': 'X', 'pid': pid,
              'tid': s.get('tid', 0), 'ts': s['ts'], 'dur': s['dur'],
              'cat': s.get('cat') or 'python'}
        args = dict(s.get('args') or {})
        if s.get('parent'):
            args['parent_id'] = s['parent']
        if s.get('depth') is not None:
            args['depth'] = s['depth']
        if args:
            ev['args'] = {k: _jsonable(v) for k, v in args.items()}
        events.append(ev)
    for pid, pname in procs.items():
        events.append({'name': 'process_name', 'ph': 'M', 'pid': pid,
                       'args': {'name': pname}})
    for (pid, tid), tname in threads.items():
        events.append({'name': 'thread_name', 'ph': 'M', 'pid': pid,
                       'tid': tid, 'args': {'name': tname or str(tid)}})
    return events


def _jsonable(v):
    try:
        json.dumps(v)
        return v
    except TypeError:
        return repr(v)


def _device_chrome_events(trace_dir):
    """Chrome-format device events under a jax.profiler logdir, if the
    run produced any (older TF profiler versions write
    *.trace.json.gz beside the xplane protobuf)."""
    if not trace_dir or not os.path.isdir(trace_dir):
        return []
    import glob
    import gzip
    events = []
    pats = (os.path.join(trace_dir, '**', '*.trace.json.gz'),
            os.path.join(trace_dir, '**', '*.trace.json'))
    for pat in pats:
        for fp in glob.glob(pat, recursive=True):
            try:
                opener = gzip.open if fp.endswith('.gz') else open
                with opener(fp, 'rt') as f:
                    doc = json.load(f)
                for ev in doc.get('traceEvents', []):
                    if isinstance(ev, dict):
                        ev.setdefault('cat', 'device')
                        events.append(ev)
            except Exception:
                continue
    return events


def _write_chrome_trace(path, spans, metadata=None):
    doc = {'traceEvents': _chrome_events(spans)}
    if metadata:
        doc['metadata'] = metadata
    d = os.path.dirname(os.path.abspath(path))
    os.makedirs(d, exist_ok=True)
    with open(path, 'w') as f:
        json.dump(doc, f)
    return path


# ---------------------------------------------------------------------------
# scheduler (paddle 2.x make_scheduler parity, torch aliases accepted)
# ---------------------------------------------------------------------------
class ProfilerState:
    CLOSED = 0
    READY = 1
    RECORD = 2
    RECORD_AND_RETURN = 3       # last RECORD step of a cycle


def make_scheduler(*, closed=None, ready=None, record=None, repeat=0,
                   skip_first=0, wait=None, warmup=None, active=None):
    """Parity: paddle.profiler.make_scheduler(closed, ready, record,
    repeat, skip_first); torch-style wait/warmup/active aliases map to
    closed/ready/record. Returns fn(step)->ProfilerState."""
    closed = wait if closed is None else closed
    ready = warmup if ready is None else ready
    record = active if record is None else record
    closed = int(closed or 0)
    ready = int(ready or 0)
    record = int(record)
    if record <= 0:
        raise ValueError("record (active) must be >= 1")
    if closed < 0 or ready < 0 or skip_first < 0 or repeat < 0:
        raise ValueError("scheduler windows must be non-negative")
    cycle = closed + ready + record

    def scheduler(step):
        if step < skip_first:
            return ProfilerState.CLOSED
        step -= skip_first
        if repeat and step >= repeat * cycle:
            return ProfilerState.CLOSED
        pos = step % cycle
        if pos < closed:
            return ProfilerState.CLOSED
        if pos < closed + ready:
            return ProfilerState.READY
        if pos == cycle - 1:
            return ProfilerState.RECORD_AND_RETURN
        return ProfilerState.RECORD
    scheduler._cycle = (skip_first, closed, ready, record, repeat)
    return scheduler


def _default_scheduler(_step):
    return ProfilerState.RECORD


class ProfilerTarget:
    CPU = 'cpu'
    GPU = 'gpu'
    TPU = 'tpu'
    CUSTOM_DEVICE = 'custom_device'


def export_chrome_tracing_handler(dir_name, worker_name=None):
    """Parity: paddle.profiler.export_chrome_tracing(dir_name) — an
    on_trace_ready handler writing one chrome-trace file per collected
    window into `dir_name`."""
    os.makedirs(dir_name, exist_ok=True)

    def handler(prof):
        worker = worker_name or f'host_{_PID}'
        lo, hi = prof.profiler_result.step_range
        path = os.path.join(dir_name,
                            f'{worker}_steps_{lo}_{hi}.paddle_trace.json')
        prof.profiler_result.export_chrome_tracing(path)
        return path
    return handler


class ProfilerResult:
    """Spans collected for one RECORD window, plus metadata."""

    def __init__(self, spans, step_range=(0, 0), device_trace_dir=None,
                 native_events=None):
        self.spans = spans
        self.step_range = tuple(step_range)
        self.device_trace_dir = device_trace_dir
        self.native_events = native_events or []

    def events(self):
        return list(self.spans)

    def _metadata(self):
        md = {'step_range': list(self.step_range),
              'schema': 'paddle_tpu.profiler/2'}
        if self.device_trace_dir:
            md['device_trace_dir'] = self.device_trace_dir
        return md

    def export_chrome_tracing(self, path):
        spans = self.spans + self.native_events
        doc = {'traceEvents': _chrome_events(spans),
               'metadata': self._metadata()}
        # best-effort merge of device-side events: TB/XLA profiler runs
        # that produced chrome-format dumps (*.trace.json[.gz]) fold in
        # under their own pids; xplane.pb-only runs stay referenced via
        # metadata.device_trace_dir (open with TB's profile plugin)
        for ev in _device_chrome_events(self.device_trace_dir):
            doc['traceEvents'].append(ev)
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, 'w') as f:
            json.dump(doc, f)
        return path

    def export_json(self, path):
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        with open(path, 'w') as f:
            json.dump({'metadata': self._metadata(),
                       'spans': [dict(s, args=_jsonable(s.get('args')))
                                 for s in self.spans]}, f)
        return path

    def summary(self, top=20):
        agg = {}
        for s in self.spans:
            a = agg.setdefault(s['name'], [0, 0])
            a[0] += 1
            a[1] += s['dur']
        rows = sorted(agg.items(), key=lambda kv: -kv[1][1])[:top]
        lines = ['name\tcalls\ttotal_ms\tavg_us']
        for name, (c, tot) in rows:
            lines.append(f'{name}\t{c}\t{tot / 1000.0:.3f}\t{tot / c:.1f}')
        return '\n'.join(lines) + '\n'


class Profiler:
    """Parity: paddle.profiler.Profiler (2.x) — scheduler-driven RECORD
    windows, on_trace_ready handlers, chrome/JSON export. The host
    tracer is the Python span buffer; `targets` containing TPU/GPU also
    brackets RECORD windows with jax.profiler device traces."""

    def __init__(self, targets=None, scheduler=None, on_trace_ready=None,
                 timer_only=False, record_shapes=False, profile_memory=False,
                 with_flops=False, device_trace_dir=None):
        self.timer_only = timer_only
        self.targets = list(targets) if targets else [ProfilerTarget.CPU]
        if scheduler is None:
            self._scheduler = _default_scheduler
        elif callable(scheduler):
            self._scheduler = scheduler
        elif isinstance(scheduler, (tuple, list)) and len(scheduler) == 2:
            start, end = scheduler
            if end <= start:
                raise ValueError("scheduler (start, end) needs end > start")
            self._scheduler = make_scheduler(closed=max(int(start), 0),
                                             record=int(end) - int(start),
                                             repeat=1)
        else:
            raise TypeError(f"bad scheduler {scheduler!r}")
        self.on_trace_ready = on_trace_ready
        self.profiler_result = None
        self._device_trace_dir = device_trace_dir
        self._device_tracing = False
        self.current_state = ProfilerState.CLOSED
        self._step_num = 0
        self._window_start = 0
        self._running = False

    # -- device bracket ------------------------------------------------------
    def _wants_device(self):
        return any(t in (ProfilerTarget.TPU, ProfilerTarget.GPU)
                   for t in self.targets)

    def _device_begin(self):
        if not self._wants_device() or self._device_tracing:
            return
        try:
            import tempfile
            self._device_trace_dir = (self._device_trace_dir or
                                      tempfile.mkdtemp(
                                          prefix='paddle_tpu_xla_trace_'))
            start_device_trace(self._device_trace_dir)
            self._device_tracing = True
        except Exception:            # device tracer unavailable: host-only
            self._device_tracing = False

    def _device_end(self):
        if self._device_tracing:
            try:
                stop_device_trace()
            except Exception:
                pass
            self._device_tracing = False

    # -- state machine -------------------------------------------------------
    def _tracer_enable(self):
        global _tracer_depth
        _tracer_depth += 1

    def _tracer_disable(self):
        global _tracer_depth
        _tracer_depth = max(0, _tracer_depth - 1)

    def _transition(self, new_state):
        old = self.current_state
        rec = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if old not in rec and new_state in rec:
            _buffer.drain()          # discard warmup noise
            self._window_start = self._step_num
            self._tracer_enable()
            self._device_begin()
        if old == ProfilerState.RECORD_AND_RETURN or \
                (old in rec and new_state not in rec):
            self._device_end()
            self._tracer_disable()
            self._collect()
            if new_state in rec:     # back-to-back windows (repeat)
                self._window_start = self._step_num
                self._tracer_enable()
                self._device_begin()
        self.current_state = new_state

    def _collect(self):
        self.profiler_result = ProfilerResult(
            _buffer.drain(),
            step_range=(self._window_start, self._step_num),
            device_trace_dir=(self._device_trace_dir
                              if self._wants_device() else None))
        if self.on_trace_ready is not None and not self.timer_only:
            self.on_trace_ready(self)

    def start(self):
        if self._running:
            return
        self._running = True
        self._step_num = 0
        self._transition(self._scheduler(0))

    def step(self, num_samples=None):
        """Advance one iteration; drives the scheduler state machine."""
        if not self._running:
            raise RuntimeError("Profiler.step() before start()")
        self._step_num += 1
        new_state = self._scheduler(self._step_num)
        if new_state != self.current_state or \
                self.current_state == ProfilerState.RECORD_AND_RETURN:
            self._transition(new_state)

    def stop(self):
        if not self._running:
            return
        rec = (ProfilerState.RECORD, ProfilerState.RECORD_AND_RETURN)
        if self.current_state in rec:
            self._device_end()
            self._tracer_disable()
            self._collect()
        self.current_state = ProfilerState.CLOSED
        self._running = False

    # -- results -------------------------------------------------------------
    def export(self, path, format='json'):
        if self.profiler_result is None:
            raise RuntimeError("no collected window to export — run a "
                               "RECORD window (or call stop()) first")
        chrome = format in ('chrome', 'chrome_trace', 'chrometracing') \
            or path.endswith(('.trace.json', '.chrome.json'))
        if chrome:
            return self.profiler_result.export_chrome_tracing(path)
        return self.profiler_result.export_json(path)

    def summary(self, sorted_by=None, op_detail=True, thread_sep=False,
                time_unit='ms'):
        if self.profiler_result is not None:
            return self.profiler_result.summary()
        return summary()

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *a):
        self.stop()
        return False


# ---------------------------------------------------------------------------
# compile telemetry: instrumented AOT compile for jit call sites
# ---------------------------------------------------------------------------
def compile_with_telemetry(jitted, label, args, kwargs=None):
    """Split trace/lower vs XLA-compile for a `jax.jit`-wrapped fn and
    publish compile seconds + FLOP estimates. Returns (callable, ok):
    the AOT-compiled executable when lowering succeeds (ok=True), else
    the plain jitted fn (ok=False). Callers keep `jitted` as dispatch
    fallback for signature drift."""
    kwargs = kwargs or {}
    c_sec = _monitor.counter('ptpu_compile_seconds_total',
                             help='cumulative XLA compile seconds',
                             labelnames=('site',))
    c_num = _monitor.counter('ptpu_compiles_total',
                             help='XLA compilations', labelnames=('site',))
    try:
        t0 = time.perf_counter()
        with RecordEvent(f'{label}::lower', event_type='compile'):
            lowered = jitted.lower(*args, **kwargs)
        with RecordEvent(f'{label}::compile', event_type='compile'):
            compiled = lowered.compile()
        dt = time.perf_counter() - t0
        c_sec.inc(dt, site=label)
        c_num.inc(1, site=label)
        # buffer-assignment census: the executable's temp (activation)
        # bytes — the resident set remat policies shrink (ISSUE 12;
        # core/memory.record_compiled_memory publishes the gauge)
        try:
            from .core import memory as _mem
            _mem.record_compiled_memory(label, compiled)
        except Exception:
            pass
        flops = _cost_flops(compiled)
        if flops is not None:
            _monitor.gauge('ptpu_xla_flops_per_run',
                           help='XLA cost-analysis FLOP estimate of the '
                                'latest compiled executable',
                           labelnames=('site',)).set(flops, site=label)
        return compiled, True
    except Exception:
        # lowering not supported for this callable/args — fall back to
        # the opaque jit path (compile time then hides in first call)
        c_num.inc(1, site=label)
        return jitted, False


def _cost_flops(compiled):
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        v = ca.get('flops')
        return float(v) if v is not None else None
    except Exception:
        return None


def device_memory_stats():
    """Live device memory via JAX (None entries when the backend does
    not expose memory_stats, e.g. CPU)."""
    try:
        import jax
        dev = jax.local_devices()[0]
        stats = dev.memory_stats() if hasattr(dev, 'memory_stats') else None
        if not stats:
            return None
        return {'bytes_in_use': stats.get('bytes_in_use'),
                'peak_bytes_in_use': stats.get('peak_bytes_in_use'),
                'bytes_limit': stats.get('bytes_limit')}
    except Exception:
        return None


# ---------------------------------------------------------------------------
# step telemetry reporter
# ---------------------------------------------------------------------------
class StepTelemetry:
    """Rolling-window step reporter: examples/sec, tokens/sec, step
    latency, compile totals, cache hit/miss, device memory, FLOP/s.
    Publishes gauges into core.monitor on every end_step; snapshot()
    returns the JSON-ready dict bench.py and the hapi callback read."""

    def __init__(self, window=20, publish=True):
        self.window = int(window)
        self.publish = publish
        self._durs = collections.deque(maxlen=self.window)
        self._examples = collections.deque(maxlen=self.window)
        self._tokens = collections.deque(maxlen=self.window)
        self._t0 = None
        self.steps = 0

    def begin_step(self):
        self._t0 = time.perf_counter()

    def end_step(self, examples=None, tokens=None):
        if self._t0 is None:
            return
        dt = time.perf_counter() - self._t0
        self._t0 = None
        self.steps += 1
        self._durs.append(dt)
        self._examples.append(0 if examples is None else int(examples))
        self._tokens.append(0 if tokens is None else int(tokens))
        if self.publish:
            self._publish()

    @contextlib.contextmanager
    def step(self, examples=None, tokens=None):
        self.begin_step()
        try:
            yield
        finally:
            self.end_step(examples=examples, tokens=tokens)

    # -- derived rates -------------------------------------------------------
    def _rate(self, counts):
        total_t = sum(self._durs)
        if not total_t:
            return 0.0
        return sum(counts) / total_t

    def examples_per_sec(self):
        return self._rate(self._examples)

    def tokens_per_sec(self):
        return self._rate(self._tokens)

    def avg_step_ms(self):
        return (sum(self._durs) / len(self._durs) * 1000.0) \
            if self._durs else 0.0

    def _publish(self):
        g = _monitor.gauge
        g('ptpu_examples_per_sec',
          help='rolling-window training throughput').set(
              self.examples_per_sec())
        if any(self._tokens):
            g('ptpu_tokens_per_sec',
              help='rolling-window token throughput').set(
                  self.tokens_per_sec())
        g('ptpu_step_ms', help='rolling mean step latency').set(
            self.avg_step_ms())
        g('ptpu_steps_total', help='telemetry steps observed').set(
            self.steps)
        mem = device_memory_stats()
        if mem and mem.get('bytes_in_use') is not None:
            g('ptpu_device_bytes_in_use',
              help='live device memory (JAX backend)').set(
                  mem['bytes_in_use'])
        # history sampling rides the publish cadence (ISSUE 18) —
        # no-op unless MetricsRegistry.enable_history() opted in
        _monitor.metrics().history_tick()

    def snapshot(self):
        reg = _monitor.metrics()

        def _counter_total(name):
            m = reg.get(name)
            if m is None:
                return 0.0
            return sum(c.value() for c in m._series().values())
        stats = _monitor.get_stats()
        snap = {
            'steps': self.steps,
            'avg_step_ms': self.avg_step_ms(),
            'examples_per_sec': self.examples_per_sec(),
            'tokens_per_sec': self.tokens_per_sec(),
            'compile_seconds_total':
                _counter_total('ptpu_compile_seconds_total'),
            'compiles_total': _counter_total('ptpu_compiles_total'),
            'compile_cache_hits':
                int(stats.get('STAT_executor_cache_hit', 0)),
            'compile_cache_misses':
                int(stats.get('STAT_executor_cache_miss', 0)),
            'device_memory': device_memory_stats(),
        }
        flops = reg.get('ptpu_xla_flops_per_run')
        if flops is not None:
            snap['xla_flops_per_run'] = {
                k[0]: c.value() for k, c in flops._series().items()}
        # numerics observatory (grad norms, nonfinite/divergence
        # counters, AMP loss scale) — zeros when it never ran
        try:
            from .core import numerics as _numerics
            snap['numerics'] = _numerics.snapshot()
        except Exception:
            snap['numerics'] = None
        # gradient-comm model (ptpu_comm_* gauges from the bucketed
        # engines) + persistent compile cache — docs/performance.md
        try:
            from .core import bucketing as _bucketing
            snap['comm'] = _bucketing.comm_snapshot() or None
        except Exception:
            snap['comm'] = None
        try:
            from .core import compile_cache as _cc
            snap['compile_cache'] = _cc.snapshot()
        except Exception:
            snap['compile_cache'] = None
        # serving engine (ptpu_serve_* gauges: decode tokens/sec, TTFT,
        # batch/page occupancy, preemptions) — docs/serving.md
        try:
            from .serving import metrics as _sm
            snap['serve'] = _sm.serve_snapshot() or None
        except Exception:
            snap['serve'] = None
        # Pallas primitive routing (ptpu_pallas_* counters): which fused
        # kernels vs reference fallbacks the traces picked — a silently
        # degraded route shows up here (docs/performance.md#fused-primitives)
        try:
            from .ops.pallas import scaffold as _scaffold
            snap['pallas'] = _scaffold.snapshot()
        except Exception:
            snap['pallas'] = None
        # async step pipeline (ptpu_host_* gauges): per-site dispatch
        # gap/depth + host_bound_fraction and DeviceLoader prefetch
        # totals — docs/performance.md#async-dispatch
        try:
            from .core import async_step as _async_step
            host = _async_step.host_snapshot()
            snap['host'] = host if (host.get('sites')
                                    or host['prefetch']['batches']) \
                else None
        except Exception:
            snap['host'] = None
        # tuned-remat view (ptpu_remat_* gauges/counters): active policy
        # per engine + checkpoint_name boundary counts, beside the
        # per-site activation-byte census — docs/performance.md#remat-policy
        try:
            from .distributed.fleet.utils.recompute import (
                snapshot as _remat_snapshot)
            from .core import memory as _mem
            remat = _remat_snapshot()
            acts = _mem.activation_bytes()
            if remat is not None or acts:
                remat = dict(remat or {})
                remat['activation_bytes'] = acts or None
            snap['remat'] = remat
        except Exception:
            snap['remat'] = None
        # pipeline schedule census (ptpu_pp_* gauges): active schedule,
        # virtual stages, tick counts and the modeled bubble fraction —
        # docs/performance.md#pipeline-schedules. Gauge presence is
        # checked first so sessions without a pipeline engine never pay
        # the fleet import.
        try:
            snap['pipeline'] = None
            if _monitor.metrics().get('ptpu_pp_ticks') is not None:
                from .distributed.fleet.meta_parallel.spmd_pipeline \
                    import pipeline_snapshot
                snap['pipeline'] = pipeline_snapshot()
        except Exception:
            snap['pipeline'] = None
        # step-time ledger (ISSUE 16): the reconciled wall decomposition
        # + MFU account, read back from the ptpu_ledger_* gauges
        try:
            from .core.ledger import ledger_snapshot
            snap['ledger'] = ledger_snapshot()
        except Exception:
            snap['ledger'] = None
        return snap


__all__ = [
    'RecordEvent', 'record_function', 'Profiler', 'ProfilerState',
    'ProfilerTarget', 'ProfilerResult', 'make_scheduler',
    'export_chrome_tracing_handler', 'start_profiler', 'stop_profiler',
    'reset_profiler', 'summary', 'export_chrome_tracing', 'profiler',
    'start_device_trace', 'stop_device_trace', 'compile_with_telemetry',
    'device_memory_stats', 'StepTelemetry', 'use_native_recorder',
    'native_dropped_events',
]
