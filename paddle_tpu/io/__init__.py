"""paddle_tpu.io — Dataset / DataLoader.

Reference parity: python/paddle/io (Dataset/IterableDataset/TensorDataset/
Sampler family, BatchSampler, DataLoader → fluid/reader.py:146 with
multiprocess workers + blocking queue). TPU-native design: the host pipeline
is a prefetching background-thread loader feeding device transfers (PJRT
handles H2D); with 1 host core per chip here, thread prefetch replaces the
reference's double-buffered reader. A C++ feed pipeline (csrc/datafeed) slots in
underneath for file-based ingestion (reference framework/data_feed.cc).
"""
import itertools
import os
import queue as _queue
import threading
import time as _time

import numpy as np

from ..core import rng
from ..core.tensor import Tensor


class Dataset:
    def __getitem__(self, idx):
        raise NotImplementedError

    def __len__(self):
        raise NotImplementedError


class IterableDataset(Dataset):
    def __iter__(self):
        raise NotImplementedError

    def __getitem__(self, idx):
        raise RuntimeError("IterableDataset has no __getitem__")

    def __len__(self):
        raise RuntimeError("IterableDataset has no __len__")


class TensorDataset(Dataset):
    def __init__(self, tensors):
        self.tensors = tensors

    def __getitem__(self, idx):
        return tuple(t[idx] for t in self.tensors)

    def __len__(self):
        return self.tensors[0].shape[0]


class ComposeDataset(Dataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __len__(self):
        return min(len(d) for d in self.datasets)

    def __getitem__(self, idx):
        out = []
        for d in self.datasets:
            sample = d[idx]
            if isinstance(sample, (list, tuple)):
                out.extend(sample)
            else:
                out.append(sample)
        return tuple(out)


class ChainDataset(IterableDataset):
    def __init__(self, datasets):
        self.datasets = list(datasets)

    def __iter__(self):
        for d in self.datasets:
            yield from d


class Subset(Dataset):
    def __init__(self, dataset, indices):
        self.dataset = dataset
        self.indices = indices

    def __getitem__(self, idx):
        return self.dataset[self.indices[idx]]

    def __len__(self):
        return len(self.indices)


def random_split(dataset, lengths, generator=None):
    total = sum(lengths)
    assert total == len(dataset)
    perm = np.random.permutation(total)
    out, off = [], 0
    for ln in lengths:
        out.append(Subset(dataset, perm[off:off + ln].tolist()))
        off += ln
    return out


class Sampler:
    def __init__(self, data_source=None):
        self.data_source = data_source

    def __iter__(self):
        raise NotImplementedError

    def __len__(self):
        return len(self.data_source)


class SequenceSampler(Sampler):
    def __iter__(self):
        return iter(range(len(self.data_source)))


class RandomSampler(Sampler):
    def __init__(self, data_source, replacement=False, num_samples=None,
                 generator=None):
        super().__init__(data_source)
        self.replacement = replacement
        self._num_samples = num_samples

    @property
    def num_samples(self):
        return self._num_samples or len(self.data_source)

    def __iter__(self):
        n = len(self.data_source)
        if self.replacement:
            return iter(np.random.randint(0, n, self.num_samples).tolist())
        return iter(np.random.permutation(n)[:self.num_samples].tolist())

    def __len__(self):
        return self.num_samples


class WeightedRandomSampler(Sampler):
    def __init__(self, weights, num_samples, replacement=True):
        self.weights = np.asarray(weights, dtype=np.float64)
        self.num_samples = num_samples
        self.replacement = replacement

    def __iter__(self):
        p = self.weights / self.weights.sum()
        idx = np.random.choice(len(self.weights), self.num_samples,
                               replace=self.replacement, p=p)
        return iter(idx.tolist())

    def __len__(self):
        return self.num_samples


class BatchSampler(Sampler):
    """Parity: fluid/dataloader/batch_sampler.py."""

    def __init__(self, dataset=None, sampler=None, shuffle=False,
                 batch_size=1, drop_last=False):
        self.batch_size = batch_size
        self.drop_last = drop_last
        if sampler is not None:
            self.sampler = sampler
        elif shuffle:
            self.sampler = RandomSampler(dataset)
        else:
            self.sampler = SequenceSampler(dataset)

    def __iter__(self):
        batch = []
        for idx in self.sampler:
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        n = len(self.sampler)
        if self.drop_last:
            return n // self.batch_size
        return (n + self.batch_size - 1) // self.batch_size


class DistributedBatchSampler(BatchSampler):
    """Parity: fluid/dataloader/batch_sampler.py DistributedBatchSampler —
    shards the index space across data-parallel ranks."""

    def __init__(self, dataset, batch_size, num_replicas=None, rank=None,
                 shuffle=False, drop_last=False):
        self.dataset = dataset
        self.batch_size = batch_size
        self.shuffle = shuffle
        self.drop_last = drop_last
        if num_replicas is None or rank is None:
            from ..distributed import get_world_size, get_rank
            num_replicas = num_replicas or get_world_size()
            rank = rank if rank is not None else get_rank()
        self.nranks = num_replicas
        self.local_rank = rank
        self.epoch = 0
        self.num_samples = int(np.ceil(len(dataset) / self.nranks))
        self.total_size = self.num_samples * self.nranks

    def __iter__(self):
        n = len(self.dataset)
        indices = np.arange(n)
        if self.shuffle:
            rng_ = np.random.RandomState(self.epoch)
            rng_.shuffle(indices)
            self.epoch += 1
        indices = np.concatenate(
            [indices, indices[:self.total_size - n]])
        indices = indices[self.local_rank::self.nranks]
        batch = []
        for idx in indices.tolist():
            batch.append(idx)
            if len(batch) == self.batch_size:
                yield batch
                batch = []
        if batch and not self.drop_last:
            yield batch

    def __len__(self):
        if self.drop_last:
            return self.num_samples // self.batch_size
        return (self.num_samples + self.batch_size - 1) // self.batch_size

    def set_epoch(self, epoch):
        self.epoch = epoch


def default_collate_fn(batch):
    """Parity: fluid/dataloader/collate.py."""
    sample = batch[0]
    if isinstance(sample, np.ndarray):
        return Tensor(np.stack(batch))
    if isinstance(sample, Tensor):
        return Tensor(np.stack([np.asarray(s.data) for s in batch]))
    if isinstance(sample, (int, np.integer)):
        return Tensor(np.asarray(batch, dtype=np.int64))
    if isinstance(sample, (float, np.floating)):
        return Tensor(np.asarray(batch, dtype=np.float32))
    if isinstance(sample, (list, tuple)):
        return tuple(default_collate_fn(list(items))
                     for items in zip(*batch))
    if isinstance(sample, dict):
        return {k: default_collate_fn([b[k] for b in batch]) for k in sample}
    return batch


def _worker_loop(dataset, index_q, result_q, parent_pid, worker_id,
                 worker_init_fn):
    """Parity: fluid/dataloader/worker.py _worker_loop:251 — reads index
    batches, emits raw samples; the ParentWatchDog role is the getppid
    check (exit when the parent dies)."""
    import queue as q
    if worker_init_fn is not None:
        try:
            worker_init_fn(worker_id)
        except Exception as e:
            result_q.put((-1, None, f"worker_init_fn: {e!r}"))
            return
    while True:
        if os.getppid() != parent_pid:        # parent died
            return
        try:
            item = index_q.get(timeout=1.0)
        except q.Empty:
            continue
        if item is None:
            return
        idx, indices = item
        try:
            result_q.put((idx, [dataset[i] for i in indices], None))
        except Exception as e:
            result_q.put((idx, None, repr(e)))
            return


class DataLoader:
    """Parity: paddle.io.DataLoader (fluid/reader.py:146). num_workers>0
    runs REAL worker processes with an index queue, result reordering and
    parent/worker death detection (A.6); IterableDataset uses a
    background prefetch thread."""

    def __init__(self, dataset, feed_list=None, places=None,
                 return_list=True, batch_sampler=None, batch_size=1,
                 shuffle=False, drop_last=False, collate_fn=None,
                 num_workers=0, use_buffer_reader=True, prefetch_factor=2,
                 use_shared_memory=True, timeout=0, worker_init_fn=None,
                 persistent_workers=False):
        self.dataset = dataset
        self.collate_fn = collate_fn or default_collate_fn
        self.num_workers = num_workers
        self.prefetch = max(2, prefetch_factor)
        self._worker_init_fn = worker_init_fn
        self._timeout = timeout
        self._iterable_ds = isinstance(dataset, IterableDataset)
        if batch_sampler is not None:
            self.batch_sampler = batch_sampler
        elif not self._iterable_ds and batch_size is not None:
            self.batch_sampler = BatchSampler(dataset, shuffle=shuffle,
                                              batch_size=batch_size,
                                              drop_last=drop_last)
        else:
            self.batch_sampler = None
            self.batch_size = batch_size
            self.drop_last = drop_last

    def __len__(self):
        if self.batch_sampler is not None:
            return len(self.batch_sampler)
        raise TypeError("DataLoader over IterableDataset has no len()")

    def _gen(self):
        from .. import profiler as _prof
        produce_h = self._produce_histogram()
        if self._iterable_ds:
            it = iter(self.dataset)
            bs = getattr(self, 'batch_size', 1)
            while True:
                t0 = _time.perf_counter()
                with _prof.RecordEvent('dataloader::produce',
                                       event_type='dataloader'):
                    batch = list(itertools.islice(it, bs))
                    if not batch:
                        return
                    if len(batch) < bs and getattr(self, 'drop_last',
                                                   False):
                        return
                    out = self.collate_fn(batch)
                produce_h.observe(_time.perf_counter() - t0)
                yield out
        else:
            for indices in self.batch_sampler:
                t0 = _time.perf_counter()
                with _prof.RecordEvent('dataloader::produce',
                                       event_type='dataloader'):
                    out = self.collate_fn(
                        [self.dataset[i] for i in indices])
                produce_h.observe(_time.perf_counter() - t0)
                yield out

    @staticmethod
    def _produce_histogram():
        from ..core.monitor import histogram
        return histogram('ptpu_dataloader_produce_seconds',
                         help='time to read+collate one batch')

    @staticmethod
    def _wait_histogram():
        from ..core.monitor import histogram
        return histogram('ptpu_dataloader_wait_seconds',
                         help='time the consumer waits for the next batch')

    def __iter__(self):
        """Instrumented batch stream: `dataloader::next` spans measure
        how long the TRAINING LOOP stalls on data (batch wait), while
        `dataloader::produce` spans (possibly on a worker thread)
        measure read+collate time — the wait/produce split the ISSUE's
        reference StatRegistry surfaces for the feed path."""
        from .. import profiler as _prof
        from ..core.monitor import counter
        wait_h = self._wait_histogram()
        batches = counter('ptpu_dataloader_batches_total',
                          help='batches yielded to the consumer')
        if self.num_workers == 0:
            inner = self._gen()
        elif self._iterable_ds or self.batch_sampler is None:
            inner = self._thread_iter()
        else:
            inner = self._multiprocess_iter()
        while True:
            t0 = _time.perf_counter()
            with _prof.RecordEvent('dataloader::next',
                                   event_type='dataloader'):
                try:
                    batch = next(inner)
                except StopIteration:
                    return
            wait_h.observe(_time.perf_counter() - t0)
            batches.inc(1)
            yield batch

    def _thread_iter(self):
        """Background-thread prefetch (IterableDataset path)."""
        q = _queue.Queue(maxsize=self.prefetch)
        _SENTINEL = object()

        def producer():
            try:
                for item in self._gen():
                    q.put(item)
            finally:
                q.put(_SENTINEL)

        t = threading.Thread(target=producer, daemon=True)
        t.start()
        while True:
            item = q.get()
            if item is _SENTINEL:
                break
            yield item

    def _mp_start_method(self):
        """'spawn' when the dataset/worker_init_fn are picklable, else
        'fork' with a warning. Decided once and cached (the probe streams
        to a null sink — no giant transient bytes for in-memory
        datasets)."""
        if getattr(self, '_mp_method', None) is not None:
            return self._mp_method
        import pickle as _pickle
        import warnings as _warnings

        class _Null:
            def write(self, _):
                return 0
        try:
            _pickle.Pickler(_Null()).dump(self.dataset)
            _pickle.Pickler(_Null()).dump(self._worker_init_fn)
            self._mp_method = 'spawn'
        except Exception:
            _warnings.warn(
                "DataLoader dataset/worker_init_fn is not picklable; "
                "falling back to the 'fork' start method. Forking after "
                "JAX initializes can deadlock workers — make the dataset "
                "picklable (module-level class) to use 'spawn'.",
                RuntimeWarning)
            self._mp_method = 'fork'
        return self._mp_method

    def _multiprocess_iter(self):
        """Real worker processes (parity: fluid/dataloader/worker.py
        _worker_loop:251 + reader.py multiprocess path): an index queue
        feeds num_workers spawned readers; samples return via a result
        queue (raw, collated in the parent — workers never touch the
        device runtime); results reorder to sampler order; a
        ParentWatchDog in each worker exits on parent death, and the
        parent detects dead workers instead of hanging.

        Workers use the 'spawn' start method: forking after JAX has
        initialized its multithreaded runtime can deadlock the child
        (CPython emits 'will likely lead to a deadlock' for exactly this),
        so a fresh interpreter per worker is the only safe default.
        Datasets/worker_init_fn must therefore be picklable; a dataset
        that is not raises at startup instead of hanging mid-epoch."""
        import multiprocessing as mp
        ctx = mp.get_context(self._mp_start_method())
        window = max(2, self.prefetch) * self.num_workers
        index_q = ctx.Queue(maxsize=window)
        result_q = ctx.Queue(maxsize=window)
        total = {}     # set once the (possibly unsized) sampler exhausts

        def feeder():
            """Feed index batches lazily — infinite/streaming samplers
            work, and a huge epoch never materializes up front."""
            n = 0
            for item in enumerate(self.batch_sampler):
                index_q.put(item)
                n += 1
            total['n'] = n
            for _ in range(self.num_workers):
                index_q.put(None)
        feed_thread = threading.Thread(target=feeder, daemon=True)
        feed_thread.start()

        workers = [
            ctx.Process(target=_worker_loop,
                        args=(self.dataset, index_q, result_q,
                              os.getpid(), wid, self._worker_init_fn),
                        daemon=True)
            for wid in range(self.num_workers)]
        for w in workers:
            w.start()
        # timeout semantics (paddle parity): 0 = wait forever; >0 = max
        # wait per BATCH (reset after every yielded batch)
        per_batch = self._timeout if self._timeout else None
        pending = {}
        want = 0
        try:
            while True:
                if 'n' in total and want >= total['n']:
                    break
                waited = 0.0
                while want not in pending:
                    try:
                        idx, samples, err = result_q.get(timeout=1.0)
                    except _queue.Empty:
                        if 'n' in total and want >= total['n']:
                            break
                        if not any(w.is_alive() for w in workers):
                            raise RuntimeError(
                                "DataLoader workers died (see worker "
                                "stderr)")
                        waited += 1.0
                        if per_batch is not None and waited >= per_batch:
                            raise RuntimeError(
                                "DataLoader worker timeout "
                                f"({per_batch}s for one batch)")
                        continue
                    if err is not None:
                        raise RuntimeError(f"DataLoader worker: {err}")
                    pending[idx] = samples
                if want in pending:
                    yield self.collate_fn(pending.pop(want))
                    want += 1
        finally:
            for w in workers:
                if w.is_alive():
                    w.terminate()
            for w in workers:
                w.join(timeout=5)


def get_worker_info():
    return None


# device-side input prefetch (ISSUE 13): background-thread H2D staging
# onto the mesh, overlapping batch t+1's transfer with step t's compute
from .device_loader import DeviceLoader  # noqa: E402
