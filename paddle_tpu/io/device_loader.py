"""DeviceLoader — background-thread device-side input prefetch.

Wraps any DataLoader/iterable of host batches and double/triple-buffers
them onto the mesh from a producer thread: batch t+1's H2D transfer
(`jax.device_put` with the engine's input sharding spec) overlaps step
t's compute, so the training loop never pays the transfer in the host
gap between dispatches. The companion of the engines' windowed dispatch
(core/async_step.py; docs/performance.md#async-dispatch).

Sharding: pass `engine=` (any of the three compiled engines — they
expose `input_sharding(index, ndim)`) so batches land pre-sharded in
the spec the compiled step expects (dp-sharded batch dim under
hybrid/pipeline, replicated under mp-only); or pass explicit
`specs=[PartitionSpec, ...]` + `mesh=`; or neither, and batches go to
the default device whole (the jit.TrainStep shape).

Staging ring: host batches are copied into a reusable ring of
depth+1 staging buffers before the device_put (pinned-host analogue —
steady-state prefetch allocates nothing on the staging side). The
transfer never aliases the ring: on the CPU backend (where device_put
can zero-copy host memory) the loader copies out of the slot
explicitly, and on accelerator backends — where the H2D put is the
copy but PJRT doesn't guarantee it completes before returning — the
ring blocks on a slot's previous transfer before overwriting it (free
in steady state, depth+1 batches later). Ring reuse can therefore
never mutate a batch already handed to a (donating) compiled step.

Gauges: ptpu_host_prefetch_depth, ptpu_host_prefetch_stalls_total
(consumer arrived before a batch was staged), and
ptpu_host_prefetch_h2d_bytes_total; per-instance `stats()` carries the
same counters plus ring reuse counts.
"""
import queue as _queue
import threading

import numpy as np

from ..core import async_step as _async
from ..core.tensor import Tensor


class DeviceLoader:
    """Iterate device-resident batches prefetched from `loader`.

    Each yielded item is a tuple of jax arrays (a non-tuple upstream
    batch yields a 1-tuple), already placed with the resolved sharding.
    Re-iterable: every `__iter__` starts a fresh producer thread over
    `iter(loader)`. `close()` stops an in-flight producer.
    """

    def __init__(self, loader, engine=None, mesh=None, specs=None,
                 depth=None):
        self.loader = loader
        self.engine = engine
        self.mesh = mesh if mesh is not None else (
            getattr(engine, 'mesh', None))
        self.specs = list(specs) if specs is not None else None
        if self.specs is not None and self.mesh is None:
            raise ValueError("DeviceLoader(specs=...) needs mesh= (or an "
                             "engine that carries one)")
        self.depth = _async.resolve_prefetch_depth(depth)
        self._ring = [None] * (self.depth + 1)   # slot -> [np buffers]
        self._ring_pending = [None] * (self.depth + 1)
        self._ring_i = 0
        self._stop = threading.Event()   # the CURRENT iteration's event
        self._producer = None            # the CURRENT producer thread
        self._spec_cache = {}            # (index, ndim) -> (sharding,
                                         #                   aliases)
        self._stats = {'batches': 0, 'stalls': 0, 'h2d_bytes': 0,
                       'ring_reuses': 0}
        self._publish_depth()
        _async.note_prefetch(loaders=1, depth=self.depth)

    # -- sharding resolution --------------------------------------------------
    def _sharding(self, index, ndim):
        """Resolved (sharding, backend_aliases) for batch position
        `index` — cached per (index, ndim): both are loader constants,
        and the prefetch hot path must not re-probe device sets per
        batch."""
        key = (index, ndim)
        cached = self._spec_cache.get(key)
        if cached is not None:
            return cached
        from jax.sharding import NamedSharding, PartitionSpec
        sh = None
        if self.specs is not None:
            if index >= len(self.specs):
                sh = NamedSharding(self.mesh, PartitionSpec())
            else:
                spec = self.specs[index]
                sh = spec if (isinstance(spec, NamedSharding)
                              or hasattr(spec, 'mesh')) \
                    else NamedSharding(self.mesh, spec)
        elif self.engine is not None and hasattr(self.engine,
                                                 'input_sharding'):
            sh = self.engine.input_sharding(index, ndim)
        cached = (sh, self._backend_aliases(sh))
        self._spec_cache[key] = cached
        return cached

    # -- staging + transfer ---------------------------------------------------
    @staticmethod
    def _host_arrays(batch):
        items = batch if isinstance(batch, (tuple, list)) else (batch,)
        out = []
        for b in items:
            if isinstance(b, Tensor):
                b = b.data
            out.append(np.asarray(b))
        return out

    def _stage(self, arrays):
        """Copy the batch into this slot's reusable staging buffers
        (allocated on first use / shape change only). Before reuse, the
        slot's PREVIOUS device arrays are blocked on: PJRT does not
        guarantee device_put's host-side read completes before it
        returns on accelerator backends, so overwriting the buffer
        could race an in-flight H2D. In steady state (depth+1 batches
        later) the transfer is long done and the block is free — and it
        runs on the producer thread, never the dispatch hot loop."""
        i = self._ring_i
        pending = self._ring_pending[i]
        if pending is not None:
            self._ring_pending[i] = None
            for a in pending:
                try:
                    a.block_until_ready()
                except AttributeError:
                    pass
        slot = self._ring[i]
        if slot is None or len(slot) != len(arrays) or any(
                buf.shape != a.shape or buf.dtype != a.dtype
                for buf, a in zip(slot, arrays)):
            slot = [np.empty(a.shape, a.dtype) for a in arrays]
            self._ring[i] = slot
        else:
            self._stats['ring_reuses'] += 1
            _async.note_prefetch(ring_reuses=1)
        for buf, a in zip(slot, arrays):
            np.copyto(buf, a)
        self._ring_i = (i + 1) % len(self._ring)
        return slot, i

    @staticmethod
    def _backend_aliases(sharding):
        """True when device_put may ALIAS a host numpy buffer instead of
        copying (the CPU backend: device memory IS host memory — same
        hazard the engines' `_place` copies around). A real accelerator
        copies on the H2D transfer, so the ring is reusable as-is."""
        try:
            import jax
            if sharding is not None:
                dev = next(iter(sharding.device_set))
                return getattr(dev, 'platform', 'cpu') == 'cpu'
            return jax.default_backend() == 'cpu'
        except Exception:
            return True

    def _transfer(self, staged, slot_idx=None):
        import jax
        out = []
        nbytes = 0
        for j, buf in enumerate(staged):
            sh, aliases = self._sharding(j, buf.ndim)
            # on an aliasing backend the put must not capture the ring
            # slot, or the next wrap would mutate a batch already handed
            # to a (donating) compiled step — copy out of the ring; on
            # TPU the H2D transfer itself is that copy. The CPU dryrun
            # thus pays a second memcpy per batch; deliberate: bypassing
            # the ring there would leave the staging path dead code on
            # the only CI backend, losing its content-verified coverage.
            src = buf.copy() if aliases else buf
            out.append(jax.device_put(src, sh) if sh is not None
                       else jax.device_put(src))
            nbytes += buf.nbytes
        self._stats['h2d_bytes'] += nbytes
        self._stats['batches'] += 1
        if slot_idx is not None:
            # remember what was put from this slot so _stage can block
            # on the transfer before the ring wraps onto it
            self._ring_pending[slot_idx] = tuple(out)
        _async.note_prefetch(batches=1, h2d_bytes=nbytes)
        self._h2d_counter().inc(nbytes)
        return tuple(out)

    # -- iteration ------------------------------------------------------------
    def __iter__(self):
        # one stop event PER iteration: starting a new iteration (or
        # close()) signals the previous producer, which otherwise kept
        # running after an early consumer break and raced the next
        # iteration's producer on the shared staging ring — and JOIN it
        # (it notices the signal within one 0.1s put timeout), because
        # a signal alone leaves it mid-_stage on the shared ring
        self._stop.set()
        prev = getattr(self, '_producer', None)
        if prev is not None and prev.is_alive():
            prev.join(timeout=5)
        stop = self._stop = threading.Event()
        q = _queue.Queue(maxsize=self.depth)
        sentinel = object()
        err = []

        def put_stop_aware(item):
            """timeout-put so a producer blocked on a full queue still
            notices the stop signal (a plain put would pin the thread —
            and the ring — forever after the consumer walks away); the
            sentinel uses the same protocol so a full queue can't drop
            it (the consumer would block forever)."""
            while not stop.is_set():
                try:
                    q.put(item, timeout=0.1)
                    return
                except _queue.Full:
                    continue

        def producer():
            try:
                for batch in self.loader:
                    if stop.is_set():
                        return
                    staged, slot_idx = self._stage(
                        self._host_arrays(batch))
                    put_stop_aware(self._transfer(staged, slot_idx))
            except Exception as e:          # surfaced on the consumer side
                err.append(e)
            finally:
                put_stop_aware(sentinel)
        t = self._producer = threading.Thread(
            target=producer, daemon=True, name='ptpu-device-prefetch')
        t.start()
        import time as _time
        stall_counter = self._stall_counter()
        first_get = True
        try:
            while True:
                # the first get of an iteration always finds an empty
                # queue (the producer hasn't staged batch 0 yet) —
                # startup latency, not a prefetch stall
                stalled = q.empty() and t.is_alive() and not first_get
                first_get = False
                t0 = _time.perf_counter()
                # timeout-get: close() from another thread (or a dead
                # producer whose sentinel was suppressed by the stop
                # signal) must end the iteration, not deadlock a
                # consumer blocked in a plain get()
                while True:
                    try:
                        item = q.get(timeout=0.2)
                        break
                    except _queue.Empty:
                        if stop.is_set() or not t.is_alive():
                            item = sentinel
                            break
                # queue wait = the transfer is in flight on the producer
                # thread, not idle host work: attribute it as blocked
                # time for the next dispatch's host-gap sample (the
                # stall counters below keep it visible on their own axis)
                _async.note_external_blocked(_time.perf_counter() - t0)
                if item is sentinel:
                    break
                if stalled:
                    # the consumer outran the prefetch of a REAL batch —
                    # the signal host_bound diagnosis needs (loader too
                    # slow or depth too small). Counted after the get so
                    # the end-of-stream sentinel wait isn't a phantom
                    # stall.
                    self._stats['stalls'] += 1
                    _async.note_prefetch(stalls=1)
                    stall_counter.inc(1)
                yield item
            if err:
                raise err[0]
        finally:
            # consumer done or walked away: stop the producer and let it
            # drain out of any pending put before the ring is reused
            stop.set()
            while not q.empty():
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
            t.join(timeout=5)

    def __len__(self):
        return len(self.loader)

    def close(self):
        self._stop.set()

    def stats(self):
        return dict(self._stats, depth=self.depth)

    # -- metrics --------------------------------------------------------------
    def _publish_depth(self):
        from ..core.monitor import gauge
        gauge('ptpu_host_prefetch_depth',
              help='DeviceLoader prefetch ring depth').set(self.depth)

    @staticmethod
    def _stall_counter():
        from ..core.monitor import counter
        return counter('ptpu_host_prefetch_stalls_total',
                       help='consumer waits on an empty prefetch queue')

    @staticmethod
    def _h2d_counter():
        from ..core.monitor import counter
        return counter('ptpu_host_prefetch_h2d_bytes_total',
                       help='bytes staged host-to-device by DeviceLoader')
