"""paddle_tpu.utils — misc utilities (parity: python/paddle/utils)."""
from . import download
from . import cpp_extension
from . import unique_name
from . import crypto
from ..core.tensor import Tensor


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"module {module_name} not found")


def run_check():
    import jax
    import jax.numpy as jnp
    x = jnp.ones((2, 2))
    y = (x @ x).block_until_ready()
    print(f"paddle_tpu is installed successfully! "
          f"devices: {jax.devices()}")
    return True


def deprecated(update_to="", since="", reason=""):
    def decorator(fn):
        return fn
    return decorator
