"""paddle_tpu.utils — misc utilities (parity: python/paddle/utils)."""
from . import download
from . import cpp_extension
from . import unique_name
from . import crypto
from ..core.tensor import Tensor


def try_import(module_name, err_msg=None):
    import importlib
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or f"module {module_name} not found")


def run_check():
    import jax
    import jax.numpy as jnp
    x = jnp.ones((2, 2))
    y = (x @ x).block_until_ready()
    print(f"paddle_tpu is installed successfully! "
          f"devices: {jax.devices()}")
    return True


def deprecated(update_to="", since="", reason=""):
    def decorator(fn):
        return fn
    return decorator


def require_version(min_version, max_version=None):
    """paddle.utils.require_version — validate the installed framework
    version against [min_version, max_version]."""
    from .. import __version__
    import re as _re

    def parse(v):
        # zero-pad to 3 segments; tolerate rc/dev suffixes ('2.5.0rc0')
        segs = []
        for x in str(v).split('.')[:3]:
            m = _re.match(r'\d+', x)
            segs.append(int(m.group()) if m else 0)
        while len(segs) < 3:
            segs.append(0)
        return tuple(segs)
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(
            f"installed version {__version__} < required {min_version}")
    if max_version is not None and parse(max_version) < cur:
        raise Exception(
            f"installed version {__version__} > allowed {max_version}")
    return True

from . import unique_name  # noqa
