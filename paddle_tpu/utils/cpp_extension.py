"""Custom-op build system.

Reference parity: python/paddle/utils/cpp_extension (setup():51 / load():716
— JIT-compile user C++/CUDA against the extension ABI, register ops at
import). TPU split: device custom kernels are Pallas (ops/pallas — the
custom-call path XLA understands); HOST custom ops are user C++ compiled
here against a plain C ABI and exposed as paddle ops operating on numpy
buffers (the pre/post-processing niche the reference's CPU custom ops
serve).

User C function signature (one per op):
    extern "C" void <name>(const float* in, float* out, int64_t n);
elementwise contract v1: same-shape float32 in/out.
"""
import ctypes
import os
import subprocess
import tempfile

import numpy as np

from ..core.tensor import Tensor


class CppExtension:
    def __init__(self, sources, extra_compile_args=None):
        self.sources = sources
        self.extra_compile_args = extra_compile_args or []


def _build(sources, extra_args, build_dir, name):
    so = os.path.join(build_dir, f"lib{name}.so")
    if os.path.exists(so) and all(
            os.path.getmtime(s) <= os.path.getmtime(so) for s in sources):
        return so
    cmd = ['g++', '-O2', '-std=c++17', '-fPIC', '-shared',
           *extra_args, *sources, '-o', so]
    r = subprocess.run(cmd, capture_output=True, text=True)
    if r.returncode != 0:
        raise RuntimeError(f"custom op build failed:\n{r.stderr}")
    return so


def load(name, sources, extra_cxx_cflags=None, build_directory=None,
         verbose=False, **kwargs):
    """Parity: cpp_extension.load():716 — JIT-compile and return a module
    exposing each op as a paddle-callable function."""
    build_dir = build_directory or os.path.join(
        tempfile.gettempdir(), 'paddle_tpu_extensions', name)
    os.makedirs(build_dir, exist_ok=True)
    so = _build(list(sources), extra_cxx_cflags or [], build_dir, name)
    lib = ctypes.CDLL(so)

    class _Module:
        pass

    mod = _Module()

    def make_op(sym):
        fn = getattr(lib, sym)
        fn.argtypes = [ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64]

        def op(x):
            arr = np.ascontiguousarray(
                np.asarray(x.data if isinstance(x, Tensor) else x),
                np.float32)
            out = np.empty_like(arr)
            fn(arr.ctypes.data_as(ctypes.c_void_p),
               out.ctypes.data_as(ctypes.c_void_p), arr.size)
            return Tensor(out)
        op.__name__ = sym
        return op

    # discover exported symbols by scanning the sources for extern "C" fns
    import re
    for src in sources:
        with open(src) as f:
            text = f.read()
        for m in re.finditer(
                r'extern\s+"C"\s+void\s+(\w+)\s*\(', text):
            sym = m.group(1)
            setattr(mod, sym, make_op(sym))
    mod._lib = lib
    return mod


def setup(name=None, ext_modules=None, **kwargs):
    """Parity: cpp_extension.setup():51 — eager build (no setuptools install
    step needed for the ctypes path)."""
    mods = ext_modules if isinstance(ext_modules, (list, tuple)) \
        else [ext_modules]
    built = []
    for ext in mods:
        built.append(load(name or 'custom_ops', ext.sources,
                          ext.extra_compile_args))
    return built[0] if len(built) == 1 else built


CUDAExtension = CppExtension  # API compat; TPU kernels go through Pallas
