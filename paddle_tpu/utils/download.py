"""Dataset/file download helper (parity: paddle/utils/download.py).

Zero-egress environments: get_path_from_url only resolves already-cached
paths; the actual fetch raises with a clear message.
"""
import os

DATA_HOME = os.path.expanduser('~/.cache/paddle_tpu/dataset')


def get_weights_path_from_url(url, md5sum=None):
    return get_path_from_url(url, os.path.expanduser('~/.cache/paddle_tpu/weights'))


def get_path_from_url(url, root_dir=DATA_HOME, md5sum=None, check_exist=True):
    fname = os.path.join(root_dir, url.split('/')[-1])
    if os.path.exists(fname):
        return fname
    raise RuntimeError(
        f"{url} is not cached at {fname} and network access is unavailable; "
        "place the file there manually")
