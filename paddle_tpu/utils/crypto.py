"""Model-file encryption utilities (N38).

Reference parity: framework/io/crypto — CipherFactory.CreateCipher
(cipher.cc:23, default "AES_CTR_NoPadding"), AESCipher
(aes_cipher.h:48), CipherUtils.GenKey/GenKeyToFile/ReadKeyFromFile
(cipher_utils.cc:25-55). Used to encrypt serialized programs/params for
deployment (the inference engine decrypts in memory).

TPU-rebuild design: AES-CTR and AES-GCM via the `cryptography` package
(baked into the image) instead of CryptoPP; the factory keys off the
same cipher-name strings so `CipherFactory.create_cipher(
"AES_CTR_NoPadding")` code ports unchanged.
"""
import os

try:
    from cryptography.hazmat.primitives.ciphers import Cipher as _CCipher
    from cryptography.hazmat.primitives.ciphers import algorithms, modes
    HAVE_CRYPTOGRAPHY = True
except ImportError:      # image without the cryptography wheel: surface a
    _CCipher = algorithms = modes = None   # clear error at USE, not import
    HAVE_CRYPTOGRAPHY = False

__all__ = ['Cipher', 'AESCipher', 'CipherFactory', 'CipherUtils']


class Cipher:
    """Parity: framework/io/crypto/cipher.h Cipher interface."""

    def encrypt(self, plaintext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def decrypt(self, ciphertext: bytes, key: bytes) -> bytes:
        raise NotImplementedError

    def encrypt_to_file(self, plaintext, key, filename):
        with open(filename, 'wb') as f:
            f.write(self.encrypt(plaintext, key))

    def decrypt_from_file(self, key, filename):
        with open(filename, 'rb') as f:
            return self.decrypt(f.read(), key)


class AESCipher(Cipher):
    """AES-CTR (default) or AES-GCM, IV/tag framed into the ciphertext —
    parity: aes_cipher.h AESCipher::Init/BuildCipher."""

    def __init__(self, cipher_name='AES_CTR_NoPadding', iv_size=128,
                 tag_size=128):
        if not HAVE_CRYPTOGRAPHY:
            raise RuntimeError(
                "paddle_tpu.utils.crypto requires the 'cryptography' "
                "package, which is not installed in this environment")
        if 'AES' not in cipher_name:
            raise ValueError(f"not an AES cipher: {cipher_name!r}")
        self._gcm = 'GCM' in cipher_name
        # CTR requires a 16-byte nonce and GCM tags are 4..16 bytes —
        # validate configured sizes instead of framing undecryptable
        # files (cipher_utils.cc enforces the same ranges)
        if not self._gcm and iv_size != 128:
            raise ValueError("AES-CTR requires iv_size=128 bits")
        if self._gcm and not (32 <= tag_size <= 128):
            raise ValueError("AES-GCM tag_size must be 32..128 bits")
        if tag_size % 8 or iv_size % 8:
            raise ValueError("iv_size/tag_size must be multiples of 8")
        self._iv_bytes = 16 if not self._gcm else 12
        self._tag_bytes = tag_size // 8
        self.name = cipher_name

    def _mode(self, iv, tag=None):
        if self._gcm:
            if tag is not None:
                return modes.GCM(iv, tag, min_tag_length=len(tag))
            return modes.GCM(iv)
        return modes.CTR(iv)

    def encrypt(self, plaintext, key):
        if isinstance(plaintext, str):
            plaintext = plaintext.encode()
        iv = os.urandom(self._iv_bytes)
        enc = _CCipher(algorithms.AES(key), self._mode(iv)).encryptor()
        ct = enc.update(plaintext) + enc.finalize()
        if self._gcm:
            return bytes([len(iv)]) + iv + enc.tag[:self._tag_bytes] + ct
        return bytes([len(iv)]) + iv + ct

    def decrypt(self, ciphertext, key):
        n_iv = ciphertext[0]
        iv = ciphertext[1:1 + n_iv]
        rest = ciphertext[1 + n_iv:]
        if self._gcm:
            tag, ct = rest[:self._tag_bytes], rest[self._tag_bytes:]
            dec = _CCipher(algorithms.AES(key),
                           self._mode(iv, tag)).decryptor()
            return dec.update(ct) + dec.finalize()
        dec = _CCipher(algorithms.AES(key), self._mode(iv)).decryptor()
        return dec.update(rest) + dec.finalize()


class CipherFactory:
    """Parity: cipher.cc CipherFactory::CreateCipher — config file with
    `cipher_name: <name>` lines, default AES_CTR_NoPadding."""

    @staticmethod
    def create_cipher(config_file=None):
        name = 'AES_CTR_NoPadding'
        iv_size = tag_size = 128
        if config_file:
            cfg = CipherUtils.load_config(config_file)
            name = cfg.get('cipher_name', name)
            iv_size = int(cfg.get('iv_size', iv_size))
            tag_size = int(cfg.get('tag_size', tag_size))
        if 'AES' in name:
            return AESCipher(name, iv_size, tag_size)
        raise ValueError(f"unsupported cipher {name!r}")


class CipherUtils:
    """Parity: cipher_utils.cc."""

    @staticmethod
    def gen_key(length):
        """length in BITS (reference GenKey(int length))."""
        if length % 8:
            raise ValueError("key length must be a multiple of 8 bits")
        return os.urandom(length // 8)

    @staticmethod
    def gen_key_to_file(length, filename):
        key = CipherUtils.gen_key(length)
        with open(filename, 'wb') as f:
            f.write(key)
        return key

    @staticmethod
    def read_key_from_file(filename):
        with open(filename, 'rb') as f:
            return f.read()

    @staticmethod
    def load_config(filename):
        out = {}
        with open(filename) as f:
            for line in f:
                line = line.strip()
                if not line or line.startswith('#'):
                    continue
                k, _, v = line.partition(':')
                out[k.strip()] = v.strip()
        return out
