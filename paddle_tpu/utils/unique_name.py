"""paddle.utils.unique_name (reference:
python/paddle/fluid/unique_name.py): the process-global name generator
behind auto-named parameters, with guard() to scope naming — two
SPMD ranks building structurally-identical Programs inside separate
guard() blocks get IDENTICAL names (required for the multi-rank
collective simulators), while unguarded Programs keep process-unique
names (required for scope safety — see program.py _unique_name)."""
import contextlib


def generate(key):
    from ..static import program as _prog
    n = _prog._GLOBAL_NAME_COUNTER.get(key, 0)
    _prog._GLOBAL_NAME_COUNTER[key] = n + 1
    return f"{key}_{n}"


@contextlib.contextmanager
def guard(new_generator=None):
    """Scope the global name counters: inside the guard, naming starts
    fresh (or from `new_generator`'s state); on exit the previous
    counters are restored."""
    from ..static import program as _prog
    saved = dict(_prog._GLOBAL_NAME_COUNTER)
    _prog._GLOBAL_NAME_COUNTER.clear()
    try:
        yield
    finally:
        _prog._GLOBAL_NAME_COUNTER.clear()
        _prog._GLOBAL_NAME_COUNTER.update(saved)


def switch(new_generator=None):
    from ..static import program as _prog
    old = dict(_prog._GLOBAL_NAME_COUNTER)
    _prog._GLOBAL_NAME_COUNTER.clear()
    return old
