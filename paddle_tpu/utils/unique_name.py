"""paddle.utils.unique_name (reference:
python/paddle/fluid/unique_name.py): the process-global name generator
behind auto-named parameters, with guard() to scope naming — two
SPMD ranks building structurally-identical Programs inside separate
guard() blocks get IDENTICAL names (required for the multi-rank
collective simulators), while unguarded Programs keep process-unique
names (required for scope safety — see program.py _unique_name)."""
import contextlib


def generate(key):
    from ..static import program as _prog
    n = _prog._GLOBAL_NAME_COUNTER.get(key, 0)
    _prog._GLOBAL_NAME_COUNTER[key] = n + 1
    return f"{_prog._GLOBAL_NAME_PREFIX}{key}_{n}"


@contextlib.contextmanager
def guard(new_generator=None, merge_high_water=False):
    """Scope the global name counters: inside the guard, naming starts
    fresh. `new_generator`, when given as a str, prefixes every name
    minted inside the guard (reference: fluid/unique_name.py
    UniqueNameGenerator prefix) — so twin guarded Programs CAN opt out
    of name sharing by using distinct prefixes.

    On exit the previous counters are restored EXACTLY (reference
    semantics): a Program built after the guard mints the same names it
    would have without the guard, which is what parameter-name-keyed
    checkpoint compatibility requires, and two sequential guard() blocks
    repeat names — what the multi-rank SPMD simulators need
    (structurally-identical Programs on every rank get identical
    parameter names). The flip side: a name minted AFTER the guard can
    collide with one minted inside it, and in one shared Scope the two
    alias one buffer — build twin Programs in separate
    scopes/processes, or pass `merge_high_water=True` to fold the
    guarded block's high-water marks into the restored counters
    (collision-proof, checkpoint-name-shifting; see
    docs/MIGRATION.md "Checkpoint name compatibility").
    """
    from ..static import program as _prog
    saved = dict(_prog._GLOBAL_NAME_COUNTER)
    saved_prefix = _prog._GLOBAL_NAME_PREFIX
    _prog._GLOBAL_NAME_COUNTER.clear()
    if isinstance(new_generator, (str, bytes)):
        _prog._GLOBAL_NAME_PREFIX = (
            new_generator.decode() if isinstance(new_generator, bytes)
            else new_generator)
    else:
        # a plain nested guard() starts a FRESH generator — empty
        # prefix, like the reference's guard(None)
        _prog._GLOBAL_NAME_PREFIX = ''
    try:
        yield
    finally:
        guarded = dict(_prog._GLOBAL_NAME_COUNTER)
        _prog._GLOBAL_NAME_PREFIX = saved_prefix
        _prog._GLOBAL_NAME_COUNTER.clear()
        _prog._GLOBAL_NAME_COUNTER.update(saved)
        if merge_high_water:
            for k, n in guarded.items():
                if n > _prog._GLOBAL_NAME_COUNTER.get(k, 0):
                    _prog._GLOBAL_NAME_COUNTER[k] = n


def switch(new_generator=None):
    """Swap the whole name-generator state (counters + prefix) and
    return the previous state — pass a returned state back in to
    restore it, or a str to install a fresh generator with that prefix
    (reference: fluid/unique_name.py switch)."""
    from ..static import program as _prog
    old = {'counters': dict(_prog._GLOBAL_NAME_COUNTER),
           'prefix': _prog._GLOBAL_NAME_PREFIX}
    _prog._GLOBAL_NAME_COUNTER.clear()
    _prog._GLOBAL_NAME_PREFIX = ''
    if isinstance(new_generator, (str, bytes)):
        _prog._GLOBAL_NAME_PREFIX = (
            new_generator.decode() if isinstance(new_generator, bytes)
            else new_generator)
    elif isinstance(new_generator, dict):
        _prog._GLOBAL_NAME_COUNTER.update(
            new_generator.get('counters', new_generator))
        _prog._GLOBAL_NAME_PREFIX = new_generator.get('prefix', '')
    return old
