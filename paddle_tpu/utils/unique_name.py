"""Unique name generator (parity: fluid/unique_name.py)."""
import collections
import contextlib

_counters = collections.defaultdict(int)


def generate(key):
    _counters[key] += 1
    return f"{key}_{_counters[key] - 1}"


@contextlib.contextmanager
def guard(new_generator=None):
    global _counters
    saved = _counters
    _counters = collections.defaultdict(int)
    try:
        yield
    finally:
        _counters = saved


def switch(new_generator=None):
    global _counters
    _counters = collections.defaultdict(int)
