"""paddle.version parity."""
full_version = '0.1.0'
major = '0'
minor = '1'
patch = '0'
rc = '0'
istaged = True
commit = 'tpu-native'
with_tpu = 'ON'
cuda_version = 'False'
cudnn_version = 'False'


def show():
    print(f"paddle_tpu {full_version} (tpu-native, commit {commit})")
