"""paddle.inference — deployment API sheet (reference:
python/paddle/inference/__init__.py over paddle_infer C++; here the
StableHLO-AOT Predictor from static/inference.py is the engine, and
Config carries the knobs that map onto it. GPU/TRT/MKLDNN switches are
accepted so ported serving scripts run unchanged — but each inert switch
warns once, so nobody believes e.g. enable_tensorrt_engine() did
anything (the knobs' real home is paddle_pass_builder.cc)."""
import enum
import warnings

import numpy as np

from .core.tensor import Tensor


class DataType(enum.Enum):
    FLOAT32 = 'float32'
    FLOAT16 = 'float16'
    INT32 = 'int32'
    INT64 = 'int64'
    UINT8 = 'uint8'
    INT8 = 'int8'


class PrecisionType(enum.Enum):
    Float32 = 'float32'
    Half = 'float16'
    Int8 = 'int8'


class PlaceType(enum.Enum):
    CPU = 'cpu'
    GPU = 'gpu'
    XPU = 'xpu'
    UNK = 'unk'


def get_num_bytes_of_data_type(dtype):
    """paddle.inference.get_num_bytes_of_data_type."""
    return np.dtype(DataType(dtype).value if isinstance(dtype, DataType)
                    else dtype).itemsize


def get_version():
    """paddle.inference.get_version."""
    from . import __version__
    return __version__


class Config:
    """paddle.inference.Config(prog_file?, params_file?) — model path +
    accepted-but-subsumed device/optimization switches."""

    def __init__(self, prog_file=None, params_file=None):
        self._path_prefix = None
        self._params_file = None
        self._device = 'cpu'
        self._enabled = {}
        if prog_file is not None:
            self.set_model(prog_file, params_file)

    def set_model(self, prog_file, params_file=None):
        if prog_file.endswith('.pdmodel'):
            prog_file = prog_file[:-len('.pdmodel')]
        self._path_prefix = prog_file
        self._params_file = params_file

    def model_dir(self):
        return self._path_prefix

    def _warn_inert(self, knob):
        # once per Config instance per knob
        if knob not in self._enabled:
            warnings.warn(
                f"paddle.inference.Config.{knob} is accepted for script "
                f"compatibility but has NO effect on this TPU/XLA build: "
                f"the StableHLO-AOT predictor runs on the PJRT default "
                f"device with XLA's own fusion/memory planning.",
                UserWarning, stacklevel=3)

    def enable_use_gpu(self, memory_pool_init_size_mb=100, device_id=0):
        self._warn_inert('enable_use_gpu')
        self._enabled['enable_use_gpu'] = True
        self._device = 'gpu'

    def disable_gpu(self):
        self._device = 'cpu'

    def use_gpu(self):
        return self._device == 'gpu'

    # accepted switches the XLA path subsumes (fusion, memory planning) —
    # these two are genuinely satisfied by XLA, so no warning
    def switch_ir_optim(self, flag=True):
        self._enabled['ir_optim'] = flag

    def enable_memory_optim(self):
        self._enabled['memory_optim'] = True

    def enable_mkldnn(self):
        self._warn_inert('enable_mkldnn')
        self._enabled['enable_mkldnn'] = True
        self._enabled['mkldnn'] = True

    def enable_tensorrt_engine(self, *a, **k):
        self._warn_inert('enable_tensorrt_engine')
        self._enabled['enable_tensorrt_engine'] = True
        self._enabled['trt'] = True

    def set_cpu_math_library_num_threads(self, n):
        self._enabled['cpu_threads'] = n

    def enable_serving_engine(self, model, max_new_tokens=32,
                              eos_token_id=None, temperature=1.0,
                              top_k=0, pad_token_id=0, **engine_knobs):
        """Route this Config's Predictor through the continuous-batching
        serving engine (serving/engine.py: paged KV pool + batched
        decode) instead of a StableHLO-AOT artifact. `model` is a
        GPTForCausalLM (or compatible) instance; `engine_knobs` are
        ServingConfig knobs (page_size, max_batch_size, prefill_chunk,
        num_pages, ...) — including the quantization pair
        `kv_dtype='int8'` (block-paged int8 KV with in-kernel dequant)
        and `weight_dtype='int8'` (weight-only-quantized decode via
        quantization.quantize_to_int8; the PrecisionType.Int8 story
        for the engine route — docs/serving.md#weight-only).
        Predictor.run then takes token-id prompts and returns
        generated ids — see docs/serving.md#predictor."""
        self._serving_model = model
        self._serving_gen = {'max_new_tokens': max_new_tokens,
                             'eos_token_id': eos_token_id,
                             'temperature': temperature, 'top_k': top_k}
        self._serving_pad = int(pad_token_id)
        self._serving_knobs = dict(engine_knobs)
        self._enabled['serving_engine'] = True

    def summary(self):
        return f"Config(path={self._path_prefix}, device={self._device})"


class Predictor:
    """paddle.inference.Predictor — wraps the StableHLO-AOT predictor
    (static/inference.py): same get_input_names/get_input_handle/run
    surface as the reference's paddle_infer::Predictor."""

    def __init__(self, config, _shared_inner=None):
        self._engine = None
        if getattr(config, '_serving_model', None) is not None:
            # serving-engine route (Config.enable_serving_engine): the
            # engine owns the paged KV pool and the batched decode loop
            from .serving import ServingEngine, ServingConfig
            self._engine = (_shared_inner if _shared_inner is not None
                            else ServingEngine(
                                config._serving_model,
                                ServingConfig(**config._serving_knobs)))
            self._inner = self._engine
            self._gen_kw = dict(config._serving_gen)
            self._pad = config._serving_pad
            self._names = ['input_ids']
            self._feeds = {}
            self._n_out = 1
            return
        from .static.inference import load_predictor
        self._inner = _shared_inner if _shared_inner is not None \
            else load_predictor(config.model_dir())
        # the AOT artifact is positional; expose x0..xn names like the
        # reference exposes the serialized feed targets
        self._names = [f'x{i}'
                       for i in range(len(self._inner.input_specs))]
        self._feeds = {}
        # output arity comes from the StableHLO module at load time, so
        # names are enumerable before the first run() (reference parity);
        # None only for pre-r5 artifacts loaded by an inner without it
        self._n_out = getattr(self._inner, 'n_outputs', None)

    def get_input_names(self):
        return list(self._names)

    def get_output_names(self):
        if self._n_out is None:
            raise RuntimeError(
                "output arity is discovered at the first run(): call "
                "run() once, then enumerate get_output_names()")
        return [f'out_{i}' for i in range(self._n_out)]

    def get_input_handle(self, name):
        return _Handle(self, name)

    def get_output_handle(self, name):
        return _OutHandle(self, name)

    def run(self, inputs=None):
        if inputs is None:                  # handle-style call
            inputs = [self._feeds[n] for n in self._names]
        if self._engine is not None:
            return self._run_serving(inputs[0])
        outs = self._inner.run(*inputs)
        # flatten to pytree LEAVES so the run-time arity agrees with the
        # load-time one (n_outputs = out_tree.num_leaves): a model
        # returning (logits, (h, c)) serves three arrays, not two slots
        # one of which is a tuple
        import jax
        self._outputs = jax.tree_util.tree_leaves(outs)
        self._n_out = len(self._outputs)
        return self._outputs

    def _run_serving(self, prompts):
        """Serving-engine run: `prompts` is a list of ragged token-id
        sequences or a padded [B, L] int array (rows trimmed of
        trailing pad_token_id). Returns ONE output: generated ids
        padded back to [B, L_max] with pad_token_id."""
        if isinstance(prompts, np.ndarray) and prompts.ndim == 2:
            rows = []
            for row in prompts:
                row = list(np.asarray(row).astype(np.int64))
                while row and row[-1] == self._pad:
                    row.pop()
                rows.append(row)
            prompts = rows
        prompts = list(prompts)
        empty = [i for i, p in enumerate(prompts) if len(p) == 0]
        if empty:
            raise ValueError(
                f"prompt rows {empty} are empty"
                f"{' after pad trimming' if self._pad is not None else ''}"
                " — the engine needs at least one token per request")
        if not prompts:
            self._outputs = [np.zeros((0, 0), np.int32)]
            self._n_out = 1
            return self._outputs
        outs = self._engine.generate(prompts, **self._gen_kw)
        n = max(len(o) for o in outs)
        padded = np.full((len(outs), n), self._pad, np.int32)
        for i, o in enumerate(outs):
            padded[i, :len(o)] = o
        self._outputs = [padded]
        self._n_out = 1
        return self._outputs


class _Handle:
    def __init__(self, pred, name):
        self._pred, self._name = pred, name

    def copy_from_cpu(self, arr):
        self._pred._feeds[self._name] = np.asarray(arr)

    def reshape(self, shape):
        pass                                 # shapes fixed at export


class _OutHandle:
    def __init__(self, pred, name):
        self._pred, self._name = pred, name

    def copy_to_cpu(self):
        outs = getattr(self._pred, '_outputs', None)
        if outs is None:
            raise RuntimeError("run() the predictor first")
        names = self._pred.get_output_names()
        if self._name not in names:
            raise KeyError(
                f"unknown output {self._name!r}; outputs: {names}")
        o = outs[names.index(self._name)]
        return np.asarray(o.data if isinstance(o, Tensor) else o)


class PredictorPool:
    """paddle.inference.PredictorPool — N predictors SHARING one loaded
    model (one StableHLO deserialization, one device copy of the
    weights — the reference's weight-sharing semantics)."""

    def __init__(self, config, size=1):
        first = Predictor(config)
        self._preds = [first] + [
            Predictor(config, _shared_inner=first._inner)
            for _ in range(int(size) - 1)]

    def retrive(self, idx):                  # [sic] reference spelling
        return self._preds[idx]

    retrieve = retrive


def create_predictor(config):
    """paddle.inference.create_predictor."""
    return Predictor(config)
