"""Quantization (slim-lite): fake-quant ops, imperative QAT, static QAT
pass, and int8 export.

Reference parity:
  * fake_quantize op family — operators/fake_quantize_op.cc
    (FindAbsMaxFunctor:33, ClipAndFakeQuantFunctor:86, the
    quant+dequant variants, channel-wise, moving-average state)
  * QuantizationTransformPass —
    contrib/slim/quantization/quantization_pass.py:263 (insert fake
    quant/dequant on quantizable ops' inputs)
  * ImperativeQuantAware — contrib/slim/quantization/imperative/qat.py
    (wrap Linear/Conv2D with quant-aware forwards)
  * PostTrainingQuantization — post_training_quantization.py (abs-max
    calibration, int8 weight export)

TPU-native design: fake quant-dequant is a single fused elementwise
program with a straight-through-estimator custom VJP (the reference's
separate quant/dequant CUDA kernels fuse away in XLA); moving-average
scales are ordinary buffers threaded through jit; int8 export stores
int8 weights + fp32 scales in the same data-only container
static/inference.py uses.

Load-bearing consumers (ISSUE 7): the serving engine's weight-only-
quantized decode (`ServingConfig(weight_dtype='int8')` — per-channel
`quantize_to_int8` with fused in-step dequant, docs/serving.md
#weight-only); the block-scaled int8 collective wire and int8 KV-cache
pages reuse the same symmetric abs-max scheme in their own layouts
(`core/bucketing.quantize_blocks`,
`ops/pallas/paged_attention.quantize_kv_rows`).
"""
import functools
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import run_op
from ..ops.common import as_tensor

__all__ = [
    'fake_quantize_dequantize_abs_max',
    'fake_channel_wise_quantize_dequantize_abs_max',
    'fake_quantize_dequantize_moving_average_abs_max',
    'quantize_to_int8', 'dequantize_from_int8',
    'ImperativeQuantAware', 'QuantizationTransformPass',
    'export_quantized_layer', 'load_quantized_predictor',
]


# ---------------------------------------------------------------------------
# fake quant ops (straight-through estimator VJP)
# ---------------------------------------------------------------------------

@functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
def _fake_qdq(x, scale, bin_cnt):
    s = jnp.maximum(scale, 1e-8)
    q = jnp.round(jnp.clip(x, -s, s) * (bin_cnt / s))
    return q * (s / bin_cnt)


def _fake_qdq_fwd(x, scale, bin_cnt):
    return _fake_qdq(x, scale, bin_cnt), (x, scale)


def _fake_qdq_bwd(bin_cnt, res, g):
    x, scale = res
    # straight-through inside the clip range (fake_quantize_op grads)
    inside = (jnp.abs(x) <= jnp.maximum(scale, 1e-8)).astype(g.dtype)
    return g * inside, jnp.zeros_like(scale)


_fake_qdq.defvjp(_fake_qdq_fwd, _fake_qdq_bwd)


def fake_quantize_dequantize_abs_max(x, bits=8, name=None):
    """Parity: fake_quantize_dequantize_abs_max — per-tensor abs-max scale
    from the CURRENT tensor. Returns (out, scale)."""
    x = as_tensor(x)
    bin_cnt = float(2 ** (bits - 1) - 1)

    def fn(a):
        s = jnp.max(jnp.abs(a.astype(jnp.float32)))
        out = _fake_qdq(a.astype(jnp.float32), s, bin_cnt)
        return out.astype(a.dtype), s
    return run_op('fake_quantize_dequantize_abs_max', fn, [x])


def fake_channel_wise_quantize_dequantize_abs_max(x, quant_axis=0, bits=8,
                                                  name=None):
    """Parity: fake_channel_wise_quantize_dequantize_abs_max — per-channel
    scales along quant_axis (0 for conv filters, 1 for mul/matmul
    weights). Returns (out, scales)."""
    if quant_axis not in (0, 1):
        raise ValueError("'quant_axis' should be 0 or 1, got "
                         f"{quant_axis}")
    x = as_tensor(x)
    bin_cnt = float(2 ** (bits - 1) - 1)

    def fn(a):
        af = a.astype(jnp.float32)
        axes = tuple(i for i in range(af.ndim) if i != quant_axis)
        s = jnp.max(jnp.abs(af), axis=axes)        # [C]
        shape = [1] * af.ndim
        shape[quant_axis] = af.shape[quant_axis]
        out = _fake_qdq(af, s.reshape(shape), bin_cnt)
        return out.astype(a.dtype), s
    return run_op('fake_channel_wise_quantize_dequantize_abs_max', fn, [x])


def fake_quantize_dequantize_moving_average_abs_max(
        x, scale_state, moving_rate=0.9, bits=8, training=True, name=None):
    """Parity: fake_quantize_dequantize_moving_average_abs_max — EMA of
    the per-batch abs max; eval uses the accumulated scale unchanged.
    scale_state: Tensor scalar. Returns (out, new_scale_state)."""
    x, scale_state = as_tensor(x), as_tensor(scale_state)
    bin_cnt = float(2 ** (bits - 1) - 1)
    r = float(moving_rate)

    def fn(a, st):
        af = a.astype(jnp.float32)
        if training:
            cur = jnp.max(jnp.abs(af))
            new = jnp.where(st > 0, r * st + (1 - r) * cur, cur)
        else:
            new = st
        out = _fake_qdq(af, new, bin_cnt)
        return out.astype(a.dtype), new
    return run_op('fake_quantize_dequantize_moving_average_abs_max', fn,
                  [x, scale_state])


def quantize_to_int8(arr, quant_axis=None, bits=8):
    """Concrete (host-side) int8 quantization for export: returns
    (int8 np.ndarray, fp32 scales np.ndarray). Parity: the export path of
    post_training_quantization.py."""
    a = np.asarray(arr, np.float32)
    bin_cnt = float(2 ** (bits - 1) - 1)
    if quant_axis is None:
        s = np.maximum(np.max(np.abs(a)), 1e-8)
        q = np.round(np.clip(a, -s, s) * (bin_cnt / s)).astype(np.int8)
        return q, np.float32(s)
    axes = tuple(i for i in range(a.ndim) if i != quant_axis)
    s = np.maximum(np.max(np.abs(a), axis=axes), 1e-8)
    shape = [1] * a.ndim
    shape[quant_axis] = a.shape[quant_axis]
    q = np.round(np.clip(a, -s.reshape(shape), s.reshape(shape))
                 * (bin_cnt / s.reshape(shape))).astype(np.int8)
    return q, s.astype(np.float32)


def dequantize_from_int8(q, scale, quant_axis=None, bits=8):
    bin_cnt = float(2 ** (bits - 1) - 1)
    qf = np.asarray(q, np.float32)
    s = np.asarray(scale, np.float32)
    if quant_axis is None:
        return qf * (s / bin_cnt)
    shape = [1] * qf.ndim
    shape[quant_axis] = qf.shape[quant_axis]
    return qf * (s.reshape(shape) / bin_cnt)


# ---------------------------------------------------------------------------
# imperative QAT (dygraph)
# ---------------------------------------------------------------------------

class _QuantWrapper:
    """Quant-aware forward for one Linear/Conv2D: fake-qdq the input
    (moving-average scale buffer) and the weight (abs-max / channel-wise)
    before the original forward (parity: imperative/qat.py QuantedLinear/
    QuantedConv2D)."""

    def __init__(self, layer, weight_quantize_type, activation_bits,
                 weight_bits, moving_rate, weight_axis):
        self.layer = layer
        self.weight_quantize_type = weight_quantize_type
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self.moving_rate = moving_rate
        self.weight_axis = weight_axis
        self._orig_forward = layer.forward
        layer.register_buffer('_act_quant_scale',
                              Tensor(jnp.zeros((), jnp.float32)))
        layer.forward = self._forward

    def _forward(self, x, *args, **kwargs):
        layer = self.layer
        x = as_tensor(x)
        xq, new_scale = fake_quantize_dequantize_moving_average_abs_max(
            x, layer._act_quant_scale, moving_rate=self.moving_rate,
            bits=self.activation_bits, training=layer.training)
        if layer.training:
            # keep it a buffer: a stop_gradient=False Tensor would
            # re-register as a parameter through Layer.__setattr__
            new_scale.stop_gradient = True
            layer._act_quant_scale = new_scale
        w = layer.weight
        if self.weight_quantize_type == 'channel_wise_abs_max':
            wq, _ = fake_channel_wise_quantize_dequantize_abs_max(
                w, quant_axis=self.weight_axis, bits=self.weight_bits)
        else:
            wq, _ = fake_quantize_dequantize_abs_max(
                w, bits=self.weight_bits)
        orig_w = layer.weight
        layer.weight = wq
        try:
            return self._orig_forward(xq, *args, **kwargs)
        finally:
            layer.weight = orig_w


class ImperativeQuantAware:
    """Parity: contrib/slim/quantization/imperative/qat.py
    ImperativeQuantAware — in-place quant-aware rewrite of a dygraph
    model's Linear/Conv2D sublayers."""

    def __init__(self, quantizable_layer_type=('Conv2D', 'Linear'),
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 weight_bits=8, activation_bits=8, moving_rate=0.9):
        if activation_quantize_type != 'moving_average_abs_max':
            raise NotImplementedError(activation_quantize_type)
        self.types = tuple(quantizable_layer_type)
        self.weight_quantize_type = weight_quantize_type
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.moving_rate = moving_rate

    def quantize(self, model):
        from ..nn.layer.common import Linear
        from ..nn.layer.conv import Conv2D
        type_map = {'Linear': Linear, 'Conv2D': Conv2D}
        targets = tuple(type_map[t] for t in self.types if t in type_map)
        wrapped = []
        for name, sub in model.named_sublayers(include_self=True):
            if isinstance(sub, targets) and \
                    not hasattr(sub, '_quant_wrapper'):
                # conv filters quantize per-output-channel on axis 0,
                # Linear [in, out] weights on axis 1 (quantization_pass
                # conv/mul convention)
                axis = 0 if isinstance(sub, type_map.get('Conv2D', ()))\
                    else 1
                sub._quant_wrapper = _QuantWrapper(
                    sub, self.weight_quantize_type, self.activation_bits,
                    self.weight_bits, self.moving_rate, axis)
                wrapped.append(name)
        if not wrapped:
            raise ValueError("no quantizable sublayers found")
        return model


# ---------------------------------------------------------------------------
# static QAT pass
# ---------------------------------------------------------------------------

class QuantizationTransformPass:
    """Parity: quantization_pass.py:263 — insert fake quant-dequant ops on
    the float inputs of quantizable ops in a recorded Program. Scales are
    emitted as extra outputs so a calibration run can fetch them."""

    _supported_quantizable_op_type = ['conv2d', 'depthwise_conv2d',
                                     'conv2d_transpose', 'mul', 'matmul',
                                     'matmul_v2']

    def __init__(self, weight_bits=8, activation_bits=8,
                 quantizable_op_type=('conv2d', 'depthwise_conv2d', 'mul',
                                      'matmul', 'matmul_v2'),
                 skip_pattern=('skip_quant',)):
        self.weight_bits = weight_bits
        self.activation_bits = activation_bits
        self.ops = set(quantizable_op_type)
        self.skip_pattern = tuple(skip_pattern)

    def apply(self, program):
        """Rewrite in place; returns the number of quant ops inserted."""
        from ..static.program import Variable, Operator, OpRole
        from ..core import dtypes as _dt
        block = program.global_block()
        out_ops = []
        quantized = {}        # var name -> quantized var name
        n = 0
        for op in block.ops:
            if op.type in self.ops and not any(
                    p in (op.attrs.get('name') or '')
                    for p in self.skip_pattern):
                new_ins = []
                for iname in op.input_names:
                    v = block.vars.get(iname)
                    if v is None or not _dt.is_floating(v.dtype):
                        new_ins.append(iname)
                        continue
                    if iname in quantized:
                        new_ins.append(quantized[iname])
                        continue
                    bits = self.weight_bits if getattr(
                        v, 'is_parameter', False) else self.activation_bits
                    bin_cnt = float(2 ** (bits - 1) - 1)
                    qname = f"{iname}.quantized"
                    sname = f"{iname}.quant_scale"
                    qv = Variable(block, qname, v.shape, v.dtype,
                                  stop_gradient=v.stop_gradient)
                    sv = Variable(block, sname, [], jnp.float32)
                    block.vars[qname] = qv
                    block.vars[sname] = sv

                    def qfn(a, _b=bin_cnt):
                        af = a.astype(jnp.float32)
                        s = jnp.max(jnp.abs(af))
                        return (_fake_qdq(af, s, _b).astype(a.dtype), s)
                    qop = Operator('fake_quantize_dequantize_abs_max',
                                   qfn, [iname], [qname, sname],
                                   {'bit_length': bits},
                                   op_role=op.op_role)
                    qop.multi_out = True
                    out_ops.append(qop)
                    quantized[iname] = qname
                    new_ins.append(qname)
                    n += 1
                op.input_names = new_ins
            out_ops.append(op)
        block.ops = out_ops
        program._quant_rewritten = True
        return n


# ---------------------------------------------------------------------------
# int8 export / load
# ---------------------------------------------------------------------------

def export_quantized_layer(path_prefix, layer, example_inputs,
                           weight_bits=8):
    """Int8 export through the static/inference.py container: weights of
    quantized sublayers stored as int8 + per-channel fp32 scales; the
    predictor dequantizes at load (weight-only int8 — the
    post_training_quantization artifact shape)."""
    import io as _io
    import json
    import zipfile
    from ..static.inference import export_layer
    export_layer(path_prefix, layer, example_inputs)

    # rewrite the .pdexec arrays: quantize eligible params
    with zipfile.ZipFile(path_prefix + '.pdexec') as z:
        meta = json.loads(z.read('meta.json'))
        loaded = np.load(_io.BytesIO(z.read('arrays.npz')),
                         allow_pickle=False)
        arrays = {k: loaded[k] for k in loaded.files}
    q_arrays, q_meta = {}, {}
    for k, a in arrays.items():
        if k.startswith('p:') and a.ndim >= 2 and \
                a.dtype in (np.float32, np.float16):
            axis = a.ndim - 1        # out-channel axis (Linear [in,out],
            q, s = quantize_to_int8(a, quant_axis=axis,  # conv [O,I,kh,kw]
                                    bits=weight_bits)
            if a.ndim == 4:
                q, s = quantize_to_int8(a, quant_axis=0, bits=weight_bits)
                axis = 0
            q_arrays[k] = q
            q_arrays[k + '.scale'] = s
            q_meta[k] = {'quant_axis': axis, 'bits': weight_bits,
                         'dtype': str(a.dtype)}
        else:
            q_arrays[k] = a
    meta['quantized'] = q_meta
    npz = _io.BytesIO()
    np.savez(npz, **q_arrays)
    with zipfile.ZipFile(path_prefix + '.pdexec', 'w') as z:
        z.writestr('meta.json', json.dumps(meta))
        z.writestr('arrays.npz', npz.getvalue())
    return path_prefix


def load_quantized_predictor(path_prefix):
    """Load an int8 artifact: dequantize weights, return a Predictor."""
    import io as _io
    import json
    import zipfile
    from ..static.inference import Predictor
    with zipfile.ZipFile(path_prefix + '.pdexec') as z:
        meta = json.loads(z.read('meta.json'))
        loaded = np.load(_io.BytesIO(z.read('arrays.npz')),
                         allow_pickle=False)
        arrays = {k: loaded[k] for k in loaded.files}
    q_meta = meta.get('quantized', {})
    deq = {}
    for k, a in arrays.items():
        if k.endswith('.scale'):
            continue
        if k in q_meta:
            info = q_meta[k]
            deq[k] = dequantize_from_int8(
                a, arrays[k + '.scale'], quant_axis=info['quant_axis'],
                bits=info['bits']).astype(info['dtype'])
        else:
            deq[k] = a
    pred = Predictor.__new__(Predictor)
    from jax import export as jax_export
    with open(path_prefix + '.stablehlo', 'rb') as f:
        pred._exported = jax_export.deserialize(f.read())
    pred._params = {k[2:]: jnp.asarray(v) for k, v in deq.items()
                    if k.startswith('p:')}
    pred._buffers = {k[2:]: jnp.asarray(v) for k, v in deq.items()
                     if k.startswith('b:')}
    pred.input_specs = [(tuple(sh), dt) for sh, dt in meta['input_specs']]
    return pred
