// Blocking bounded MPMC channel.
//
// Reference parity: paddle/fluid/framework/channel.h — the queue backing the
// data-feed pipeline (file readers -> batch assembler -> device feed).
#pragma once

#include <condition_variable>
#include <deque>
#include <mutex>

namespace ptpu {

template <typename T>
class Channel {
 public:
  explicit Channel(size_t capacity = 0) : capacity_(capacity) {}

  // Returns false if the channel is closed.
  bool Put(T&& item) {
    std::unique_lock<std::mutex> lk(mu_);
    send_cv_.wait(lk, [&] {
      return closed_ || capacity_ == 0 || buf_.size() < capacity_;
    });
    if (closed_) return false;
    buf_.push_back(std::move(item));
    recv_cv_.notify_one();
    return true;
  }

  // Returns false when closed AND drained.
  bool Get(T* out) {
    std::unique_lock<std::mutex> lk(mu_);
    recv_cv_.wait(lk, [&] { return closed_ || !buf_.empty(); });
    if (buf_.empty()) return false;
    *out = std::move(buf_.front());
    buf_.pop_front();
    send_cv_.notify_one();
    return true;
  }

  void Close() {
    std::lock_guard<std::mutex> lk(mu_);
    closed_ = true;
    send_cv_.notify_all();
    recv_cv_.notify_all();
  }

  size_t Size() {
    std::lock_guard<std::mutex> lk(mu_);
    return buf_.size();
  }

  bool Closed() {
    std::lock_guard<std::mutex> lk(mu_);
    return closed_;
  }

 private:
  size_t capacity_;
  bool closed_ = false;
  std::deque<T> buf_;
  std::mutex mu_;
  std::condition_variable send_cv_, recv_cv_;
};

}  // namespace ptpu
