// Host data-feed pipeline: files -> reader threads -> channel -> batches.
//
// Reference parity: paddle/fluid/framework/data_feed.cc (DataFeed:208,
// InMemoryDataFeed:395, MultiSlotDataFeed:757) + data_set.cc shuffle — the
// C++ ingestion stack under fleet's InMemoryDataset. TPU-native shape: the
// assembled batch is a dense contiguous float/int64 buffer ready for one
// host->device transfer (PJRT handles the copy; no LoD — fixed slot widths).
//
// Record text format (MultiSlot-style, one instance per line):
//   slot0_v0 slot0_v1 ... | slot1_v0 ... | ...
// with per-slot fixed widths declared at init; '|' separates slots.
#include <algorithm>
#include <atomic>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <memory>
#include <random>
#include <sstream>
#include <string>
#include <thread>
#include <vector>

#include "channel.h"

namespace ptpu {

struct SlotDesc {
  int width;      // values per instance
  bool is_float;  // else int64
};

struct Instance {
  std::vector<float> fvals;
  std::vector<int64_t> ivals;
};

class DataFeed {
 public:
  DataFeed(std::vector<SlotDesc> slots, int batch_size, int num_threads,
           size_t channel_capacity)
      : slots_(std::move(slots)),
        batch_size_(batch_size),
        num_threads_(num_threads),
        channel_(channel_capacity ? channel_capacity : 4096) {
    fwidth_ = iwidth_ = 0;
    for (auto& s : slots_) {
      (s.is_float ? fwidth_ : iwidth_) += s.width;
    }
  }

  ~DataFeed() { Stop(); }

  void SetFiles(std::vector<std::string> files) { files_ = std::move(files); }

  void Start() {
    done_readers_ = 0;
    file_cursor_ = 0;
    for (int i = 0; i < num_threads_; ++i) {
      readers_.emplace_back([this] { ReadLoop(); });
    }
  }

  void Stop() {
    channel_.Close();
    for (auto& t : readers_) {
      if (t.joinable()) t.join();
    }
    readers_.clear();
  }

  // Fill caller buffers with one batch; returns rows filled (0 = exhausted).
  int NextBatch(float* fbuf, int64_t* ibuf) {
    int n = 0;
    Instance inst;
    while (n < batch_size_ && channel_.Get(&inst)) {
      if (fbuf && fwidth_)
        std::memcpy(fbuf + (size_t)n * fwidth_, inst.fvals.data(),
                    sizeof(float) * fwidth_);
      if (ibuf && iwidth_)
        std::memcpy(ibuf + (size_t)n * iwidth_, inst.ivals.data(),
                    sizeof(int64_t) * iwidth_);
      ++n;
    }
    return n;
  }

  // In-memory global shuffle (reference: data_set.cc shuffle semantics,
  // single-host scope here; cross-host shuffle rides the PS/launcher tier).
  void LoadIntoMemoryAndShuffle(uint64_t seed) {
    std::vector<Instance> all;
    Instance inst;
    for (auto& f : files_) {
      std::ifstream in(f);
      std::string line;
      while (std::getline(in, line)) {
        if (Parse(line, &inst)) all.push_back(std::move(inst));
      }
    }
    std::mt19937_64 rng(seed);
    std::shuffle(all.begin(), all.end(), rng);
    memory_ = std::move(all);
    mem_cursor_ = 0;
  }

  int NextBatchFromMemory(float* fbuf, int64_t* ibuf) {
    int n = 0;
    while (n < batch_size_ && mem_cursor_ < memory_.size()) {
      const Instance& inst = memory_[mem_cursor_++];
      if (fbuf && fwidth_)
        std::memcpy(fbuf + (size_t)n * fwidth_, inst.fvals.data(),
                    sizeof(float) * fwidth_);
      if (ibuf && iwidth_)
        std::memcpy(ibuf + (size_t)n * iwidth_, inst.ivals.data(),
                    sizeof(int64_t) * iwidth_);
      ++n;
    }
    return n;
  }

  void RewindMemory(bool reshuffle, uint64_t seed) {
    if (reshuffle) {
      std::mt19937_64 rng(seed);
      std::shuffle(memory_.begin(), memory_.end(), rng);
    }
    mem_cursor_ = 0;
  }

  size_t MemorySize() const { return memory_.size(); }
  int FloatWidth() const { return fwidth_; }
  int IntWidth() const { return iwidth_; }

 private:
  void ReadLoop() {
    while (true) {
      size_t idx = file_cursor_.fetch_add(1);
      if (idx >= files_.size()) break;
      std::ifstream in(files_[idx]);
      std::string line;
      Instance inst;
      while (std::getline(in, line)) {
        if (Parse(line, &inst)) {
          if (!channel_.Put(std::move(inst))) return;
          inst = Instance();
        }
      }
    }
    if (++done_readers_ == num_threads_) channel_.Close();
  }

  bool Parse(const std::string& line, Instance* out) {
    out->fvals.clear();
    out->ivals.clear();
    out->fvals.reserve(fwidth_);
    out->ivals.reserve(iwidth_);
    const char* p = line.c_str();
    for (auto& slot : slots_) {
      for (int i = 0; i < slot.width; ++i) {
        while (*p == ' ' || *p == '|') ++p;
        if (*p == '\0') return false;
        char* end = nullptr;
        if (slot.is_float) {
          out->fvals.push_back(std::strtof(p, &end));
        } else {
          out->ivals.push_back(std::strtoll(p, &end, 10));
        }
        if (end == p) return false;
        p = end;
      }
    }
    return out->fvals.size() == (size_t)fwidth_ &&
           out->ivals.size() == (size_t)iwidth_;
  }

  std::vector<SlotDesc> slots_;
  int batch_size_;
  int num_threads_;
  int fwidth_, iwidth_;
  Channel<Instance> channel_;
  std::vector<std::string> files_;
  std::atomic<size_t> file_cursor_{0};
  std::atomic<int> done_readers_{0};
  std::vector<std::thread> readers_;
  std::vector<Instance> memory_;
  size_t mem_cursor_ = 0;
};

}  // namespace ptpu

// ---- C API (ctypes) --------------------------------------------------------
extern "C" {

void* ptpu_datafeed_create(const int* widths, const int* is_float,
                           int num_slots, int batch_size, int num_threads,
                           int channel_capacity) {
  std::vector<ptpu::SlotDesc> slots;
  for (int i = 0; i < num_slots; ++i)
    slots.push_back({widths[i], is_float[i] != 0});
  return new ptpu::DataFeed(std::move(slots), batch_size, num_threads,
                            channel_capacity);
}

void ptpu_datafeed_set_files(void* h, const char** files, int n) {
  std::vector<std::string> fs(files, files + n);
  static_cast<ptpu::DataFeed*>(h)->SetFiles(std::move(fs));
}

void ptpu_datafeed_start(void* h) { static_cast<ptpu::DataFeed*>(h)->Start(); }

int ptpu_datafeed_next(void* h, float* fbuf, int64_t* ibuf) {
  return static_cast<ptpu::DataFeed*>(h)->NextBatch(fbuf, ibuf);
}

void ptpu_datafeed_load_shuffle(void* h, uint64_t seed) {
  static_cast<ptpu::DataFeed*>(h)->LoadIntoMemoryAndShuffle(seed);
}

int ptpu_datafeed_next_mem(void* h, float* fbuf, int64_t* ibuf) {
  return static_cast<ptpu::DataFeed*>(h)->NextBatchFromMemory(fbuf, ibuf);
}

void ptpu_datafeed_rewind(void* h, int reshuffle, uint64_t seed) {
  static_cast<ptpu::DataFeed*>(h)->RewindMemory(reshuffle != 0, seed);
}

int64_t ptpu_datafeed_memory_size(void* h) {
  return (int64_t)static_cast<ptpu::DataFeed*>(h)->MemorySize();
}

void ptpu_datafeed_destroy(void* h) {
  delete static_cast<ptpu::DataFeed*>(h);
}
}
