// Host profiler: RAII-style event records + chrome-trace export.
//
// Reference parity: platform/profiler.cc RecordEvent + device_tracer.cc's
// chrome-trace output (N4). Device-side timing comes from XLA/PJRT's own
// profiler (jax.profiler — xplane); this records the HOST side (op dispatch,
// data feed, checkpoint IO) with thread ids, matching the reference's
// host-event tables. Export is the chrome://tracing JSON the reference's
// tooling consumes.
#include <atomic>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <fstream>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace ptpu {

struct Event {
  std::string name;
  uint64_t start_us;
  uint64_t end_us;
  uint64_t tid;
};

class Profiler {
 public:
  static Profiler& Get() {
    static Profiler p;
    return p;
  }

  void Enable(bool on) { enabled_ = on; }
  bool Enabled() const { return enabled_; }

  uint64_t NowUs() {
    return std::chrono::duration_cast<std::chrono::microseconds>(
               std::chrono::steady_clock::now().time_since_epoch())
        .count();
  }

  void Record(const char* name, uint64_t start_us, uint64_t end_us) {
    if (!enabled_) return;
    std::lock_guard<std::mutex> lk(mu_);
    // bounded ring (parity with the Python fallback recorder): a
    // forgotten-enabled profiler must not grow without limit. The
    // oldest half is dropped in one memmove-ish splice so steady-state
    // recording stays O(1) amortized.
    if (events_.size() >= capacity_) {
      size_t drop = capacity_ / 2;
      dropped_ += drop;
      events_.erase(events_.begin(), events_.begin() + drop);
    }
    events_.push_back({name, start_us, end_us,
                       std::hash<std::thread::id>()(
                           std::this_thread::get_id()) %
                           100000});
  }

  void Clear() {
    std::lock_guard<std::mutex> lk(mu_);
    events_.clear();
    dropped_ = 0;
  }

  uint64_t Dropped() {
    std::lock_guard<std::mutex> lk(mu_);
    return dropped_;
  }

  void SetCapacity(uint64_t cap) {
    std::lock_guard<std::mutex> lk(mu_);
    capacity_ = cap < 2 ? 2 : cap;
  }

  size_t Count() {
    std::lock_guard<std::mutex> lk(mu_);
    return events_.size();
  }

  // Aggregated table: name -> (calls, total_us, min_us, max_us).
  std::string Summary() {
    std::lock_guard<std::mutex> lk(mu_);
    struct Agg {
      uint64_t calls = 0, total = 0, mn = UINT64_MAX, mx = 0;
    };
    std::map<std::string, Agg> agg;
    for (auto& e : events_) {
      auto& a = agg[e.name];
      uint64_t d = e.end_us - e.start_us;
      a.calls++;
      a.total += d;
      if (d < a.mn) a.mn = d;
      if (d > a.mx) a.mx = d;
    }
    std::string out =
        "name\tcalls\ttotal_ms\tavg_us\tmin_us\tmax_us\n";
    char buf[512];
    for (auto& kv : agg) {
      snprintf(buf, sizeof(buf), "%s\t%llu\t%.3f\t%.1f\t%llu\t%llu\n",
               kv.first.c_str(), (unsigned long long)kv.second.calls,
               kv.second.total / 1000.0,
               (double)kv.second.total / kv.second.calls,
               (unsigned long long)kv.second.mn,
               (unsigned long long)kv.second.mx);
      out += buf;
    }
    return out;
  }

  bool ExportChromeTrace(const std::string& path) {
    std::lock_guard<std::mutex> lk(mu_);
    std::ofstream out(path);
    if (!out) return false;
    out << "{\"traceEvents\":[";
    bool first = true;
    for (auto& e : events_) {
      if (!first) out << ",";
      first = false;
      out << "{\"name\":\"" << e.name << "\",\"ph\":\"X\",\"pid\":0,"
          << "\"tid\":" << e.tid << ",\"ts\":" << e.start_us
          << ",\"dur\":" << (e.end_us - e.start_us) << "}";
    }
    out << "]}";
    return out.good();
  }

 private:
  std::atomic<bool> enabled_{false};
  std::mutex mu_;
  std::vector<Event> events_;
  size_t capacity_ = 1 << 20;
  uint64_t dropped_ = 0;
};

}  // namespace ptpu

extern "C" {

void ptpu_profiler_enable(int on) { ptpu::Profiler::Get().Enable(on != 0); }

uint64_t ptpu_profiler_now() { return ptpu::Profiler::Get().NowUs(); }

void ptpu_profiler_record(const char* name, uint64_t start_us,
                          uint64_t end_us) {
  ptpu::Profiler::Get().Record(name, start_us, end_us);
}

void ptpu_profiler_clear() { ptpu::Profiler::Get().Clear(); }

int64_t ptpu_profiler_count() {
  return (int64_t)ptpu::Profiler::Get().Count();
}

// Writes summary into buf (truncated at cap); returns needed length.
int ptpu_profiler_summary(char* buf, int cap) {
  std::string s = ptpu::Profiler::Get().Summary();
  int n = (int)s.size() < cap - 1 ? (int)s.size() : cap - 1;
  std::memcpy(buf, s.data(), n);
  buf[n] = '\0';
  return (int)s.size();
}

int ptpu_profiler_export(const char* path) {
  return ptpu::Profiler::Get().ExportChromeTrace(path) ? 1 : 0;
}

uint64_t ptpu_profiler_dropped() {
  return ptpu::Profiler::Get().Dropped();
}

void ptpu_profiler_set_capacity(uint64_t cap) {
  ptpu::Profiler::Get().SetCapacity(cap);
}
}
