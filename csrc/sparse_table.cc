// Sharded concurrent sparse embedding table with fused optimizer update.
//
// Reference parity: paddle/fluid/distributed tables — CommonSparseTable
// (service/…, N30) and the heterPS GPU hashtable (framework/fleet/heter_ps/
// hashtable.h, optimizer.cuh.h, N31): feature-id -> embedding row with the
// optimizer state stored inline, pull (lookup w/ on-miss init) and push
// (gradient update) APIs. TPU-native shape: this table lives on HOST CPU
// memory (trillion-parameter scale — BASELINE config 5); the TPU holds only
// the dense towers. Pull gathers rows into a contiguous buffer for one H2D
// transfer; push applies adagrad/sgd on the host shards in parallel.
//
// Layout per row: [embedding dim floats][adagrad G2 accumulator (dim)] —
// SGD mode stores only the embedding.
#include <algorithm>
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ptpu {

class SparseTable {
 public:
  enum Opt { SGD = 0, ADAGRAD = 1, ADAM = 2 };

  SparseTable(int dim, int num_shards, int opt, float init_range,
              uint64_t seed, float beta1 = 0.9f, float beta2 = 0.999f,
              float eps = 1e-8f)
      : dim_(dim),
        num_shards_(num_shards),
        opt_((Opt)opt),
        init_range_(init_range),
        seed_(seed),
        beta1_(beta1),
        beta2_(beta2),
        eps_(eps),
        shards_(num_shards),
        locks_(num_shards) {}

  virtual ~SparseTable() = default;

  // Row layouts: SGD [w]; ADAGRAD [w, g2]; ADAM [w, m, v, t] — the
  // optimizer state inline with the embedding (reference: sparse
  // accessor "embedx + sgd/adam fields", ctr_accessor / sparse_sgd_rule)
  int RowWidth() const {
    if (opt_ == ADAM) return dim_ * 3 + 1;
    return opt_ == ADAGRAD ? dim_ * 2 : dim_;
  }

  // Gather rows for `n` ids into out[n, dim]; missing ids are initialized
  // (uniform[-init_range, init_range]) — reference accessor "create on
  // miss" semantics.
  void Pull(const int64_t* ids, int n, float* out) {
    ParallelOver(n, [&](int i) {
      int64_t id = ids[i];
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      auto& row = GetOrInit(s, id);
      std::memcpy(out + (size_t)i * dim_, row.data(), sizeof(float) * dim_);
    });
  }

  // Apply gradients: grads[n, dim] for ids[n]; duplicate ids accumulate
  // sequentially per shard (deterministic within a shard).
  void Push(const int64_t* ids, int n, const float* grads, float lr) {
    ParallelOver(n, [&](int i) {
      int64_t id = ids[i];
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      auto& row = GetOrInit(s, id);
      const float* g = grads + (size_t)i * dim_;
      if (opt_ == ADAGRAD) {
        float* w = row.data();
        float* g2 = row.data() + dim_;
        for (int d = 0; d < dim_; ++d) {
          g2[d] += g[d] * g[d];
          w[d] -= lr * g[d] / (std::sqrt(g2[d]) + eps_);
        }
      } else if (opt_ == ADAM) {
        // bias-corrected adam per row; hypers are per-table accessor
        // config (reference: ps.proto TableParameter / sparse_sgd_rule),
        // not compile-time constants
        float* w = row.data();
        float* m = row.data() + dim_;
        float* v = row.data() + 2 * dim_;
        float& t = row[3 * dim_];
        t += 1.f;
        float bc1 = 1.f - std::pow(beta1_, t);
        float bc2 = 1.f - std::pow(beta2_, t);
        for (int d = 0; d < dim_; ++d) {
          m[d] = beta1_ * m[d] + (1.f - beta1_) * g[d];
          v[d] = beta2_ * v[d] + (1.f - beta2_) * g[d] * g[d];
          w[d] -= lr * (m[d] / bc1) / (std::sqrt(v[d] / bc2) + eps_);
        }
      } else {
        float* w = row.data();
        for (int d = 0; d < dim_; ++d) w[d] -= lr * g[d];
      }
    });
  }

  // Assign embedding values (optimizer state untouched) — used by the
  // geo communicator to refresh the worker-local mirror from the server
  // (reference: SparseGeoTable pull-and-overwrite semantics).
  void Set(const int64_t* ids, int n, const float* rows) {
    ParallelOver(n, [&](int i) {
      int64_t id = ids[i];
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      auto& row = GetOrInit(s, id);
      std::memcpy(row.data(), rows + (size_t)i * dim_,
                  sizeof(float) * dim_);
    });
  }

  int64_t Size() const {
    int64_t total = 0;
    for (auto& s : shards_) total += (int64_t)s.size();
    return total;
  }

  // Shrink: drop rows whose L2 norm is below threshold (reference:
  // SSDSparseTable/CommonSparseTable shrink for stale features).
  int64_t Shrink(float threshold) {
    int64_t dropped = 0;
    for (size_t s = 0; s < shards_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      for (auto it = shards_[s].begin(); it != shards_[s].end();) {
        float norm = 0;
        for (int d = 0; d < dim_; ++d)
          norm += it->second[d] * it->second[d];
        if (std::sqrt(norm) < threshold) {
          it = shards_[s].erase(it);
          ++dropped;
        } else {
          ++it;
        }
      }
    }
    return dropped;
  }

  bool Save(const std::string& path) {
    std::ofstream out(path, std::ios::binary);
    if (!out) return false;
    int64_t n = Size();
    int rw = RowWidth();
    out.write((char*)&dim_, sizeof(dim_));
    out.write((char*)&rw, sizeof(rw));
    out.write((char*)&n, sizeof(n));
    for (size_t s = 0; s < shards_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      for (auto& kv : shards_[s]) {
        out.write((char*)&kv.first, sizeof(int64_t));
        out.write((char*)kv.second.data(), sizeof(float) * rw);
      }
    }
    return out.good();
  }

  bool Load(const std::string& path) {
    std::ifstream in(path, std::ios::binary);
    if (!in) return false;
    int dim, rw;
    int64_t n;
    in.read((char*)&dim, sizeof(dim));
    in.read((char*)&rw, sizeof(rw));
    in.read((char*)&n, sizeof(n));
    if (dim != dim_ || rw != RowWidth()) return false;
    for (int64_t i = 0; i < n; ++i) {
      int64_t id;
      in.read((char*)&id, sizeof(id));
      std::vector<float> row(rw);
      in.read((char*)row.data(), sizeof(float) * rw);
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      shards_[s][id] = std::move(row);
    }
    return in.good();
  }

 protected:
  size_t Shard(int64_t id) const {
    return ((uint64_t)id * 0x9E3779B97F4A7C15ull >> 32) % num_shards_;
  }

  virtual std::vector<float>& GetOrInit(size_t s, int64_t id) {
    auto it = shards_[s].find(id);
    if (it != shards_[s].end()) return it->second;
    return shards_[s].emplace(id, NewRow(id)).first->second;
  }

  template <typename F>
  void ParallelOver(int n, F f) {
    int nthreads = (int)std::min<size_t>(
        std::max(1u, std::thread::hardware_concurrency()), 8);
    if (n < 1024 || nthreads <= 1) {
      for (int i = 0; i < n; ++i) f(i);
      return;
    }
    std::vector<std::thread> ts;
    int chunk = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
      int lo = t * chunk, hi = std::min(n, lo + chunk);
      if (lo >= hi) break;
      ts.emplace_back([&, lo, hi] {
        for (int i = lo; i < hi; ++i) f(i);
      });
    }
    for (auto& t : ts) t.join();
  }

  std::vector<float> NewRow(int64_t id) {
    std::vector<float> row(RowWidth(), 0.f);
    std::mt19937_64 rng(seed_ ^ (uint64_t)id);
    std::uniform_real_distribution<float> dist(-init_range_, init_range_);
    for (int d = 0; d < dim_; ++d) row[d] = dist(rng);
    return row;
  }

  int dim_;
  int num_shards_;
  Opt opt_;
  float init_range_;
  uint64_t seed_;
  float beta1_, beta2_, eps_;
  std::vector<std::unordered_map<int64_t, std::vector<float>>> shards_;
  std::vector<std::mutex> locks_;
};

// Disk-spilling sparse table (reference parity:
// distributed/table/ssd_sparse_table.h — hot rows in memory, cold rows in
// a disk store; here an append-only per-shard log with an in-memory
// id→offset index instead of rocksdb, which this image doesn't ship).
// Eviction: approximate LRU by per-row access epoch — when a shard's hot
// map exceeds its budget the oldest half spills to its log.
class SsdSparseTable : public SparseTable {
 public:
  SsdSparseTable(int dim, int num_shards, int opt, float init_range,
                 uint64_t seed, float beta1, float beta2, float eps,
                 int64_t mem_budget_rows, const std::string& dir)
      : SparseTable(dim, num_shards, opt, init_range, seed, beta1, beta2,
                    eps),
        dir_(dir),
        budget_per_shard_(
            std::max<int64_t>(2, mem_budget_rows / num_shards)),
        epochs_(num_shards),
        access_(num_shards),
        index_(num_shards),
        logs_(num_shards) {
    for (int s = 0; s < num_shards; ++s) {
      logs_[s].open(LogPath(s),
                    std::ios::binary | std::ios::app | std::ios::out);
    }
  }

  ~SsdSparseTable() override {
    for (auto& f : logs_) f.close();
  }

  int64_t MemRows() {
    int64_t n = 0;
    for (size_t s = 0; s < shards_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      n += (int64_t)shards_[s].size();
    }
    return n;
  }

  // total DISTINCT rows (hot + cold)
  int64_t DiskRows() {
    int64_t n = 0;
    for (size_t s = 0; s < index_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      n += (int64_t)shards_[s].size();
      for (auto& kv : index_[s])
        if (!shards_[s].count(kv.first)) ++n;
    }
    return n;
  }

  // Full-table snapshot incl. cold rows (base Save would silently drop
  // everything spilled). Format-compatible with SparseTable::Save.
  bool SaveAll(const std::string& path) {
    Flush();
    std::ofstream out(path, std::ios::binary);
    if (!out) return false;
    int rw = RowWidth();
    int64_t n = DiskRows();
    out.write((char*)&dim_, sizeof(dim_));
    out.write((char*)&rw, sizeof(rw));
    out.write((char*)&n, sizeof(n));
    for (size_t s = 0; s < index_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      for (auto& kv : index_[s]) {
        std::vector<float> row = shards_[s].count(kv.first)
            ? shards_[s][kv.first] : ReadRow(s, kv.second, kv.first);
        out.write((char*)&kv.first, sizeof(int64_t));
        out.write((char*)row.data(), sizeof(float) * rw);
      }
    }
    return out.good();
  }

  // Restore a snapshot straight into the logs (never materializes the
  // table in RAM — the point of the spill tier).
  bool LoadAll(const std::string& path) {
    std::ifstream in(path, std::ios::binary);
    if (!in) return false;
    int dim, rw;
    int64_t n;
    in.read((char*)&dim, sizeof(dim));
    in.read((char*)&rw, sizeof(rw));
    in.read((char*)&n, sizeof(n));
    if (dim != dim_ || rw != RowWidth()) return false;
    std::vector<float> row(rw);
    for (int64_t i = 0; i < n; ++i) {
      int64_t id;
      in.read((char*)&id, sizeof(id));
      in.read((char*)row.data(), sizeof(float) * rw);
      if (!in) return false;
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      SpillRow(s, id, row);
    }
    for (auto& f : logs_) f.flush();
    return true;
  }

  // Spill every hot row to the log (checkpoint/shutdown).
  void Flush() {
    for (size_t s = 0; s < shards_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      for (auto& kv : shards_[s]) SpillRow(s, kv.first, kv.second);
      logs_[s].flush();
    }
  }

  // Rebuild the disk index by scanning the logs (restart recovery —
  // last record per id wins). A crash-truncated trailing record is
  // dropped, not indexed. Hot maps start empty.
  bool Recover() {
    int rw = RowWidth();
    int64_t rec = (int64_t)(sizeof(int64_t) + sizeof(float) * rw);
    for (size_t s = 0; s < index_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      index_[s].clear();
      shards_[s].clear();
      access_[s].clear();
      std::ifstream in(LogPath(s), std::ios::binary | std::ios::ate);
      if (!in) continue;
      int64_t file_size = (int64_t)in.tellg();
      in.seekg(0);
      int64_t off = 0;
      int64_t id;
      while (off + rec <= file_size &&
             in.read((char*)&id, sizeof(id))) {
        index_[s][id] = off;
        off += rec;
        in.seekg(off);
      }
    }
    return true;
  }

 protected:
  std::vector<float>& GetOrInit(size_t s, int64_t id) override {
    ++epochs_[s];
    auto it = shards_[s].find(id);
    if (it == shards_[s].end()) {
      std::vector<float> row;
      auto dit = index_[s].find(id);
      if (dit != index_[s].end()) {
        row = ReadRow(s, dit->second, id);
      } else {
        row = NewRow(id);
      }
      it = shards_[s].emplace(id, std::move(row)).first;
      access_[s][id] = epochs_[s];
      MaybeEvict(s);
      it = shards_[s].find(id);   // eviction may rehash
    } else {
      access_[s][id] = epochs_[s];
    }
    return it->second;
  }

 private:
  std::string LogPath(int s) const {
    return dir_ + "/shard_" + std::to_string(s) + ".log";
  }

  void SpillRow(size_t s, int64_t id, const std::vector<float>& row) {
    logs_[s].seekp(0, std::ios::end);
    int64_t off = (int64_t)logs_[s].tellp();
    logs_[s].write((const char*)&id, sizeof(id));
    logs_[s].write((const char*)row.data(),
                   sizeof(float) * row.size());
    index_[s][id] = off;
  }

  std::vector<float> ReadRow(size_t s, int64_t off, int64_t id) {
    std::vector<float> row(RowWidth());
    std::ifstream in(LogPath(s), std::ios::binary);
    in.seekg(off + (int64_t)sizeof(int64_t));
    in.read((char*)row.data(), sizeof(float) * row.size());
    if ((size_t)in.gcount() != sizeof(float) * row.size()) {
      // unreadable record (should have been dropped by Recover's
      // truncation guard) — fall back to a fresh init, never garbage
      return NewRow(id);
    }
    return row;
  }

  void MaybeEvict(size_t s) {
    if ((int64_t)shards_[s].size() <= budget_per_shard_) return;
    // spill the oldest half by access epoch
    std::vector<std::pair<uint64_t, int64_t>> order;
    order.reserve(shards_[s].size());
    for (auto& kv : shards_[s])
      order.emplace_back(access_[s][kv.first], kv.first);
    std::sort(order.begin(), order.end());
    size_t n_evict = order.size() / 2;
    logs_[s].seekp(0, std::ios::end);
    for (size_t i = 0; i < n_evict; ++i) {
      int64_t id = order[i].second;
      SpillRow(s, id, shards_[s][id]);
      shards_[s].erase(id);
      access_[s].erase(id);
    }
    logs_[s].flush();
  }

  std::string dir_;
  int64_t budget_per_shard_;
  std::vector<uint64_t> epochs_;
  std::vector<std::unordered_map<int64_t, uint64_t>> access_;
  std::vector<std::unordered_map<int64_t, int64_t>> index_;
  mutable std::vector<std::fstream> logs_;
};

// Server-side dense parameter table (reference parity:
// distributed/table/common_dense_table.h — a fixed-size parameter block
// workers pull whole and push gradients into, with the optimizer applied
// server-side).
class DenseTable {
 public:
  DenseTable(int64_t size, int opt)
      : size_(size), opt_((SparseTable::Opt)opt), w_(size, 0.f), t_(0.f) {
    if (opt_ != SparseTable::SGD) g2_.assign(size, 0.f);
    if (opt_ == SparseTable::ADAM) v_.assign(size, 0.f);
  }

  void Set(const float* vals) {
    std::lock_guard<std::mutex> lk(mu_);
    std::memcpy(w_.data(), vals, sizeof(float) * size_);
  }

  void Pull(float* out) {
    std::lock_guard<std::mutex> lk(mu_);
    std::memcpy(out, w_.data(), sizeof(float) * size_);
  }

  void Push(const float* g, float lr) {
    std::lock_guard<std::mutex> lk(mu_);
    if (opt_ == SparseTable::ADAGRAD) {
      for (int64_t d = 0; d < size_; ++d) {
        g2_[d] += g[d] * g[d];
        w_[d] -= lr * g[d] / (std::sqrt(g2_[d]) + 1e-6f);
      }
    } else if (opt_ == SparseTable::ADAM) {
      t_ += 1.f;
      float bc1 = 1.f - std::pow(0.9f, t_);
      float bc2 = 1.f - std::pow(0.999f, t_);
      for (int64_t d = 0; d < size_; ++d) {
        g2_[d] = 0.9f * g2_[d] + 0.1f * g[d];  // m in g2_
        v_[d] = 0.999f * v_[d] + 0.001f * g[d] * g[d];
        w_[d] -= lr * (g2_[d] / bc1) / (std::sqrt(v_[d] / bc2) + 1e-8f);
      }
    } else {
      for (int64_t d = 0; d < size_; ++d) w_[d] -= lr * g[d];
    }
  }

  int64_t Size() const { return size_; }

  bool Save(const std::string& path) {
    std::lock_guard<std::mutex> lk(mu_);
    std::ofstream out(path, std::ios::binary);
    if (!out) return false;
    int opt = (int)opt_;
    out.write((char*)&size_, sizeof(size_));
    out.write((char*)&opt, sizeof(opt));
    out.write((char*)&t_, sizeof(t_));
    out.write((char*)w_.data(), sizeof(float) * size_);
    if (!g2_.empty()) out.write((char*)g2_.data(), sizeof(float) * size_);
    if (!v_.empty()) out.write((char*)v_.data(), sizeof(float) * size_);
    return out.good();
  }

  bool Load(const std::string& path) {
    std::lock_guard<std::mutex> lk(mu_);
    std::ifstream in(path, std::ios::binary);
    if (!in) return false;
    int64_t size;
    int opt;
    in.read((char*)&size, sizeof(size));
    in.read((char*)&opt, sizeof(opt));
    // optimizer layout mismatch would silently misread the accumulator
    // blocks (SparseTable::Load's rw check plays the same role)
    if (size != size_ || opt != (int)opt_) return false;
    in.read((char*)&t_, sizeof(t_));
    in.read((char*)w_.data(), sizeof(float) * size_);
    if (!g2_.empty()) in.read((char*)g2_.data(), sizeof(float) * size_);
    if (!v_.empty()) in.read((char*)v_.data(), sizeof(float) * size_);
    return in.good();
  }

 private:
  int64_t size_;
  SparseTable::Opt opt_;
  std::vector<float> w_, g2_, v_;
  float t_;
  std::mutex mu_;
};

}  // namespace ptpu

extern "C" {

void* ptpu_dense_create(int64_t size, int opt) {
  return new ptpu::DenseTable(size, opt);
}

void ptpu_dense_set(void* h, const float* vals) {
  static_cast<ptpu::DenseTable*>(h)->Set(vals);
}

void ptpu_dense_pull(void* h, float* out) {
  static_cast<ptpu::DenseTable*>(h)->Pull(out);
}

void ptpu_dense_push(void* h, const float* g, float lr) {
  static_cast<ptpu::DenseTable*>(h)->Push(g, lr);
}

int64_t ptpu_dense_size(void* h) {
  return static_cast<ptpu::DenseTable*>(h)->Size();
}

int ptpu_dense_save(void* h, const char* path) {
  return static_cast<ptpu::DenseTable*>(h)->Save(path) ? 1 : 0;
}

int ptpu_dense_load(void* h, const char* path) {
  return static_cast<ptpu::DenseTable*>(h)->Load(path) ? 1 : 0;
}

void ptpu_dense_destroy(void* h) {
  delete static_cast<ptpu::DenseTable*>(h);
}

void* ptpu_table_create(int dim, int num_shards, int opt, float init_range,
                        uint64_t seed) {
  return new ptpu::SparseTable(dim, num_shards, opt, init_range, seed);
}

// v2: per-table accessor hypers (ps.proto TableParameter analogue)
void* ptpu_table_create2(int dim, int num_shards, int opt, float init_range,
                         uint64_t seed, float beta1, float beta2,
                         float eps) {
  return new ptpu::SparseTable(dim, num_shards, opt, init_range, seed,
                               beta1, beta2, eps);
}

void* ptpu_ssd_table_create(int dim, int num_shards, int opt,
                            float init_range, uint64_t seed, float beta1,
                            float beta2, float eps, int64_t mem_budget_rows,
                            const char* dir) {
  return new ptpu::SsdSparseTable(dim, num_shards, opt, init_range, seed,
                                  beta1, beta2, eps, mem_budget_rows, dir);
}

int64_t ptpu_ssd_mem_rows(void* h) {
  return static_cast<ptpu::SsdSparseTable*>(h)->MemRows();
}

int64_t ptpu_ssd_total_rows(void* h) {
  return static_cast<ptpu::SsdSparseTable*>(h)->DiskRows();
}

void ptpu_ssd_flush(void* h) {
  static_cast<ptpu::SsdSparseTable*>(h)->Flush();
}

int ptpu_ssd_recover(void* h) {
  return static_cast<ptpu::SsdSparseTable*>(h)->Recover() ? 1 : 0;
}

int ptpu_ssd_save(void* h, const char* path) {
  return static_cast<ptpu::SsdSparseTable*>(h)->SaveAll(path) ? 1 : 0;
}

int ptpu_ssd_load(void* h, const char* path) {
  return static_cast<ptpu::SsdSparseTable*>(h)->LoadAll(path) ? 1 : 0;
}

void ptpu_table_pull(void* h, const int64_t* ids, int n, float* out) {
  static_cast<ptpu::SparseTable*>(h)->Pull(ids, n, out);
}

void ptpu_table_push(void* h, const int64_t* ids, int n, const float* grads,
                     float lr) {
  static_cast<ptpu::SparseTable*>(h)->Push(ids, n, grads, lr);
}

void ptpu_table_set(void* h, const int64_t* ids, int n, const float* rows) {
  static_cast<ptpu::SparseTable*>(h)->Set(ids, n, rows);
}

int64_t ptpu_table_size(void* h) {
  return static_cast<ptpu::SparseTable*>(h)->Size();
}

int64_t ptpu_table_shrink(void* h, float threshold) {
  return static_cast<ptpu::SparseTable*>(h)->Shrink(threshold);
}

int ptpu_table_save(void* h, const char* path) {
  return static_cast<ptpu::SparseTable*>(h)->Save(path) ? 1 : 0;
}

int ptpu_table_load(void* h, const char* path) {
  return static_cast<ptpu::SparseTable*>(h)->Load(path) ? 1 : 0;
}

void ptpu_table_destroy(void* h) {
  delete static_cast<ptpu::SparseTable*>(h);
}
}
