// Sharded concurrent sparse embedding table with fused optimizer update.
//
// Reference parity: paddle/fluid/distributed tables — CommonSparseTable
// (service/…, N30) and the heterPS GPU hashtable (framework/fleet/heter_ps/
// hashtable.h, optimizer.cuh.h, N31): feature-id -> embedding row with the
// optimizer state stored inline, pull (lookup w/ on-miss init) and push
// (gradient update) APIs. TPU-native shape: this table lives on HOST CPU
// memory (trillion-parameter scale — BASELINE config 5); the TPU holds only
// the dense towers. Pull gathers rows into a contiguous buffer for one H2D
// transfer; push applies adagrad/sgd on the host shards in parallel.
//
// Layout per row: [embedding dim floats][adagrad G2 accumulator (dim)] —
// SGD mode stores only the embedding.
#include <atomic>
#include <cmath>
#include <cstdint>
#include <cstring>
#include <fstream>
#include <mutex>
#include <random>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

namespace ptpu {

class SparseTable {
 public:
  enum Opt { SGD = 0, ADAGRAD = 1, ADAM = 2 };

  SparseTable(int dim, int num_shards, int opt, float init_range,
              uint64_t seed)
      : dim_(dim),
        num_shards_(num_shards),
        opt_((Opt)opt),
        init_range_(init_range),
        seed_(seed),
        shards_(num_shards),
        locks_(num_shards) {}

  // Row layouts: SGD [w]; ADAGRAD [w, g2]; ADAM [w, m, v, t] — the
  // optimizer state inline with the embedding (reference: sparse
  // accessor "embedx + sgd/adam fields", ctr_accessor / sparse_sgd_rule)
  int RowWidth() const {
    if (opt_ == ADAM) return dim_ * 3 + 1;
    return opt_ == ADAGRAD ? dim_ * 2 : dim_;
  }

  // Gather rows for `n` ids into out[n, dim]; missing ids are initialized
  // (uniform[-init_range, init_range]) — reference accessor "create on
  // miss" semantics.
  void Pull(const int64_t* ids, int n, float* out) {
    ParallelOver(n, [&](int i) {
      int64_t id = ids[i];
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      auto& row = GetOrInit(s, id);
      std::memcpy(out + (size_t)i * dim_, row.data(), sizeof(float) * dim_);
    });
  }

  // Apply gradients: grads[n, dim] for ids[n]; duplicate ids accumulate
  // sequentially per shard (deterministic within a shard).
  void Push(const int64_t* ids, int n, const float* grads, float lr) {
    ParallelOver(n, [&](int i) {
      int64_t id = ids[i];
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      auto& row = GetOrInit(s, id);
      const float* g = grads + (size_t)i * dim_;
      if (opt_ == ADAGRAD) {
        float* w = row.data();
        float* g2 = row.data() + dim_;
        for (int d = 0; d < dim_; ++d) {
          g2[d] += g[d] * g[d];
          w[d] -= lr * g[d] / (std::sqrt(g2[d]) + 1e-6f);
        }
      } else if (opt_ == ADAM) {
        // bias-corrected adam per row (beta1=.9, beta2=.999, eps=1e-8 —
        // the reference sparse-adam accessor defaults)
        float* w = row.data();
        float* m = row.data() + dim_;
        float* v = row.data() + 2 * dim_;
        float& t = row[3 * dim_];
        t += 1.f;
        float bc1 = 1.f - std::pow(0.9f, t);
        float bc2 = 1.f - std::pow(0.999f, t);
        for (int d = 0; d < dim_; ++d) {
          m[d] = 0.9f * m[d] + 0.1f * g[d];
          v[d] = 0.999f * v[d] + 0.001f * g[d] * g[d];
          w[d] -= lr * (m[d] / bc1) / (std::sqrt(v[d] / bc2) + 1e-8f);
        }
      } else {
        float* w = row.data();
        for (int d = 0; d < dim_; ++d) w[d] -= lr * g[d];
      }
    });
  }

  // Assign embedding values (optimizer state untouched) — used by the
  // geo communicator to refresh the worker-local mirror from the server
  // (reference: SparseGeoTable pull-and-overwrite semantics).
  void Set(const int64_t* ids, int n, const float* rows) {
    ParallelOver(n, [&](int i) {
      int64_t id = ids[i];
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      auto& row = GetOrInit(s, id);
      std::memcpy(row.data(), rows + (size_t)i * dim_,
                  sizeof(float) * dim_);
    });
  }

  int64_t Size() const {
    int64_t total = 0;
    for (auto& s : shards_) total += (int64_t)s.size();
    return total;
  }

  // Shrink: drop rows whose L2 norm is below threshold (reference:
  // SSDSparseTable/CommonSparseTable shrink for stale features).
  int64_t Shrink(float threshold) {
    int64_t dropped = 0;
    for (size_t s = 0; s < shards_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      for (auto it = shards_[s].begin(); it != shards_[s].end();) {
        float norm = 0;
        for (int d = 0; d < dim_; ++d)
          norm += it->second[d] * it->second[d];
        if (std::sqrt(norm) < threshold) {
          it = shards_[s].erase(it);
          ++dropped;
        } else {
          ++it;
        }
      }
    }
    return dropped;
  }

  bool Save(const std::string& path) {
    std::ofstream out(path, std::ios::binary);
    if (!out) return false;
    int64_t n = Size();
    int rw = RowWidth();
    out.write((char*)&dim_, sizeof(dim_));
    out.write((char*)&rw, sizeof(rw));
    out.write((char*)&n, sizeof(n));
    for (size_t s = 0; s < shards_.size(); ++s) {
      std::lock_guard<std::mutex> lk(locks_[s]);
      for (auto& kv : shards_[s]) {
        out.write((char*)&kv.first, sizeof(int64_t));
        out.write((char*)kv.second.data(), sizeof(float) * rw);
      }
    }
    return out.good();
  }

  bool Load(const std::string& path) {
    std::ifstream in(path, std::ios::binary);
    if (!in) return false;
    int dim, rw;
    int64_t n;
    in.read((char*)&dim, sizeof(dim));
    in.read((char*)&rw, sizeof(rw));
    in.read((char*)&n, sizeof(n));
    if (dim != dim_ || rw != RowWidth()) return false;
    for (int64_t i = 0; i < n; ++i) {
      int64_t id;
      in.read((char*)&id, sizeof(id));
      std::vector<float> row(rw);
      in.read((char*)row.data(), sizeof(float) * rw);
      size_t s = Shard(id);
      std::lock_guard<std::mutex> lk(locks_[s]);
      shards_[s][id] = std::move(row);
    }
    return in.good();
  }

 private:
  size_t Shard(int64_t id) const {
    return ((uint64_t)id * 0x9E3779B97F4A7C15ull >> 32) % num_shards_;
  }

  std::vector<float>& GetOrInit(size_t s, int64_t id) {
    auto it = shards_[s].find(id);
    if (it != shards_[s].end()) return it->second;
    std::vector<float> row(RowWidth(), 0.f);
    std::mt19937_64 rng(seed_ ^ (uint64_t)id);
    std::uniform_real_distribution<float> dist(-init_range_, init_range_);
    for (int d = 0; d < dim_; ++d) row[d] = dist(rng);
    return shards_[s].emplace(id, std::move(row)).first->second;
  }

  template <typename F>
  void ParallelOver(int n, F f) {
    int nthreads = (int)std::min<size_t>(
        std::max(1u, std::thread::hardware_concurrency()), 8);
    if (n < 1024 || nthreads <= 1) {
      for (int i = 0; i < n; ++i) f(i);
      return;
    }
    std::vector<std::thread> ts;
    int chunk = (n + nthreads - 1) / nthreads;
    for (int t = 0; t < nthreads; ++t) {
      int lo = t * chunk, hi = std::min(n, lo + chunk);
      if (lo >= hi) break;
      ts.emplace_back([&, lo, hi] {
        for (int i = lo; i < hi; ++i) f(i);
      });
    }
    for (auto& t : ts) t.join();
  }

  int dim_;
  int num_shards_;
  Opt opt_;
  float init_range_;
  uint64_t seed_;
  std::vector<std::unordered_map<int64_t, std::vector<float>>> shards_;
  std::vector<std::mutex> locks_;
};

// Server-side dense parameter table (reference parity:
// distributed/table/common_dense_table.h — a fixed-size parameter block
// workers pull whole and push gradients into, with the optimizer applied
// server-side).
class DenseTable {
 public:
  DenseTable(int64_t size, int opt)
      : size_(size), opt_((SparseTable::Opt)opt), w_(size, 0.f), t_(0.f) {
    if (opt_ != SparseTable::SGD) g2_.assign(size, 0.f);
    if (opt_ == SparseTable::ADAM) v_.assign(size, 0.f);
  }

  void Set(const float* vals) {
    std::lock_guard<std::mutex> lk(mu_);
    std::memcpy(w_.data(), vals, sizeof(float) * size_);
  }

  void Pull(float* out) {
    std::lock_guard<std::mutex> lk(mu_);
    std::memcpy(out, w_.data(), sizeof(float) * size_);
  }

  void Push(const float* g, float lr) {
    std::lock_guard<std::mutex> lk(mu_);
    if (opt_ == SparseTable::ADAGRAD) {
      for (int64_t d = 0; d < size_; ++d) {
        g2_[d] += g[d] * g[d];
        w_[d] -= lr * g[d] / (std::sqrt(g2_[d]) + 1e-6f);
      }
    } else if (opt_ == SparseTable::ADAM) {
      t_ += 1.f;
      float bc1 = 1.f - std::pow(0.9f, t_);
      float bc2 = 1.f - std::pow(0.999f, t_);
      for (int64_t d = 0; d < size_; ++d) {
        g2_[d] = 0.9f * g2_[d] + 0.1f * g[d];  // m in g2_
        v_[d] = 0.999f * v_[d] + 0.001f * g[d] * g[d];
        w_[d] -= lr * (g2_[d] / bc1) / (std::sqrt(v_[d] / bc2) + 1e-8f);
      }
    } else {
      for (int64_t d = 0; d < size_; ++d) w_[d] -= lr * g[d];
    }
  }

  int64_t Size() const { return size_; }

  bool Save(const std::string& path) {
    std::lock_guard<std::mutex> lk(mu_);
    std::ofstream out(path, std::ios::binary);
    if (!out) return false;
    int opt = (int)opt_;
    out.write((char*)&size_, sizeof(size_));
    out.write((char*)&opt, sizeof(opt));
    out.write((char*)&t_, sizeof(t_));
    out.write((char*)w_.data(), sizeof(float) * size_);
    if (!g2_.empty()) out.write((char*)g2_.data(), sizeof(float) * size_);
    if (!v_.empty()) out.write((char*)v_.data(), sizeof(float) * size_);
    return out.good();
  }

  bool Load(const std::string& path) {
    std::lock_guard<std::mutex> lk(mu_);
    std::ifstream in(path, std::ios::binary);
    if (!in) return false;
    int64_t size;
    int opt;
    in.read((char*)&size, sizeof(size));
    in.read((char*)&opt, sizeof(opt));
    // optimizer layout mismatch would silently misread the accumulator
    // blocks (SparseTable::Load's rw check plays the same role)
    if (size != size_ || opt != (int)opt_) return false;
    in.read((char*)&t_, sizeof(t_));
    in.read((char*)w_.data(), sizeof(float) * size_);
    if (!g2_.empty()) in.read((char*)g2_.data(), sizeof(float) * size_);
    if (!v_.empty()) in.read((char*)v_.data(), sizeof(float) * size_);
    return in.good();
  }

 private:
  int64_t size_;
  SparseTable::Opt opt_;
  std::vector<float> w_, g2_, v_;
  float t_;
  std::mutex mu_;
};

}  // namespace ptpu

extern "C" {

void* ptpu_dense_create(int64_t size, int opt) {
  return new ptpu::DenseTable(size, opt);
}

void ptpu_dense_set(void* h, const float* vals) {
  static_cast<ptpu::DenseTable*>(h)->Set(vals);
}

void ptpu_dense_pull(void* h, float* out) {
  static_cast<ptpu::DenseTable*>(h)->Pull(out);
}

void ptpu_dense_push(void* h, const float* g, float lr) {
  static_cast<ptpu::DenseTable*>(h)->Push(g, lr);
}

int64_t ptpu_dense_size(void* h) {
  return static_cast<ptpu::DenseTable*>(h)->Size();
}

int ptpu_dense_save(void* h, const char* path) {
  return static_cast<ptpu::DenseTable*>(h)->Save(path) ? 1 : 0;
}

int ptpu_dense_load(void* h, const char* path) {
  return static_cast<ptpu::DenseTable*>(h)->Load(path) ? 1 : 0;
}

void ptpu_dense_destroy(void* h) {
  delete static_cast<ptpu::DenseTable*>(h);
}

void* ptpu_table_create(int dim, int num_shards, int opt, float init_range,
                        uint64_t seed) {
  return new ptpu::SparseTable(dim, num_shards, opt, init_range, seed);
}

void ptpu_table_pull(void* h, const int64_t* ids, int n, float* out) {
  static_cast<ptpu::SparseTable*>(h)->Pull(ids, n, out);
}

void ptpu_table_push(void* h, const int64_t* ids, int n, const float* grads,
                     float lr) {
  static_cast<ptpu::SparseTable*>(h)->Push(ids, n, grads, lr);
}

void ptpu_table_set(void* h, const int64_t* ids, int n, const float* rows) {
  static_cast<ptpu::SparseTable*>(h)->Set(ids, n, rows);
}

int64_t ptpu_table_size(void* h) {
  return static_cast<ptpu::SparseTable*>(h)->Size();
}

int64_t ptpu_table_shrink(void* h, float threshold) {
  return static_cast<ptpu::SparseTable*>(h)->Shrink(threshold);
}

int ptpu_table_save(void* h, const char* path) {
  return static_cast<ptpu::SparseTable*>(h)->Save(path) ? 1 : 0;
}

int ptpu_table_load(void* h, const char* path) {
  return static_cast<ptpu::SparseTable*>(h)->Load(path) ? 1 : 0;
}

void ptpu_table_destroy(void* h) {
  delete static_cast<ptpu::SparseTable*>(h);
}
}
