// TCP key-value store: rendezvous + barrier for multi-host launch.
//
// Reference parity: platform/gen_comm_id_helper.{h,cc} (SocketServer, TCP
// broadcast of ncclUniqueId — N8) + the Gloo HTTP/FS KV rendezvous
// (role_maker.py Gloo:35, gloo_wrapper HdfsStore — N9). One store serves a
// job: rank 0 hosts it; all ranks set/get/wait keys and barrier on it. On
// TPU the payloads are the jax.distributed coordinator address and the
// cluster membership instead of NCCL ids; the protocol is payload-agnostic.
//
// Wire protocol (all little-endian):
//   u8 op ('S' set, 'G' get, 'W' wait, 'A' add, 'B' barrier-enter)
//   u32 key_len, key bytes
//   op S:  u32 val_len, val bytes             -> u8 ok
//   op G:  -> u32 val_len (0xFFFFFFFF = miss), val bytes
//   op W:  blocks until key exists            -> same as G
//   op A:  i64 delta                          -> i64 new value
//   op B:  u32 world                          -> u8 released
#include <arpa/inet.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <sys/socket.h>
#include <unistd.h>

#include <atomic>
#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <map>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

namespace ptpu {

static bool ReadN(int fd, void* buf, size_t n) {
  char* p = static_cast<char*>(buf);
  while (n) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

static bool WriteN(int fd, const void* buf, size_t n) {
  const char* p = static_cast<const char*>(buf);
  while (n) {
    ssize_t r = ::send(fd, p, n, MSG_NOSIGNAL);
    if (r <= 0) return false;
    p += r;
    n -= r;
  }
  return true;
}

class TcpStoreServer {
 public:
  explicit TcpStoreServer(int port) : port_(port) {}

  bool Start() {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return false;
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(port_);
    if (::bind(listen_fd_, (sockaddr*)&addr, sizeof(addr)) != 0) return false;
    if (port_ == 0) {
      socklen_t len = sizeof(addr);
      ::getsockname(listen_fd_, (sockaddr*)&addr, &len);
      port_ = ntohs(addr.sin_port);
    }
    if (::listen(listen_fd_, 128) != 0) return false;
    running_ = true;
    accept_thread_ = std::thread([this] { AcceptLoop(); });
    return true;
  }

  void Stop() {
    running_ = false;
    if (listen_fd_ >= 0) {
      ::shutdown(listen_fd_, SHUT_RDWR);
      ::close(listen_fd_);
      listen_fd_ = -1;
    }
    cv_.notify_all();
    if (accept_thread_.joinable()) accept_thread_.join();
    for (auto& t : workers_)
      if (t.joinable()) t.join();
    workers_.clear();
  }

  int port() const { return port_; }

  ~TcpStoreServer() { Stop(); }

 private:
  void AcceptLoop() {
    while (running_) {
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) break;
      int one = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
      workers_.emplace_back([this, fd] { Serve(fd); });
    }
  }

  void Serve(int fd) {
    while (running_) {
      uint8_t op;
      if (!ReadN(fd, &op, 1)) break;
      uint32_t klen;
      if (!ReadN(fd, &klen, 4)) break;
      std::string key(klen, '\0');
      if (klen && !ReadN(fd, key.data(), klen)) break;
      if (op == 'S') {
        uint32_t vlen;
        if (!ReadN(fd, &vlen, 4)) break;
        std::string val(vlen, '\0');
        if (vlen && !ReadN(fd, val.data(), vlen)) break;
        {
          std::lock_guard<std::mutex> lk(mu_);
          kv_[key] = std::move(val);
        }
        cv_.notify_all();
        uint8_t ok = 1;
        if (!WriteN(fd, &ok, 1)) break;
      } else if (op == 'G' || op == 'W') {
        std::unique_lock<std::mutex> lk(mu_);
        if (op == 'W') {
          cv_.wait(lk, [&] { return !running_ || kv_.count(key); });
        }
        auto it = kv_.find(key);
        if (it == kv_.end()) {
          uint32_t miss = 0xFFFFFFFFu;
          lk.unlock();
          if (!WriteN(fd, &miss, 4)) break;
        } else {
          std::string val = it->second;
          lk.unlock();
          uint32_t vlen = (uint32_t)val.size();
          if (!WriteN(fd, &vlen, 4)) break;
          if (vlen && !WriteN(fd, val.data(), vlen)) break;
        }
      } else if (op == 'A') {
        int64_t delta;
        if (!ReadN(fd, &delta, 8)) break;
        int64_t now;
        {
          std::lock_guard<std::mutex> lk(mu_);
          int64_t cur = 0;
          auto it = kv_.find(key);
          if (it != kv_.end() && it->second.size() == 8)
            std::memcpy(&cur, it->second.data(), 8);
          now = cur + delta;
          std::string v(8, '\0');
          std::memcpy(v.data(), &now, 8);
          kv_[key] = std::move(v);
        }
        cv_.notify_all();
        if (!WriteN(fd, &now, 8)) break;
      } else if (op == 'B') {
        uint32_t world;
        if (!ReadN(fd, &world, 4)) break;
        uint64_t gen;
        {
          std::unique_lock<std::mutex> lk(mu_);
          auto& b = barriers_[key];
          gen = b.generation;
          if (++b.arrived == world) {
            b.arrived = 0;
            b.generation++;
            cv_.notify_all();
          } else {
            cv_.wait(lk, [&] {
              return !running_ || barriers_[key].generation != gen;
            });
          }
        }
        uint8_t ok = 1;
        if (!WriteN(fd, &ok, 1)) break;
      } else {
        break;
      }
    }
    ::close(fd);
  }

  struct Barrier {
    uint32_t arrived = 0;
    uint64_t generation = 0;
  };

  int port_;
  int listen_fd_ = -1;
  std::atomic<bool> running_{false};
  std::thread accept_thread_;
  std::vector<std::thread> workers_;
  std::mutex mu_;
  std::condition_variable cv_;
  std::map<std::string, std::string> kv_;
  std::map<std::string, Barrier> barriers_;
};

class TcpStoreClient {
 public:
  bool Connect(const std::string& host, int port, int timeout_sec) {
    for (int i = 0; i < timeout_sec * 10; ++i) {
      fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
      sockaddr_in addr{};
      addr.sin_family = AF_INET;
      addr.sin_port = htons(port);
      ::inet_pton(AF_INET, host.c_str(), &addr.sin_addr);
      if (::connect(fd_, (sockaddr*)&addr, sizeof(addr)) == 0) {
        int one = 1;
        ::setsockopt(fd_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        return true;
      }
      ::close(fd_);
      ::usleep(100 * 1000);
    }
    return false;
  }

  bool Set(const std::string& key, const std::string& val) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!SendHeader('S', key)) return false;
    uint32_t vlen = (uint32_t)val.size();
    if (!WriteN(fd_, &vlen, 4)) return false;
    if (vlen && !WriteN(fd_, val.data(), vlen)) return false;
    uint8_t ok;
    return ReadN(fd_, &ok, 1) && ok == 1;
  }

  bool Get(const std::string& key, std::string* out, bool wait) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!SendHeader(wait ? 'W' : 'G', key)) return false;
    uint32_t vlen;
    if (!ReadN(fd_, &vlen, 4)) return false;
    if (vlen == 0xFFFFFFFFu) return false;
    out->resize(vlen);
    return vlen == 0 || ReadN(fd_, out->data(), vlen);
  }

  bool Add(const std::string& key, int64_t delta, int64_t* out) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!SendHeader('A', key)) return false;
    if (!WriteN(fd_, &delta, 8)) return false;
    return ReadN(fd_, out, 8);
  }

  bool Barrier(const std::string& key, uint32_t world) {
    std::lock_guard<std::mutex> lk(mu_);
    if (!SendHeader('B', key)) return false;
    if (!WriteN(fd_, &world, 4)) return false;
    uint8_t ok;
    return ReadN(fd_, &ok, 1) && ok == 1;
  }

  ~TcpStoreClient() {
    if (fd_ >= 0) ::close(fd_);
  }

 private:
  bool SendHeader(uint8_t op, const std::string& key) {
    if (!WriteN(fd_, &op, 1)) return false;
    uint32_t klen = (uint32_t)key.size();
    if (!WriteN(fd_, &klen, 4)) return false;
    return klen == 0 || WriteN(fd_, key.data(), klen);
  }

  int fd_ = -1;
  std::mutex mu_;
};

}  // namespace ptpu

extern "C" {

void* ptpu_store_server_start(int port) {
  auto* s = new ptpu::TcpStoreServer(port);
  if (!s->Start()) {
    delete s;
    return nullptr;
  }
  return s;
}

int ptpu_store_server_port(void* h) {
  return static_cast<ptpu::TcpStoreServer*>(h)->port();
}

void ptpu_store_server_stop(void* h) {
  delete static_cast<ptpu::TcpStoreServer*>(h);
}

void* ptpu_store_client_connect(const char* host, int port, int timeout_sec) {
  auto* c = new ptpu::TcpStoreClient();
  if (!c->Connect(host, port, timeout_sec)) {
    delete c;
    return nullptr;
  }
  return c;
}

int ptpu_store_set(void* h, const char* key, const char* val, int vlen) {
  return static_cast<ptpu::TcpStoreClient*>(h)->Set(
             key, std::string(val, vlen))
             ? 1
             : 0;
}

// Returns length, -1 on miss. Caller buffer must be >= cap.
int ptpu_store_get(void* h, const char* key, char* buf, int cap, int wait) {
  std::string out;
  if (!static_cast<ptpu::TcpStoreClient*>(h)->Get(key, &out, wait != 0))
    return -1;
  int n = (int)out.size() < cap ? (int)out.size() : cap;
  std::memcpy(buf, out.data(), n);
  return (int)out.size();
}

int64_t ptpu_store_add(void* h, const char* key, int64_t delta) {
  int64_t out = -1;
  static_cast<ptpu::TcpStoreClient*>(h)->Add(key, delta, &out);
  return out;
}

int ptpu_store_barrier(void* h, const char* key, uint32_t world) {
  return static_cast<ptpu::TcpStoreClient*>(h)->Barrier(key, world) ? 1 : 0;
}

void ptpu_store_client_close(void* h) {
  delete static_cast<ptpu::TcpStoreClient*>(h);
}
}
