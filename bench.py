"""Benchmark: flagship GPT train-step throughput on one TPU chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); vs_baseline is measured
MFU against the BASELINE.json north-star target of 45% MFU (value > 1.0
beats the target). Model: GPT ~124M (config ladder step toward GPT-1.3B),
bf16, fused single-program train step (forward+backward+Adam).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np


def main():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    paddle.seed(0)
    B, L = 8, 1024
    # GPT-350M (gpt_medium, the config ladder's step toward GPT-1.3B): big
    # enough matmuls to saturate the MXU on one chip
    config = GPTConfig(vocab_size=50304, hidden_size=1024, num_layers=24,
                       num_heads=16, max_seq_len=L, hidden_dropout=0.0,
                       attn_dropout=0.0, use_flash_attention=True)
    model = GPTForCausalLM(config)
    # bf16 params (fp32 master kept by the optimizer)
    for p in model.parameters():
        if p.data.dtype == jnp.float32:
            p.data = p.data.astype(jnp.bfloat16)
    crit = GPTPretrainingCriterion(config)
    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)

    def loss_fn(m, ids, labels):
        return crit(m(ids), labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    n_iter = 10
    ids_np = rng.randint(0, config.vocab_size,
                         (n_iter, B, L)).astype('int32')
    labels_np = np.roll(ids_np, -1, 2).astype('int32')
    ids_stack = Tensor(ids_np)
    labels_stack = Tensor(labels_np)

    # warmup/compile: k steps fused into one dispatch (lax.scan over the
    # train step) so launch overhead amortizes — the TPU-idiomatic loop.
    losses = step.run_steps(ids_stack, labels_stack)
    float(losses[0])
    t0 = time.time()
    losses = step.run_steps(ids_stack, labels_stack)
    float(losses[-1])  # sync
    dt = (time.time() - t0) / n_iter

    # FLOPs: 6 * n_params * tokens (fwd+bwd) + attention term
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    tokens = B * L
    flops = 6 * n_params * tokens + 12 * config.num_layers * \
        config.hidden_size * L * tokens
    tflops = flops / dt / 1e12
    # TPU v5e peak: 197 bf16 TFLOP/s
    mfu = tflops / 197.0
    target_mfu = 0.45
    result = {
        "metric": "gpt350m_trainstep_mfu",
        "value": round(mfu, 4),
        "unit": "fraction_of_v5e_peak",
        "vs_baseline": round(mfu / target_mfu, 4),
        "detail": {
            "ms_per_step": round(dt * 1000, 2),
            "tokens_per_sec": round(tokens / dt, 1),
            "tflops": round(tflops, 2),
            "params": n_params,
            "batch": B, "seq_len": L,
        },
    }
    print(json.dumps(result))


if __name__ == '__main__':
    main()
