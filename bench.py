"""Benchmark: GPT-1.3B (north-star model) train-step MFU on one TPU chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); vs_baseline is measured
MFU against the BASELINE.json north-star target fraction of 45% MFU
(value > 1.0 beats the target).

Headline: GPT-1.3B (hidden 2048, 24 layers, seq 2048), bf16, through the
1F1B SPMD pipeline engine at pp=1 — per-block rematerialization, microbatch
accumulation, param-dtype grad accumulator, single fused XLA program per
step. Single-chip memory budget (v5e 16G HBM) cannot hold fp32 Adam
moments for 1.3B params (+10.4G); the optimizer here is SGD — at scale the
hybrid engine shards Adam state over the 'sharding' axis (ZeRO, tested on
the virtual mesh). detail carries the BERT-base config-3 measurement
(bf16 + ZeRO-2 machinery via the hybrid engine).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

V5E_PEAK_TFLOPS = 197.0
TARGET_MFU = 0.45


def bench_gpt_1p3b():
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        SpmdPipelineEngine)
    import paddle_tpu.distributed.fleet as fm

    fm.fleet._hcg = None
    topology_runtime.build_mesh(['dp', 'pp'], [1, 1])
    paddle.seed(0)
    L = 2048
    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=L, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=True)
    embed, blocks, head = build_gpt_pipeline(cfg)
    layers = [embed, head] + blocks
    for layer in layers:
        for p in layer.parameters():
            if p.data.dtype == jnp.float32:
                p.data = p.data.astype(jnp.bfloat16)
    n_params = sum(int(np.prod(p.shape))
                   for layer in layers for p in layer.parameters())
    opt = paddle.optimizer.SGD(learning_rate=1e-4, parameters=[],
                               multi_precision=False)
    A, mb = 4, 2
    eng = SpmdPipelineEngine(embed, blocks, head, opt, accumulate_steps=A,
                             use_remat=True, schedule='1F1B',
                             grad_accum_dtype='param')
    # A=4 x mb=2 measured best on one v5e chip (58.8% vs 53.9% at mb=1:
    # bigger per-microbatch matmuls amortize layernorm/transpose overhead)
    # the engine owns device copies; free the eager duplicates (2.6G)
    for layer in layers:
        for p in layer.parameters():
            p._data = jnp.zeros((1,), jnp.bfloat16)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (A * mb, L)).astype('int32')
    labels = np.roll(ids, -1, 1).astype('int32')
    data = (Tensor(ids), Tensor(labels))
    loss = eng.train_batch(data)          # compile + warmup
    assert np.isfinite(float(loss))
    n = 5
    dt = float('inf')                      # best of 3 trials (the tunneled
    for _ in range(3):                     # chip is time-shared; min is the
        t0 = time.time()                   # honest single-tenant number)
        for _ in range(n):
            loss = eng.train_batch(data)
        float(loss)                        # sync
        dt = min(dt, (time.time() - t0) / n)

    tokens = A * mb * L
    flops = 6 * n_params * tokens + \
        12 * cfg.num_layers * cfg.hidden_size * L * tokens
    tflops = flops / dt / 1e12
    return {
        'mfu': tflops / V5E_PEAK_TFLOPS,
        'ms_per_step': dt * 1000,
        'tokens_per_sec': tokens / dt,
        'tflops': tflops,
        'params': n_params,
        'seq_len': L,
        'microbatches': A,
    }


def bench_bert_config3():
    """BASELINE config 3: BERT-base pretraining, bf16 + the ZeRO-2 hybrid
    engine path (sharding machinery engaged; degree 1 on one chip).
    Flash at L=512 measured 46.0% MFU vs 40.7% dense after the 512x512
    tile tuning, so the crossover flag is lowered here (tools/
    bert_tune.py holds the variant sweep)."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core import flags
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        bert_pretrain_loss)
    from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
        HybridParallelTrainStep)

    flags.set_flags({'FLAGS_flash_min_seq': 512})
    topology_runtime.build_mesh(['dp', 'sharding'], [1, 1])
    paddle.seed(0)
    B, L = 64, 512
    cfg = BertConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                     num_heads=12, intermediate_size=3072, max_seq_len=L,
                     hidden_dropout=0.0, attn_dropout=0.0)
    model = BertForPretraining(cfg)
    for p in model.parameters():
        if p.data.dtype == jnp.float32:
            p.data = p.data.astype(jnp.bfloat16)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())

    def loss_fn(m, ids, mlm_labels, nsp_labels):
        # fused MLM path: chunked projection-xent, no [B*L, vocab] logits
        return m(ids, masked_lm_labels=mlm_labels,
                 next_sentence_label=nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    eng = HybridParallelTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids = Tensor(rng.randint(0, cfg.vocab_size, (B, L)).astype('int32'))
    mlm = Tensor(np.asarray(ids.data).astype('int64'))
    nsp = Tensor(rng.randint(0, 2, (B,)).astype('int64'))
    loss = eng(ids, mlm, nsp)              # compile + warmup
    assert np.isfinite(float(loss))
    n = 10                       # amortize the ~60ms tunnel RTT
    dt = float('inf')                      # best of 4 (time-shared chip)
    for _ in range(4):
        t0 = time.time()
        for _ in range(n):
            loss = eng(ids, mlm, nsp)
        float(loss)
        dt = min(dt, (time.time() - t0) / n)
    tokens = B * L
    flops = 6 * n_params * tokens + \
        12 * cfg.num_layers * cfg.hidden_size * L * tokens
    return {
        'samples_per_sec': B / dt,
        'ms_per_step': dt * 1000,
        'mfu': flops / dt / 1e12 / V5E_PEAK_TFLOPS,
        'params': n_params,
        'batch': B, 'seq_len': L,
    }


def bench_lenet_config1():
    """BASELINE config 1: MNIST LeNet, dygraph + jitted train step."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    model = LeNet(10)
    opt = paddle.optimizer.Adam(parameters=model.parameters())
    step = TrainStep(model, lambda m, img, lb: nn.functional.cross_entropy(
        m(img), lb), opt)
    B = 256
    rng = np.random.RandomState(0)
    imgs = paddle.to_tensor(rng.rand(B, 1, 28, 28).astype('float32'))
    labels = paddle.to_tensor(rng.randint(0, 10, (B,)).astype('int64'))
    float(step(imgs, labels))              # compile
    n = 20
    dt = float('inf')
    for _ in range(3):
        t0 = time.time()
        for _ in range(n):
            loss = step(imgs, labels)
        float(loss)
        dt = min(dt, (time.time() - t0) / n)
    return {'images_per_sec': B / dt, 'ms_per_step': dt * 1000,
            'batch': B}


def bench_resnet50_config2(B=128, steps=20, trials=3):
    """BASELINE config 2: ResNet-50 ImageNet shape, bf16, dp machinery
    (degree 1 on one chip — the dp grad sync is the hybrid engine's
    pmean, exercised multi-device in the dryrun/tests)."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
        HybridParallelTrainStep)
    import paddle_tpu.distributed.fleet as fm

    fm.fleet._hcg = None
    topology_runtime.build_mesh(['dp'], [1])
    paddle.seed(0)
    model = resnet50(num_classes=1000)
    for p in model.parameters():
        if p.data.dtype == jnp.float32:
            p.data = p.data.astype(jnp.bfloat16)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())

    def loss_fn(m, x, y):
        return nn.functional.cross_entropy(m(x), y)

    eng = HybridParallelTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = Tensor(jnp.asarray(rng.rand(B, 3, 224, 224), jnp.bfloat16))
    y = Tensor(rng.randint(0, 1000, (B,)).astype('int64'))
    loss = eng(x, y)                        # compile
    assert np.isfinite(float(loss))
    n = steps
    dt = float('inf')
    for _ in range(trials):
        t0 = time.time()
        for _ in range(n):
            loss = eng(x, y)
        float(loss)
        dt = min(dt, (time.time() - t0) / n)
    # ResNet-50 @224: ~4.1 GFLOPs forward per image; train ~3x forward
    flops = 3 * 4.1e9 * B
    return {'images_per_sec': B / dt, 'ms_per_step': dt * 1000,
            'mfu': flops / dt / 1e12 / V5E_PEAK_TFLOPS,
            'params': n_params, 'batch': B}


def bench_deepfm_ps_config5():
    """BASELINE config 5: DeepFM over the REAL PS wire (PsServer +
    PsClient over localhost TCP against csrc/sparse_table): per step,
    pull the batch's embedding rows, run the jitted dense
    DeepFM fwd+bwd on the chip, push the row grads back. Reports
    steps/sec + pull/push latency (the reference's
    test_model_benchmark.sh role for the PS family)."""
    import jax
    import jax.numpy as jnp
    from paddle_tpu.distributed.ps.service import PsServer, PsClient

    fields, dim, B = 26, 8, 512
    srv = PsServer().start()
    srv.add_table(0, dim=dim, optimizer='adagrad', seed=3)
    client = PsClient([f'127.0.0.1:{srv.port}'])
    rng = np.random.RandomState(0)
    # criteo-ish power-law ids over a large space
    ids = (rng.pareto(1.2, (B, fields)) * 1000).astype(np.int64) % (10**7)

    w1 = jnp.asarray(rng.randn(fields * dim, 32) * 0.05, jnp.float32)
    b1 = jnp.zeros((32,), jnp.float32)
    w2 = jnp.asarray(rng.randn(32, 1) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.randint(0, 2, (B, 1)), jnp.float32)

    @jax.jit
    def dense_step(emb, w1, b1, w2, labels):
        def loss_of(emb, w1, b1, w2):
            e = emb.reshape(B, fields, dim)
            s = e.sum(1)
            fm = 0.5 * (s * s - (e * e).sum(1)).sum(-1, keepdims=True)
            h = jax.nn.relu(e.reshape(B, -1) @ w1 + b1)
            logit = h @ w2 + fm
            return jnp.mean(jnp.clip(logit, 0) - logit * labels
                            + jnp.log1p(jnp.exp(-jnp.abs(logit))))
        loss, grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2, 3))(
            emb, w1, b1, w2)
        ge, gw1, gb1, gw2 = grads
        lr = 0.05
        return loss, ge, w1 - lr * gw1, b1 - lr * gb1, w2 - lr * gw2

    flat = ids.reshape(-1)
    emb = client.pull(0, flat, dim)         # warm rows + compile
    loss, ge, w1, b1, w2 = dense_step(jnp.asarray(emb), w1, b1, w2,
                                      labels)
    client.push(0, flat, np.asarray(ge), lr=0.05)

    n = 20
    t_pull = t_push = t_dense = 0.0
    t0 = time.time()
    for _ in range(n):
        tp = time.time()
        emb = client.pull(0, flat, dim)
        t_pull += time.time() - tp
        td = time.time()
        loss, ge, w1, b1, w2 = dense_step(jnp.asarray(emb), w1, b1, w2,
                                          labels)
        ge_np = np.asarray(ge)              # sync + host transfer
        t_dense += time.time() - td
        tu = time.time()
        client.push(0, flat, ge_np, lr=0.05)
        t_push += time.time() - tu
    dt = (time.time() - t0) / n
    rows = B * fields
    out = {'steps_per_sec': 1.0 / dt, 'ms_per_step': dt * 1000,
           'pull_ms': t_pull / n * 1000, 'push_ms': t_push / n * 1000,
           'dense_ms': t_dense / n * 1000,
           'rows_per_pull': rows,
           'pull_rows_per_sec': rows / (t_pull / n),
           'push_rows_per_sec': rows / (t_push / n),
           'table_rows': int(client.table_size(0))}
    client.shutdown()
    client.close()
    return out


def _retry(fn, attempts=3):
    """The tunneled chip's remote-compile channel occasionally drops a
    response mid-read (transient 'response body closed' /
    'read body' JaxRuntimeError); retry so one hiccup doesn't blank a
    config's numbers in the round record."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:           # noqa: BLE001
            last = e
            transient = any(tok in repr(e) for tok in (
                'remote_compile', 'read body', 'response body',
                'UNAVAILABLE', 'DEADLINE'))
            if not transient or i == attempts - 1:
                raise
            time.sleep(5 * (i + 1))
    raise last


def main():
    g = _retry(bench_gpt_1p3b)
    detail = {
        'ms_per_step': round(g['ms_per_step'], 1),
        'tokens_per_sec': round(g['tokens_per_sec'], 1),
        'tflops': round(g['tflops'], 2),
        'params': g['params'],
        'seq_len': g['seq_len'],
        'microbatches': g['microbatches'],
    }
    try:
        b = _retry(bench_bert_config3)
        detail['bert_base_zero2_bf16'] = {
            'samples_per_sec': round(b['samples_per_sec'], 2),
            'ms_per_step': round(b['ms_per_step'], 1),
            'mfu': round(b['mfu'], 4),
        }
    except Exception as e:           # headline must still print
        detail['bert_base_zero2_bf16'] = {'error': repr(e)[:200]}
    for key, fn, rounds in (
            ('lenet_mnist', bench_lenet_config1, 2),
            ('resnet50_dp_bf16', bench_resnet50_config2, 2),
            ('deepfm_ps', bench_deepfm_ps_config5, 2),
    ):
        try:
            r = _retry(fn)
            detail[key] = {k: (round(v, rounds)
                               if isinstance(v, float) else v)
                           for k, v in r.items()}
        except Exception as e:
            detail[key] = {'error': repr(e)[:200]}
    result = {
        'metric': 'gpt1.3b_trainstep_mfu',
        'value': round(g['mfu'], 4),
        'unit': 'fraction_of_v5e_peak',
        'vs_baseline': round(g['mfu'] / TARGET_MFU, 4),
        'detail': detail,
    }
    print(json.dumps(result))


if __name__ == '__main__':
    main()
