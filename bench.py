"""Benchmark: GPT-1.3B (north-star model) train-step MFU on one TPU chip.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline"}.

The reference publishes no numbers (BASELINE.md); vs_baseline is measured
MFU against the BASELINE.json north-star target fraction of 45% MFU
(value > 1.0 beats the target).

Headline: GPT-1.3B (hidden 2048, 24 layers, seq 2048), bf16, through the
1F1B SPMD pipeline engine at pp=1 — per-block rematerialization, microbatch
accumulation, param-dtype grad accumulator, single fused XLA program per
step. The optimizer is the north star's real one — AdamW — with bf16-stored
moments (5.7G beside 2.8G bf16 params; fp32 moments +10.4G don't fit a 16G
v5e) and fp32 update math in-register; at scale the hybrid engine instead
shards fp32 Adam state over the 'sharding' axis (ZeRO, tested on the
virtual mesh). detail carries the SGD leg (r1-r4 comparability) and the
BERT-base config-3 measurement (bf16 + ZeRO-2 via the hybrid engine).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

import numpy as np

V5E_PEAK_TFLOPS = 197.0
TARGET_MFU = 0.45
# record schema (ISSUE 16): v2 = top-level legs + schema_version/round
# stamps + the headline ledger record (r04/r05 artifacts predate this
# and nest legs inside detail — bench_compare normalizes both shapes)
BENCH_SCHEMA_VERSION = 2


def _next_round_id():
    """rNN one past the newest BENCH_r*.json beside this script (the
    artifact naming the driver uses); BENCH_ROUND env overrides."""
    import re
    here = os.path.dirname(os.path.abspath(__file__))
    rounds = []
    try:
        for f in os.listdir(here):
            m = re.match(r'BENCH_r(\d+)\.json$', f)
            if m:
                rounds.append(int(m.group(1)))
    except OSError:
        pass
    return f'r{(max(rounds) + 1 if rounds else 6):02d}'



def _host_gap_record(eng, sync_step, make_batches, dispatch,
                     n_sync=3, sync_trials=2, n=5, trials=3):
    """Shared ISSUE-13 harness for the training legs: measure the
    sync_loop sub-record (host-synchronous discipline — `sync_step()`
    does one per-step feed + blocking fetch) and then the windowed
    timed region (DeviceLoader + `dispatch(batch)`, loss fetched only
    at trial end) on the SAME engine. Returns (detail.host record,
    windowed best dt seconds)."""
    from paddle_tpu.io import DeviceLoader
    eng._gap.reset()
    sync_dt = float('inf')
    for _ in range(sync_trials):
        t0 = time.time()
        for _ in range(n_sync):
            sync_step()
        sync_dt = min(sync_dt, (time.time() - t0) / n_sync)
    sync_gap = eng.host_gap_snapshot()

    eng._gap.reset()
    dt = float('inf')                      # best-of-trials (time-shared
    loader_stats = None                    # chip; min is the honest
    for _ in range(trials):                # single-tenant number)
        loader = DeviceLoader(make_batches(n), engine=eng)
        t0 = time.time()
        last = None
        for b in loader:
            last = dispatch(b)
        eng.flush()
        last.result()                      # ONE fetch, at trial end
        dt = min(dt, (time.time() - t0) / n)
        loader_stats = loader.stats()
    win_gap = eng.host_gap_snapshot()
    host = {
        'dispatch_window': eng._inflight.size,
        'prefetch': loader_stats,
        'device_lr': eng._lr.fn is not None,
        'windowed': {k: win_gap.get(k) for k in
                     ('steps', 'host_gap_seconds', 'host_residue_seconds',
                      'host_bound_fraction', 'dispatch_depth_mean',
                      'dispatch_depth_max')},
        'sync_loop': dict(
            {k: sync_gap.get(k) for k in
             ('steps', 'host_gap_seconds', 'host_residue_seconds',
              'host_bound_fraction')},
            ms_per_step=sync_dt * 1000),
        # the ISSUE-13 CPU-dryrun acceptance signal: the windowed loop's
        # host gap must be strictly below the synchronous loop's
        'host_gap_reduced':
            win_gap['host_gap_seconds'] < sync_gap['host_gap_seconds'],
    }
    return host, dt


def bench_gpt_1p3b(optimizer='adamw'):
    """optimizer='adamw' is the headline: the north star is Fleet hybrid
    training, and nobody trains GPT with SGD. fp32 Adam moments for 1.3B
    params (+10.4G) don't fit a 16G v5e chip, so moments are stored bf16
    (5.7G beside 2.8G bf16 params) and the update math runs fp32
    in-register (optimizer.py Adam.moment_dtype). 'sgd' is kept as a
    detail leg for cross-round comparability with r1-r4."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        SpmdPipelineEngine)
    import paddle_tpu.distributed.fleet as fm

    fm.fleet._hcg = None
    topology_runtime.build_mesh(['dp', 'pp'], [1, 1])
    paddle.seed(0)
    L = 2048
    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=L, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=True)
    embed, blocks, head = build_gpt_pipeline(cfg)
    layers = [embed, head] + blocks
    for layer in layers:
        for p in layer.parameters():
            if p.data.dtype == jnp.float32:
                p.data = p.data.astype(jnp.bfloat16)
    n_params = sum(int(np.prod(p.shape))
                   for layer in layers for p in layer.parameters())
    if optimizer == 'adamw':
        opt = paddle.optimizer.AdamW(learning_rate=1e-4, parameters=[],
                                     weight_decay=0.01,
                                     multi_precision=False,
                                     moment_dtype='bfloat16')
    else:
        opt = paddle.optimizer.SGD(learning_rate=1e-4, parameters=[],
                                   multi_precision=False)
    A, mb = 4, 2
    eng = SpmdPipelineEngine(embed, blocks, head, opt, accumulate_steps=A,
                             use_remat=True, schedule='1F1B',
                             grad_accum_dtype='param')
    # A=4 x mb=2 measured best on one v5e chip (58.8% vs 53.9% at mb=1:
    # bigger per-microbatch matmuls amortize layernorm/transpose overhead)
    # the engine owns device copies; free the eager duplicates (2.6G)
    for layer in layers:
        for p in layer.parameters():
            p._data = jnp.zeros((1,), jnp.bfloat16)

    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (A * mb, L)).astype('int32')
    labels = np.roll(ids, -1, 1).astype('int32')
    data = (Tensor(ids), Tensor(labels))
    from paddle_tpu.core import memory as _mem
    census_before = _mem.sample(count_buffers=True)
    loss = eng.train_batch(data)          # compile + warmup
    assert np.isfinite(float(loss))
    census_after = _mem.sample(count_buffers=True)

    # sync_loop sub-record + windowed timed region (ISSUE 13): the
    # headline ms_per_step now comes from the DeviceLoader + windowed
    # dispatch loop, with the host-synchronous discipline measured on
    # the same engine for the host-gap comparison
    # step-time ledger (ISSUE 16): name the arch facts the engine can't
    # infer so the ledger's analytic FLOPs match the bench formula below
    from paddle_tpu.core import ledger as _ledger_mod
    _ledger_mod.configure('pipeline', layers=cfg.num_layers,
                          hidden=cfg.hidden_size, seq_len=L,
                          n_params=n_params, arch='gpt')
    # telemetry time axis (ISSUE 18): history rings sample on the
    # telemetry publishes inside the timed loop, and the engine alert
    # pack rides along — a clean leg must not fire a critical rule
    # (_check_legs asserts on the recorded summary)
    from paddle_tpu.core import monitor as _monitor
    from paddle_tpu.core.alerts import AlertManager, default_rules
    hist = _monitor.metrics().enable_history(capacity=240)
    alerts = AlertManager(hist, rules=default_rules(), source='bench')
    host, dt = _host_gap_record(
        eng,
        sync_step=lambda: float(
            eng.train_batch((Tensor(ids), Tensor(labels)))),
        make_batches=lambda k: [(ids, labels)] * k,
        dispatch=eng.train_step,
        n_sync=3, sync_trials=2, n=5, trials=3)
    # the reconciled where-did-the-step-go account, published by the
    # flush inside the windowed loop (health_dump ledger renders this)
    ledger_rec = eng._ledger.account()
    _monitor.metrics().history_tick()   # final sample + rule pass
    series_rec = hist.export(max_points=24)
    alerts_rec = alerts.summary()
    alerts.detach()

    tokens = A * mb * L
    flops = 6 * n_params * tokens + \
        12 * cfg.num_layers * cfg.hidden_size * L * tokens
    tflops = flops / dt / 1e12
    # teardown proof (r5 regression): shutdown must actually release the
    # ~8.5G of params+moments+executables; the post-shutdown census from
    # the memory accountant goes into the round record
    before = len(jax.live_arrays())
    released = eng.shutdown()
    # which fused Pallas primitives the compiled step actually routed to
    # (ISSUE 8): BENCH_r06+ attributes ms_per_step deltas to these. On a
    # CPU-only bench run the optimizer/norm kernels auto-fall back, so
    # the routes dict is the honest evidence either way (interpret-mode
    # parity lives in tests/test_fused_primitives.py).
    from paddle_tpu.ops.pallas import scaffold as _scaffold
    from paddle_tpu.distributed.fleet.utils.recompute import (
        boundary_counts as _remat_boundaries)
    return {
        'mfu': tflops / V5E_PEAK_TFLOPS,
        'ms_per_step': dt * 1000,
        'tokens_per_sec': tokens / dt,
        'tflops': tflops,
        'params': n_params,
        'seq_len': L,
        'microbatches': A,
        'optimizer': optimizer,
        'fused_primitives': {'active': _scaffold.active_primitives(),
                             'routes': _scaffold.routes_snapshot()},
        # tuned-remat evidence (ISSUE 12): the resolved policy, the
        # checkpoint_name boundaries the trace carried, and the
        # activation census around the compile (the compiled-program
        # temp bytes ride in telemetry.remat.activation_bytes +
        # memory.sample.activation_bytes)
        'remat': {
            'policy': eng._remat_policy or (
                'full' if eng.use_remat else 'none'),
            'boundaries': _remat_boundaries(),
            'census_before': {k: census_before.get(k) for k in
                              ('bytes_in_use', 'live_bytes',
                               'live_buffers')},
            'census_after': {k: census_after.get(k) for k in
                             ('bytes_in_use', 'live_bytes',
                              'live_buffers')},
            'activation_bytes': census_after.get('activation_bytes'),
        },
        # async step pipeline (ISSUE 13): dispatch window + prefetch
        # depth + host-gap before/after — BENCH_r06's instrument for
        # telling compute-bound from host-bound
        'host': host,
        # step-time ledger (ISSUE 16): compute/exposed-comm/bubble/
        # host-gap/residue decomposition + model TFLOP/s with the remat
        # recompute factor reflected (MFU only on real TPU peaks)
        'ledger': ledger_rec,
        # telemetry time axis (ISSUE 18): the downsampled history-ring
        # block + the alert summary for the leg (health_dump alerts
        # renders both; _check_legs fails the leg on a critical fire)
        'series': series_rec,
        'alerts': alerts_rec,
        'live_buffers_before_shutdown': before,
        'live_buffers_after_shutdown': released.get('live_buffers'),
        'live_bytes_after_shutdown': released.get('live_bytes'),
    }


def bench_bert_config3():
    """BASELINE config 3: BERT-base pretraining, bf16 + the ZeRO-2 hybrid
    engine path (sharding machinery engaged; degree 1 on one chip).
    Flash at L=512 measured 46.0% MFU vs 40.7% dense after the 512x512
    tile tuning, so the crossover flag is lowered here (tools/
    bert_tune.py holds the variant sweep)."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core import flags
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.bert import (BertConfig, BertForPretraining,
                                        bert_pretrain_loss)
    from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
        HybridParallelTrainStep)

    flags.set_flags({'FLAGS_flash_min_seq': 512})
    topology_runtime.build_mesh(['dp', 'sharding'], [1, 1])
    paddle.seed(0)
    B, L = 64, 512
    cfg = BertConfig(vocab_size=30522, hidden_size=768, num_layers=12,
                     num_heads=12, intermediate_size=3072, max_seq_len=L,
                     hidden_dropout=0.0, attn_dropout=0.0)
    model = BertForPretraining(cfg)
    for p in model.parameters():
        if p.data.dtype == jnp.float32:
            p.data = p.data.astype(jnp.bfloat16)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())

    def loss_fn(m, ids, mlm_labels, nsp_labels):
        # fused MLM path: chunked projection-xent, no [B*L, vocab] logits
        return m(ids, masked_lm_labels=mlm_labels,
                 next_sentence_label=nsp_labels)

    opt = paddle.optimizer.AdamW(learning_rate=1e-4,
                                 parameters=model.parameters(),
                                 weight_decay=0.01)
    eng = HybridParallelTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    ids_np = rng.randint(0, cfg.vocab_size, (B, L)).astype('int32')
    mlm_np = ids_np.astype('int64')
    nsp_np = rng.randint(0, 2, (B,)).astype('int64')
    ids, mlm, nsp = Tensor(ids_np), Tensor(mlm_np), Tensor(nsp_np)
    loss = eng(ids, mlm, nsp)              # compile + warmup
    assert np.isfinite(float(loss))

    # sync_loop sub-record + windowed timed region (ISSUE 13), same
    # harness as the headline leg; n=10 amortizes the ~60ms tunnel RTT
    host, dt = _host_gap_record(
        eng,
        sync_step=lambda: float(
            eng(Tensor(ids_np), Tensor(mlm_np), Tensor(nsp_np))),
        make_batches=lambda k: [(ids_np, mlm_np, nsp_np)] * k,
        dispatch=lambda b: eng.train_step(*b),
        n_sync=3, sync_trials=2, n=10, trials=4)
    tokens = B * L
    flops = 6 * n_params * tokens + \
        12 * cfg.num_layers * cfg.hidden_size * L * tokens
    eng.shutdown()
    return {
        'samples_per_sec': B / dt,
        'ms_per_step': dt * 1000,
        'mfu': flops / dt / 1e12 / V5E_PEAK_TFLOPS,
        'params': n_params,
        'batch': B, 'seq_len': L,
        'host': host,
    }


def bench_lenet_config1():
    """BASELINE config 1: MNIST LeNet, dygraph + jitted train step."""
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.jit import TrainStep

    paddle.seed(0)
    model = LeNet(10)
    opt = paddle.optimizer.Adam(parameters=model.parameters())
    step = TrainStep(model, lambda m, img, lb: nn.functional.cross_entropy(
        m(img), lb), opt)
    B = 256
    rng = np.random.RandomState(0)
    imgs = paddle.to_tensor(rng.rand(B, 1, 28, 28).astype('float32'))
    labels = paddle.to_tensor(rng.randint(0, 10, (B,)).astype('int64'))
    float(step(imgs, labels))              # compile
    n = 20
    dt = float('inf')
    for _ in range(3):
        t0 = time.time()
        for _ in range(n):
            loss = step(imgs, labels)
        float(loss)
        dt = min(dt, (time.time() - t0) / n)
    return {'images_per_sec': B / dt, 'ms_per_step': dt * 1000,
            'batch': B}


def bench_resnet50_config2(B=128, steps=20, trials=3):
    """BASELINE config 2: ResNet-50 ImageNet shape, bf16, dp machinery
    (degree 1 on one chip — the dp grad sync is the hybrid engine's
    pmean, exercised multi-device in the dryrun/tests)."""
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.vision.models import resnet50
    from paddle_tpu import nn
    from paddle_tpu.distributed.fleet.meta_parallel.hybrid_engine import (
        HybridParallelTrainStep)
    import paddle_tpu.distributed.fleet as fm

    fm.fleet._hcg = None
    topology_runtime.build_mesh(['dp'], [1])
    paddle.seed(0)
    model = resnet50(num_classes=1000)
    for p in model.parameters():
        if p.data.dtype == jnp.float32:
            p.data = p.data.astype(jnp.bfloat16)
    n_params = sum(int(np.prod(p.shape)) for p in model.parameters())
    opt = paddle.optimizer.Momentum(learning_rate=0.1, momentum=0.9,
                                    parameters=model.parameters())

    def loss_fn(m, x, y):
        return nn.functional.cross_entropy(m(x), y)

    eng = HybridParallelTrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(0)
    x = Tensor(jnp.asarray(rng.rand(B, 3, 224, 224), jnp.bfloat16))
    y = Tensor(rng.randint(0, 1000, (B,)).astype('int64'))
    loss = eng(x, y)                        # compile
    assert np.isfinite(float(loss))
    n = steps
    dt = float('inf')
    for _ in range(trials):
        t0 = time.time()
        for _ in range(n):
            loss = eng(x, y)
        float(loss)
        dt = min(dt, (time.time() - t0) / n)
    # ResNet-50 @224: ~4.1 GFLOPs forward per image; train ~3x forward
    flops = 3 * 4.1e9 * B
    eng.shutdown()
    return {'images_per_sec': B / dt, 'ms_per_step': dt * 1000,
            'mfu': flops / dt / 1e12 / V5E_PEAK_TFLOPS,
            'params': n_params, 'batch': B}


def bench_deepfm_ps_config5():
    """BASELINE config 5: DeepFM over the REAL PS wire (PsServer +
    PsClient over localhost TCP against csrc/sparse_table), OVERLAPPED
    via the AsyncCommunicator (reference communicator.h:197 role): the
    prefetch thread pulls batch t+1 and uploads it to the device while
    the chip computes step t, and the push drainer forces step t's
    gradient readback + wire push in the background. Steady state
    ms_per_step ~= max(device step, host wire work), not their sum
    (VERDICT r4 weak #2: the un-overlapped loop measured 165 ms of
    which 97% was serial transfer). Reports the un-overlapped
    components too so the overlap is visible in the record."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    from paddle_tpu.distributed.ps.service import PsServer, PsClient
    from paddle_tpu.distributed.ps.communicator import AsyncCommunicator

    fields, dim, B, K = 26, 8, 512, 16      # K = merged steps per RTT
    srv = PsServer().start()
    srv.add_table(0, dim=dim, optimizer='adagrad', seed=3)
    client = PsClient([f'127.0.0.1:{srv.port}'])
    rng = np.random.RandomState(0)
    # criteo-ish power-law ids over a large space; the steady-state
    # loop cycles over warmed distinct chunks (resident rows — the r4
    # bench's regime, so the overlap number isolates pipelining from
    # first-touch row inserts; the scale leg covers cold/spilled rows)
    n_chunks = 12
    distinct = [(rng.pareto(1.2, (K, B, fields)) * 1000)
                .astype(np.int64).reshape(K, -1) % (10**7)
                for _ in range(3)]
    id_stream = [distinct[i % 3] for i in range(n_chunks + 1)]

    w1 = jnp.asarray(rng.randn(fields * dim, 32) * 0.05, jnp.float32)
    b1 = jnp.zeros((32,), jnp.float32)
    w2 = jnp.asarray(rng.randn(32, 1) * 0.05, jnp.float32)
    labels = jnp.asarray(rng.randint(0, 2, (B, 1)), jnp.float32)

    def one_step(emb, w1, b1, w2):
        def loss_of(emb, w1, b1, w2):
            e = emb.reshape(B, fields, dim)
            s = e.sum(1)
            fm = 0.5 * (s * s - (e * e).sum(1)).sum(-1, keepdims=True)
            h = jax.nn.relu(e.reshape(B, -1) @ w1 + b1)
            logit = h @ w2 + fm
            return jnp.mean(jnp.clip(logit, 0) - logit * labels
                            + jnp.log1p(jnp.exp(-jnp.abs(logit))))
        loss, grads = jax.value_and_grad(loss_of, argnums=(0, 1, 2, 3))(
            emb, w1, b1, w2)
        ge, gw1, gb1, gw2 = grads
        lr = 0.05
        return loss, ge, w1 - lr * gw1, b1 - lr * gb1, w2 - lr * gw2

    @jax.jit
    def dense_chunk(embs, w1, b1, w2):
        """K merged train steps in ONE dispatch (the reference
        Communicator's batch-merge, TPU-shaped): scan carries the dense
        params through K batches; the K row-grad sets come back in one
        device->host readback. Embedding rows within the chunk are
        one-chunk stale — the async-PS contract."""
        def body(carry, emb):
            w1, b1, w2 = carry
            loss, ge, w1, b1, w2 = one_step(emb, w1, b1, w2)
            return (w1, b1, w2), (loss, ge)
        (w1, b1, w2), (losses, ges) = lax.scan(body, (w1, b1, w2), embs)
        return losses.mean(), ges, w1, b1, w2

    # warm every distinct chunk's rows + compile, then measure the
    # UN-overlapped per-step parts on the same warm-row state
    for ch in distinct:
        for f in ch:
            client.pull(0, f, dim)
    flat0 = id_stream[-1]
    embs = jnp.asarray(np.stack([client.pull(0, f, dim)
                                 for f in flat0]))
    loss, ges, w1, b1, w2 = dense_chunk(embs, w1, b1, w2)
    np.asarray(ges)
    pull_ms = push_ms = dense_ms = float('inf')
    for _ in range(2):                       # best of 2 (shared chip)
        tp = time.time()
        pulled = [client.pull(0, f, dim) for f in flat0]
        pull_ms = min(pull_ms, (time.time() - tp) * 1000 / K)
        td = time.time()
        loss, ges, w1, b1, w2 = dense_chunk(
            jnp.asarray(np.stack(pulled)), w1, b1, w2)
        ges_np = np.asarray(ges)
        dense_ms = min(dense_ms, (time.time() - td) * 1000 / K)
        tu = time.time()
        for f, g in zip(flat0, ges_np):
            client.push(0, f, g, lr=0.05)
        push_ms = min(push_ms, (time.time() - tu) * 1000 / K)

    # chunk adapter: the communicator moves whole K-chunks per queue
    # item. Tunnel discipline: only the MAIN thread touches the device
    # (the tunneled chip serializes crossings, so worker-thread H2D/D2H
    # just adds head-of-line blocking); the prefetch thread overlaps
    # the K pulls and the drainer overlaps the K pushes with compute.
    import types as _types
    chunk_client = _types.SimpleNamespace(
        pull=lambda tid, ids, d: np.stack(
            [client.pull(tid, f, d) for f in ids]),
        push=lambda tid, ids, grads, lr: [
            client.push(tid, f, g, lr) for f, g in zip(ids, grads)])
    dt = float('inf')
    for _ in range(2):                       # best of 2 (shared chip)
        comm = AsyncCommunicator(chunk_client, 0, dim, depth=2)
        batches = comm.pull_ahead(id_stream[:n_chunks])
        ids0, emb0 = next(batches)           # prime the pipeline
        t0 = time.time()
        done = 0
        for ids_t, emb_t in batches:
            loss, ges, w1, b1, w2 = dense_chunk(jnp.asarray(emb0),
                                                w1, b1, w2)
            comm.push_async(ids0, np.asarray(ges), lr=0.05)
            done += K
            ids0, emb0 = ids_t, emb_t
        loss, ges, w1, b1, w2 = dense_chunk(jnp.asarray(emb0),
                                            w1, b1, w2)
        comm.push_async(ids0, np.asarray(ges), lr=0.05)
        done += K
        comm.flush()
        float(loss)
        dt = min(dt, (time.time() - t0) / done)
        comm.stop()

    rows = B * fields
    out = {'steps_per_sec': 1.0 / dt, 'ms_per_step': dt * 1000,
           'pull_ms': pull_ms, 'push_ms': push_ms,
           'dense_ms': dense_ms, 'merged_steps': K,
           'overlap_speedup': (pull_ms + push_ms + dense_ms) / (dt * 1000),
           'rows_per_pull': rows,
           'pull_rows_per_sec': rows / (pull_ms / 1000),
           'push_rows_per_sec': rows / (push_ms / 1000),
           'table_rows': int(client.table_size(0))}
    client.shutdown()
    client.close()
    return out


def bench_ps_scale(total_rows=2_000_000, mem_budget_rows=1 << 18,
                   dim=8, batch_rows=13312):
    """PS-at-scale leg (VERDICT r5 #4): the SSD spill tier engaged for
    real over the TCP wire — ~2M distinct rows against a 256k-row RAM
    budget (>85% of the table lives in the spill logs), then pull/push
    latency measured on uniform batches over the WHOLE id space, so
    most touches hit cold spilled rows (reference scale claim:
    README.md:49-50 10^11-feature PS; same tier, laptop-sized corpus)."""
    import tempfile
    from paddle_tpu.distributed.ps.service import PsServer, PsClient

    tmp = tempfile.TemporaryDirectory(prefix='ps_scale_')
    srv = PsServer().start()
    srv.add_table(0, dim=dim, optimizer='adagrad', seed=3,
                  ssd_path=tmp.name, mem_budget_rows=mem_budget_rows)
    client = PsClient([f'127.0.0.1:{srv.port}'])
    rng = np.random.RandomState(0)

    # populate: first-touch pulls insert rows; the budget forces spill
    t0 = time.time()
    seen = 0
    chunk = 1 << 17
    while seen < total_rows:
        ids = np.arange(seen, min(seen + chunk, total_rows),
                        dtype=np.int64)
        client.pull(0, ids, dim)
        seen += len(ids)
    build_s = time.time() - t0
    tbl = srv.tables[0]
    resident = int(tbl.mem_rows())
    total = int(tbl.total_rows())

    # steady state: uniform random batches over the full space — cold
    # (spilled) rows dominate each pull/push
    n = 15
    t_pull = t_push = 0.0
    for _ in range(n):
        ids = rng.randint(0, total_rows, batch_rows).astype(np.int64)
        tp = time.time()
        rows = client.pull(0, ids, dim)
        t_pull += time.time() - tp
        g = rng.rand(batch_rows, dim).astype(np.float32) * 0.01
        tu = time.time()
        client.push(0, ids, g, lr=0.05)
        t_push += time.time() - tu
    out = {'table_rows': total,
           'resident_rows': resident,
           'spilled_rows': total - resident,
           'spilled_frac': round(1 - resident / max(total, 1), 4),
           'mem_budget_rows': mem_budget_rows,
           'build_rows_per_sec': total_rows / build_s,
           'pull_ms': t_pull / n * 1000,
           'push_ms': t_push / n * 1000,
           'rows_per_batch': batch_rows,
           'pull_rows_per_sec': batch_rows / (t_pull / n),
           'push_rows_per_sec': batch_rows / (t_push / n)}
    client.shutdown()
    client.close()
    tmp.cleanup()
    return out


def bench_gpt_serve():
    """gpt_serve_throughput: the serving engine (paged KV pool +
    continuous batching + ragged paged attention, docs/serving.md) vs
    sequential per-request `generate` on the SAME mixed-length request
    stream. The acceptance number is `speedup_vs_sequential` — batched
    continuous decode must beat one-request-at-a-time decode by roughly
    the achievable batch occupancy; the dense per-request cache's
    O(B * max_len) memory also drops to O(pages in use)
    (kv_pages_high_water * page_size tokens)."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingConfig

    paddle.seed(0)
    on_tpu = jax.default_backend() == 'tpu'
    if on_tpu:
        # GPT-2 124M-ish decode workload, bf16 weights/KV
        cfg = GPTConfig(vocab_size=50304, hidden_size=768, num_layers=12,
                        num_heads=12, max_seq_len=1024, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=True)
        n_req, max_new, batch, page_size, chunk = 16, 64, 8, 16, 128
        lo, hi = 32, 384
    else:
        # CPU CI shape: the leg must still run end to end on the test mesh
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=2, max_seq_len=128, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        n_req, max_new, batch, page_size, chunk = 6, 8, 3, 8, 16
        lo, hi = 4, 24
    model = GPTForCausalLM(cfg)
    if on_tpu:
        for p in model.parameters():
            if p.data.dtype == jnp.float32:
                p.data = p.data.astype(jnp.bfloat16)
    model.eval()
    rng = np.random.RandomState(0)
    lens = rng.randint(lo, hi + 1, n_req)
    prompts = [list(rng.randint(1, cfg.vocab_size, int(n))) for n in lens]

    # -- sequential per-request baseline (dense cache, greedy). First
    # pass warms every (1, L0+max_new) compiled-step shape — the dense
    # path recompiles per prompt length, and charging those compiles to
    # the baseline would flatter the engine; the measured pass is
    # steady-state decode on both sides --------------------------------
    for p in prompts:
        model.generate(Tensor(np.asarray([p], 'int32')),
                       max_new_tokens=max_new, top_k=0)
    t0 = time.time()
    gen_tokens = 0
    for p in prompts:
        out = model.generate(Tensor(np.asarray([p], 'int32')),
                             max_new_tokens=max_new, top_k=0)
        gen_tokens += out.shape[-1] - len(p)
    seq_dt = time.time() - t0
    seq_tps = gen_tokens / seq_dt

    # -- continuous batching over the paged pool ----------------------------
    # page-table width sized to the WORKLOAD, not max_seq_len: attention
    # cost (and the fallback's gather) scales with table width, and the
    # stream's contexts are known to fit hi+max_new tokens
    pages_per_seq = -(-(hi + max_new) // page_size)
    # telemetry time axis (ISSUE 18): the serve publish cadence
    # (telemetry_serve's publish -> history_tick) samples the rings
    # while the stream runs; the engine alert pack must stay quiet
    from paddle_tpu.core import monitor as _monitor
    from paddle_tpu.core.alerts import AlertManager, default_rules
    hist = _monitor.metrics().enable_history(capacity=240)
    alerts = AlertManager(hist, rules=default_rules(), source='bench')
    eng = ServingEngine(model, ServingConfig(
        page_size=page_size, max_batch_size=batch, prefill_chunk=chunk,
        max_pages_per_seq=pages_per_seq))
    eng.generate([prompts[0]], max_new_tokens=2, top_k=0)  # compile warmup
    eng.reset_stats()       # also clears the request journals/timeline
    t0 = time.time()
    outs = eng.generate(prompts, max_new_tokens=max_new, top_k=0)
    serve_dt = time.time() - t0
    serve_tokens = sum(len(o) - len(p) for o, p in zip(outs, prompts))
    st = eng.stats()

    # per-request SLO percentiles from the lifecycle journals (EXACT
    # per-request values for the measured stream — the monitor
    # histograms in telemetry_serve are bucket-interpolated and include
    # warmup; these are the headline numbers)
    from paddle_tpu.serving.request_trace import percentile_of
    table = eng.request_table()
    slo = {}
    for key, label in (('ttft_s', 'ttft_ms'), ('tpot_s', 'tpot_ms'),
                       ('queue_wait_s', 'queue_wait_ms'),
                       ('e2e_s', 'e2e_ms')):
        vals = [r[key] for r in table.values()]
        slo[label] = {
            f'p{q}': (round(p * 1000.0, 3)
                      if (p := percentile_of(vals, q)) is not None
                      else None)
            for q in (50, 90, 99)}
    timeline = eng.timeline.summary()

    dense_cache_tokens = n_req * cfg.max_seq_len
    paged_tokens = st['pool']['high_water'] * page_size
    # serving ledger (ISSUE 17), captured BEFORE shutdown (which
    # unregisters the ledger): reconciled wall decomposition, the
    # goodput identity and the decode roofline for the measured
    # stream (warmup excluded by reset_stats)
    serve_ledger = eng.ledger.account()
    serve_goodput = eng.ledger.goodput()
    serve_roofline = eng.ledger.roofline()
    _monitor.metrics().history_tick()   # final sample + rule pass
    series_rec = hist.export(max_points=24)
    alerts_rec = alerts.summary()
    alerts.detach()
    eng.shutdown()

    # -- shared-prefix stream (ISSUE 9): N requests with a common
    # system prompt, served by the PR-5 config (no prefix cache, no
    # speculation) and by the prefix+spec engine. TTFT should drop by
    # the cached prefill chunks, decode tokens/sec should rise by the
    # accepted drafts per verify dispatch — greedy outputs identical.
    sys_len = 256 if on_tpu else 16
    spec_k = 4
    n_shared = 8 if on_tpu else 4
    system = list(rng.randint(1, cfg.vocab_size, sys_len))
    shared_prompts = [system + list(rng.randint(
        1, cfg.vocab_size, int(n)))
        for n in rng.randint(lo, hi + 1, n_shared)]
    pages_shared = -(-(sys_len + hi + max_new) // page_size)

    def _run_shared(**knobs):
        e = ServingEngine(model, ServingConfig(
            page_size=page_size, max_batch_size=batch,
            prefill_chunk=chunk, max_pages_per_seq=pages_shared,
            **knobs))
        # warm every compiled shape this engine will hit: prefill +
        # decode via the stream head, the verify shape via a
        # repetitive prompt the n-gram proposer fires on
        e.generate([shared_prompts[0]], max_new_tokens=2, top_k=0)
        if knobs.get('spec_k'):
            e.generate([[7, 8, 9] * 4], max_new_tokens=4, top_k=0)
        e.reset_stats()
        t0 = time.time()
        outs = e.generate(shared_prompts, max_new_tokens=max_new,
                          top_k=0)
        dt = time.time() - t0
        toks = sum(len(o) - len(p)
                   for o, p in zip(outs, shared_prompts))
        stl = e.stats()
        ttft = percentile_of(
            [r['ttft_s'] for r in e.request_table().values()], 50)
        e.shutdown()
        return {
            'tokens_per_sec': toks / dt,
            'decode_tokens_per_sec': stl['decode_tokens_per_sec'],
            'ttft_p50_ms': (round(ttft * 1000.0, 3)
                            if ttft is not None else None),
            'prefill_tokens': stl['prefill_tokens_total'],
            'decode_steps': stl['decode_steps_total'],
            'decode_tokens': stl['decode_tokens_total'],
            'prefix_hits': stl['prefix_hits_total'],
            'prefix_hit_tokens': stl['prefix_hit_tokens_total'],
            'spec_proposed': stl['spec_proposed_tokens_total'],
            'spec_accepted': stl['spec_accepted_tokens_total'],
            'spec_acceptance_rate': stl['spec_acceptance_rate'],
        }, outs

    base_rec, base_outs = _run_shared(prefix_cache=False, spec_k=0)
    opt_rec, opt_outs = _run_shared(prefix_cache=True, spec_k=spec_k)
    shared_prefix = {
        'requests': n_shared,
        'system_prompt_tokens': sys_len,
        'spec_k': spec_k,
        'baseline_pr5': base_rec,
        'prefix_spec': opt_rec,
        'outputs_identical': base_outs == opt_outs,
        'ttft_speedup_vs_pr5':
            (base_rec['ttft_p50_ms'] / opt_rec['ttft_p50_ms']
             if base_rec['ttft_p50_ms'] and opt_rec['ttft_p50_ms']
             else None),
        'decode_speedup_vs_pr5':
            (opt_rec['decode_tokens_per_sec']
             / base_rec['decode_tokens_per_sec']
             if base_rec['decode_tokens_per_sec'] else None),
    }

    # -- fused decode windows (ISSUE 19): small-batch decode is where
    # per-token serving goes host-bound (one dispatch + one fetch per
    # token, device done long before Python). The same stream at fused
    # k in {1, 4, 8}: decode tok/s and the ledger's measured
    # host_bound_fraction side by side, outputs identical across k.
    sb_batch = min(4, batch)
    sb_prompts = prompts[:sb_batch]
    # long enough for several windows at k=8 — a stream one window
    # swallows whole leaves no inter-step interval for the gap monitor
    # to price, and host_bound_fraction would read None
    sb_max_new = max(max_new, 24)
    sb_pages = -(-(hi + sb_max_new) // page_size)

    def _run_fused(k):
        e = ServingEngine(model, ServingConfig(
            page_size=page_size, max_batch_size=sb_batch,
            prefill_chunk=chunk, max_pages_per_seq=sb_pages,
            fused_k=k))
        # warm every compiled shape this engine will hit — prefill,
        # the [B, 1] step (mixed prefill/decode sweeps) and the fused
        # (B,) scan — on a short pass over the same stream
        e.generate(sb_prompts, max_new_tokens=2, top_k=0)
        e.reset_stats()
        t0 = time.time()
        outs = e.generate(sb_prompts, max_new_tokens=sb_max_new,
                          top_k=0)
        dt = time.time() - t0
        stf = e.stats()
        led = e.ledger.account() or {}
        e.shutdown()
        toks = sum(len(o) - len(p) for o, p in zip(outs, sb_prompts))
        return {
            'fused_k': k,
            'tokens_per_sec': toks / dt,
            'decode_tokens_per_sec': stf['decode_tokens_per_sec'],
            'host_bound_fraction': led.get('host_bound_fraction'),
            'fused_windows': stf['fused_windows_total'],
            'fused_iterations': stf['fused_iterations_total'],
            'fused_tokens': stf['fused_tokens_total'],
            'decode_steps': stf['decode_steps_total'],
        }, outs

    sb_recs, sb_outs = {}, {}
    for k in (1, 4, 8):
        sb_recs[k], sb_outs[k] = _run_fused(k)
    small_batch = {
        'requests': sb_batch,
        'decode_slots': sb_batch,
        'max_new_tokens': sb_max_new,
        'per_k': {str(k): r for k, r in sb_recs.items()},
        'outputs_identical':
            sb_outs[1] == sb_outs[4] == sb_outs[8],
    }

    # -- tiered KV cache (ISSUE 20): the SAME mixed stream through a
    # device pool sized BELOW its concurrent contexts, with the host
    # tier absorbing the overflow. The bars: token identity with a
    # sized-to-fit run (spill/resurrect must be invisible in the
    # tokens), sustained throughput + SLO percentiles under
    # oversubscription, and resurrect-from-host TTFT strictly beating
    # recompute-from-scratch on a long cold prompt.
    fit_pages = batch * pages_per_seq          # sized-to-fit capacity
    over_pages = max(pages_per_seq + 1, int(fit_pages * 0.5))

    def _run_tiered(num_pages, host_pages):
        e = ServingEngine(model, ServingConfig(
            page_size=page_size, max_batch_size=batch,
            prefill_chunk=chunk, max_pages_per_seq=pages_per_seq,
            num_pages=num_pages, host_tier_pages=host_pages,
            spill_watermark=0.7))
        e.generate([prompts[0]], max_new_tokens=2, top_k=0)
        e.reset_stats()
        t0 = time.time()
        o = e.generate(prompts, max_new_tokens=max_new, top_k=0)
        dt = time.time() - t0
        stt = e.stats()
        pst = stt['pool']
        tab = e.request_table()
        pct = {
            label: {f'p{q}': (round(v * 1000.0, 3)
                              if (v := percentile_of(
                                  [r[key] for r in tab.values()], q))
                              is not None else None)
                    for q in (50, 90, 99)}
            for key, label in (('ttft_s', 'ttft_ms'),
                               ('e2e_s', 'e2e_ms'))}
        toks = sum(len(x) - len(p) for x, p in zip(o, prompts))
        rec = {
            'device_pages': num_pages,
            'host_pages': host_pages,
            'tokens_per_sec': toks / dt,
            'decode_tokens_per_sec': stt['decode_tokens_per_sec'],
            'preemptions': stt['preemptions_total'],
            'slo': pct,
            'spilled_pages': pst.get('tier_spilled_pages_total', 0),
            'spilled_bytes': pst.get('tier_spilled_bytes_total', 0),
            'fetched_pages': pst.get('tier_fetched_pages_total', 0),
            'fetched_bytes': pst.get('tier_fetched_bytes_total', 0),
            'resurrected_pages':
                pst.get('tier_resurrected_pages_total', 0),
        }
        e.shutdown()
        return rec, o

    fit_rec, fit_outs = _run_tiered(fit_pages, 0)
    over_rec, over_outs = _run_tiered(over_pages, fit_pages * 2)

    # resurrect-vs-recompute TTFT: one long prompt whose prefix pages
    # sit on the host tier vs the same prompt with a cold cache —
    # best-of-3 each, the fetch must beat re-running the prefill.
    # 16 pages of prompt (14 on the CPU CI shape — max_seq_len caps
    # it): long enough that prefill compute dominates the
    # (near-constant) fetch dispatch overhead
    long_pages = 16 if on_tpu else 14
    long_prompt = list(rng.randint(
        1, cfg.vocab_size, long_pages * page_size + 1))
    e = ServingEngine(model, ServingConfig(
        page_size=page_size, max_batch_size=2, prefill_chunk=chunk,
        max_pages_per_seq=long_pages + 4,
        host_tier_pages=2 * long_pages + 4))
    e.generate([long_prompt], max_new_tokens=2, top_k=0)  # warm shapes
    recompute_ttft, resurrect_ttft = [], []
    for _ in range(3):
        e.pool.reset()                        # cold: nothing cached
        e.reset_stats()
        e.generate([long_prompt], max_new_tokens=2, top_k=0)
        (r,) = e.request_table().values()
        recompute_ttft.append(r['ttft_s'])
        # prefix now registered: push it to the host tier, measure
        # the resurrect path
        spilled = e.pool.spill_lru(sync=True)
        assert spilled >= long_pages, spilled
        e.reset_stats()
        outs_r = e.generate([long_prompt], max_new_tokens=2, top_k=0)
        (r,) = e.request_table().values()
        resurrect_ttft.append(r['ttft_s'])
    resurrect_identical = outs_r[0][:len(long_prompt) + 2] \
        == e.generate([long_prompt], max_new_tokens=2,
                      top_k=0)[0][:len(long_prompt) + 2]
    e.shutdown()
    oversubscribed = {
        'requests': n_req,
        'oversubscription':
            round(fit_pages / float(over_pages), 3),
        'outputs_identical': over_outs == fit_outs,
        'sized_to_fit': fit_rec,
        'tiered': over_rec,
        'recompute_ttft_ms':
            round(min(recompute_ttft) * 1000.0, 3),
        'resurrect_ttft_ms':
            round(min(resurrect_ttft) * 1000.0, 3),
        'resurrect_ttft_speedup':
            (min(recompute_ttft) / min(resurrect_ttft)
             if min(resurrect_ttft) else None),
        'resurrect_outputs_identical': resurrect_identical,
    }
    return {
        'serve_tokens_per_sec': serve_tokens / serve_dt,
        'sequential_tokens_per_sec': seq_tps,
        'speedup_vs_sequential': (serve_tokens / serve_dt) / seq_tps,
        'decode_tokens_per_sec': st['decode_tokens_per_sec'],
        'ttft_ms_mean': st['ttft_ms_mean'],
        'slo': slo,
        'timeline': timeline,
        'batch_occupancy': st['batch_occupancy'],
        'kv_page_utilization': st['kv_page_utilization'],
        'kv_pages_high_water': st['pool']['high_water'],
        'preemptions': st['preemptions_total'],
        'requests': n_req,
        'max_new_tokens': max_new,
        'decode_slots': batch,
        'page_size': page_size,
        # quantized-KV capacity accounting (ISSUE 7): the pool's dtype
        # and real byte footprint, so the round record shows the
        # tokens-per-byte win when kv_dtype='int8' legs land
        'kv_dtype': st['pool']['kv_dtype'],
        'kv_pool_bytes': st['pool']['pool_bytes'],
        'kv_bytes_per_token': st['pool']['bytes_per_token'],
        'prompt_lens': [int(n) for n in lens],
        'kv_tokens_dense_vs_paged': [dense_cache_tokens, paged_tokens],
        'shared_prefix': shared_prefix,
        # fused decode windows (ISSUE 19): the small-batch record plus
        # flat headline keys bench_compare tracks across rounds (k=8
        # leg vs the k=1 per-token path on the identical stream)
        'small_batch': small_batch,
        'small_batch_decode_tokens_per_sec':
            sb_recs[8]['decode_tokens_per_sec'],
        'small_batch_host_bound_fraction':
            sb_recs[8]['host_bound_fraction'],
        'fused_speedup_vs_per_token':
            (sb_recs[8]['decode_tokens_per_sec']
             / sb_recs[1]['decode_tokens_per_sec']
             if sb_recs[1]['decode_tokens_per_sec'] else None),
        # tiered KV cache (ISSUE 20): the oversubscribed record plus
        # flat headline keys bench_compare tracks across rounds
        'oversubscribed': oversubscribed,
        'oversubscribed_decode_tokens_per_sec':
            over_rec['decode_tokens_per_sec'],
        'resurrect_ttft_speedup':
            oversubscribed['resurrect_ttft_speedup'],
        # serving ledger & roofline (ISSUE 17): the wall decomposition
        # (components reconcile to wall_seconds, residue surfaced),
        # the delivered/wasted goodput account, and the decode
        # bytes-moved roofline (MBU only on TPU, absolute GB/s always)
        'ledger': serve_ledger,
        'goodput': serve_goodput,
        'roofline': serve_roofline,
        'goodput_fraction': serve_goodput.get('goodput_fraction'),
        'host_bound_fraction':
            (serve_ledger or {}).get('host_bound_fraction'),
        'hbm_gbps': (serve_roofline or {}).get('hbm_gbps'),
        'mbu': (serve_roofline or {}).get('mbu'),
        # telemetry time axis (ISSUE 18): downsampled rings + alert
        # summary for the measured stream (no critical may fire on a
        # clean leg — _check_legs asserts it)
        'series': series_rec,
        'alerts': alerts_rec,
        'backend': jax.default_backend(),
    }


def bench_gpt_serve_cluster():
    """gpt_serve_cluster (ISSUE 11): a 2-replica dp serving cluster
    behind the prefix-affinity router vs the single PR-9 engine on the
    SAME sustained mixed-length stream (two system-prompt families +
    random tails). Records per-replica AND aggregate SLO percentiles
    from the lifecycle journals, router placement stats (affinity /
    least-loaded / spills / rejects), and the aggregate decode
    throughput. On the CPU dryrun the replicas interleave on one core,
    so the wall clock can't show the dp speedup — the aggregate of
    per-replica decode rates (each measured over its OWN decode time,
    the same clock the 1-chip leg uses) is the scaling signal, and the
    wall numbers ride along for hardware rounds."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingConfig
    from paddle_tpu.serving.cluster import ClusterRouter, LocalReplica
    from paddle_tpu.serving.request_trace import percentile_of

    paddle.seed(0)
    on_tpu = jax.default_backend() == 'tpu'
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                        num_layers=12, num_heads=12, max_seq_len=1024,
                        hidden_dropout=0.0, attn_dropout=0.0,
                        use_flash_attention=True)
        n_req, max_new, batch, page_size, chunk = 24, 48, 8, 16, 128
        sys_len, lo, hi = 128, 16, 256
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=2, max_seq_len=128,
                        hidden_dropout=0.0, attn_dropout=0.0,
                        use_flash_attention=False)
        n_req, max_new, batch, page_size, chunk = 10, 8, 3, 8, 16
        sys_len, lo, hi = 16, 2, 24
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    families = [list(rng.randint(1, cfg.vocab_size, sys_len))
                for _ in range(2)]
    prompts = [families[i % 2]
               + list(rng.randint(1, cfg.vocab_size,
                                  int(rng.randint(lo, hi + 1))))
               for i in range(n_req)]
    pages_per_seq = -(-(sys_len + hi + max_new) // page_size)

    def _mk_config():
        return ServingConfig(page_size=page_size,
                             max_batch_size=batch,
                             prefill_chunk=chunk,
                             max_pages_per_seq=pages_per_seq)

    def _slo(table):
        out = {}
        for key, label in (('ttft_s', 'ttft_ms'),
                           ('tpot_s', 'tpot_ms'),
                           ('queue_wait_s', 'queue_wait_ms'),
                           ('e2e_s', 'e2e_ms')):
            vals = [r[key] for r in table.values()]
            out[label] = {
                f'p{q}': (round(p * 1000.0, 3)
                          if (p := percentile_of(vals, q)) is not None
                          else None)
                for q in (50, 90, 99)}
        return out

    # -- 1-chip baseline: the PR-9 engine on the whole stream --------------
    single = ServingEngine(model, _mk_config())
    single.generate([prompts[0]], max_new_tokens=2, top_k=0)  # warmup
    single.reset_stats()
    t0 = time.time()
    ref_outs = single.generate(prompts, max_new_tokens=max_new,
                               top_k=0)
    single_dt = time.time() - t0
    sstats = single.stats()
    single_rec = {
        'tokens_per_sec': sum(len(o) - len(p) for o, p in
                              zip(ref_outs, prompts)) / single_dt,
        'decode_tokens_per_sec': sstats['decode_tokens_per_sec'],
        'slo': _slo(single.request_table()),
        'prefill_tokens': sstats['prefill_tokens_total'],
        'prefix_hits': sstats['prefix_hits_total'],
    }
    single.shutdown()

    # -- 2-replica cluster on the SAME stream ------------------------------
    replicas = [LocalReplica(ServingEngine(model, _mk_config()), rid)
                for rid in ('r0', 'r1')]
    for r in replicas:      # same warmup the single engine got
        r.engine.generate([prompts[0]], max_new_tokens=2, top_k=0)
        r.engine.reset_stats()
    router = ClusterRouter(replicas, page_size=page_size,
                           max_queue=2 * n_req)
    t0 = time.time()
    outs = router.serve(prompts, max_new_tokens=max_new, top_k=0,
                        timeout_s=600)
    cluster_dt = time.time() - t0
    gen_tokens = sum(len(o) - len(p) for o, p in zip(outs, prompts))
    per_replica = {}
    agg_decode_tps = 0.0
    all_tables = {}
    for r in replicas:
        st = r.engine.stats()
        table = r.engine.request_table()
        all_tables.update({f'{r.replica_id}:{k}': v
                           for k, v in table.items()})
        agg_decode_tps += st['decode_tokens_per_sec']
        per_replica[r.replica_id] = {
            'requests': len(table),
            'decode_tokens_per_sec': st['decode_tokens_per_sec'],
            'prefill_tokens': st['prefill_tokens_total'],
            'prefix_hits': st['prefix_hits_total'],
            'batch_occupancy': st['batch_occupancy'],
            'slo': _slo(table),
            # per-replica goodput (ISSUE 17), read off the live ledger
            'goodput': r.engine.ledger.goodput(),
        }
    router.refresh()        # fresh statuses -> snapshot goodput sees
                            # every replica's final token counts
    snap = router.snapshot()

    # -- structured-rejection retry-hint accuracy (ISSUE 15): overload
    # a tiny-bound router over the SAME (warm) replicas, record the
    # RouterRejected retry_after_s hint, then measure how long the
    # cluster actually took to accept a retry — the hint's quality is
    # part of the round record because serve()'s throttle loop backs
    # off by it
    from paddle_tpu.serving.cluster import RouterRejected
    hint_router = ClusterRouter(replicas, page_size=page_size,
                                max_queue=2, refresh_interval_s=0.0)
    hinted = actual = None
    for p in prompts * 4:
        try:
            hint_router.submit(p, max_new_tokens=max_new, top_k=0)
        except RouterRejected as rej:
            hinted = rej.retry_after_s
            t_rej = time.time()
            break
    if hinted is not None:
        t_dead = time.time() + 300
        while time.time() < t_dead:
            hint_router.pump()
            try:
                hint_router.submit(prompts[0],
                                   max_new_tokens=max_new, top_k=0)
                actual = time.time() - t_rej
                break
            except RouterRejected:
                continue
    hint_router.run(timeout_s=600)
    retry_hint = {
        'hinted_s': hinted,
        'actual_s': actual,
        # `is not None`: a legitimate 0.0 hint is exactly the case the
        # accuracy record must not silently drop
        'hint_over_actual': (hinted / actual
                             if hinted is not None and actual
                             else None),
    }
    router.shutdown()
    return {
        'requests': n_req,
        'replicas': len(replicas),
        'max_new_tokens': max_new,
        'decode_slots_per_replica': batch,
        'page_size': page_size,
        'retry_hint': retry_hint,
        'single_engine': single_rec,
        'cluster': {
            'wall_tokens_per_sec': gen_tokens / cluster_dt,
            'aggregate_decode_tokens_per_sec': agg_decode_tps,
            'slo': _slo(all_tables),
            'per_replica': per_replica,
            'router': snap,
        },
        'aggregate_decode_speedup_vs_single':
            (agg_decode_tps / single_rec['decode_tokens_per_sec']
             if single_rec['decode_tokens_per_sec'] else None),
        # cluster-aggregated goodput (ISSUE 17): replica accounts
        # summed, with any drain-resubmit recompute repriced wasted
        'cluster_goodput': snap.get('goodput'),
        'goodput_fraction':
            (snap.get('goodput') or {}).get('goodput_fraction'),
        'affinity_hit_rate': snap['affinity_hit_rate'],
        'outputs_identical_to_single': outs == ref_outs,
        'backend': jax.default_backend(),
    }


def bench_gpt_serve_tenants():
    """gpt_serve_tenants (ISSUE 15): the adversarial multi-tenant
    stream — ONE heavy tenant flooding long requests + three light
    tenants submitting short ones mid-stream — served by the FCFS
    scheduler (no tenants configured) and by the SLO scheduler
    (priority classes + a quota on the heavy tenant) on the SAME
    stream. The acceptance numbers: light-tenant p99 e2e under the SLO
    scheduler vs its SOLO baseline (bar: <= 1.5x), and aggregate
    decode throughput vs FCFS (bar: >= ~0.9x — priority scheduling
    must not burn the pool's work-conservation). On the shared 1-core
    CPU dryrun both ratios carry wall-clock noise — the deterministic
    tokens-per-engine-sweep version of the same bars is asserted in
    tests/test_serving_tenants.py; the hardware round reads these as
    measured. The record also carries per-tenant SLO percentiles,
    quota/charged-preemption counters, and the degradation-ladder
    stage timeline."""
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingConfig
    from paddle_tpu.serving.request_trace import percentile_of

    paddle.seed(0)
    on_tpu = jax.default_backend() == 'tpu'
    if on_tpu:
        cfg = GPTConfig(vocab_size=50304, hidden_size=768,
                        num_layers=12, num_heads=12, max_seq_len=1024,
                        hidden_dropout=0.0, attn_dropout=0.0,
                        use_flash_attention=True)
        batch, page_size, chunk = 8, 16, 128
        heavy_n, heavy_len, heavy_new = 12, 256, 128
        light_n, light_len, light_new = 12, 24, 16
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=64, num_layers=2,
                        num_heads=2, max_seq_len=128,
                        hidden_dropout=0.0, attn_dropout=0.0,
                        use_flash_attention=False)
        batch, page_size, chunk = 2, 8, 16
        heavy_n, heavy_len, heavy_new = 5, 12, 12
        light_n, light_len, light_new = 6, 4, 4
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    heavy = [list(rng.randint(1, cfg.vocab_size, heavy_len))
             for _ in range(heavy_n)]
    light = [list(rng.randint(1, cfg.vocab_size, light_len))
             for _ in range(light_n)]
    pages_per_seq = -(-(heavy_len + heavy_new) // page_size)

    def _mk_engine(tenants):
        e = ServingEngine(model, ServingConfig(
            page_size=page_size, max_batch_size=batch,
            prefill_chunk=chunk, max_pages_per_seq=pages_per_seq,
            tenants=tenants))
        e.generate([heavy[0][:4]], max_new_tokens=2, top_k=0)  # warm
        if e._ladder is not None:
            # warm the stage-2 halved-chunk prefill shape too — a
            # ladder transition mid-overload must not pay a compile
            # (the measured stream would charge it to one tenant's e2e)
            e._ladder.stage = 2
            e.generate([heavy[0][:4]], max_new_tokens=2, top_k=0)
            e._ladder.stage = 0
            e._ladder._ring.clear()
        e.reset_stats()
        return e

    def _slo_pcts(table, tenant_prefix=None):
        rows = [r for r in table.values()
                if tenant_prefix is None
                or (r.get('tenant_id') or '').startswith(tenant_prefix)]
        out = {}
        for key, label in (('queue_wait_s', 'queue_wait_ms'),
                           ('e2e_s', 'e2e_ms')):
            vals = [r[key] for r in rows]
            out[label] = {
                f'p{q}': (round(p * 1000.0, 3)
                          if (p := percentile_of(vals, q)) is not None
                          else None)
                for q in (50, 90, 99)}
        return out

    def _run(tenants):
        eng = _mk_engine(tenants)
        t0 = time.time()
        hreqs = [eng.submit(p, max_new_tokens=heavy_new, top_k=0,
                            tenant_id='heavy') for p in heavy]
        for _ in range(3):
            eng.step()              # heavy saturates the slots first
        lreqs = [eng.submit(p, max_new_tokens=light_new, top_k=0,
                            tenant_id=f'light{i % 3}')
                 for i, p in enumerate(light)]
        while eng.scheduler.has_work:
            eng.step()
        dt = time.time() - t0
        st = eng.stats()
        table = eng.request_table()
        gen = sum(len(r.generated) for r in hreqs + lreqs)
        rec = {
            'wall_s': round(dt, 3),
            'tokens_per_sec': gen / dt,
            'decode_tokens_per_sec': st['decode_tokens_per_sec'],
            'preemptions': st['preemptions_total'],
            'quota_deferrals': st['quota_deferrals_total'],
            'preemptions_charged': st['preemptions_charged_total'],
            'light': _slo_pcts(table, 'light'),
            'heavy': _slo_pcts(table, 'heavy'),
            'per_tenant': {
                tid: {k: row.get(k) for k in
                      ('priority', 'submitted', 'completed',
                       'quota_deferrals', 'preemptions_charged',
                       'charge_tokens', 'tokens_billed')}
                for tid, row in
                st['tenancy'].get('tenants', {}).items()},
            'ladder': {
                'stage_transitions':
                    st['tenancy'].get('stage_transitions', 0),
                'final_stage': st['degrade_stage'],
                'timeline': [
                    {'to': h['to'], 'from': h['from'],
                     'pressure': h['pressure']}
                    for h in eng.ladder_history()],
                'max_stage': max(
                    [h['to'] for h in eng.ladder_history()] or [0]),
            },
            # goodput account (ISSUE 17): delivered/wasted identity +
            # the per-tenant split (who paid for the preempt churn)
            'goodput': eng.ledger.goodput(),
        }
        outs = [r.output_ids() for r in hreqs + lreqs]
        eng.shutdown()
        return rec, outs

    # SOLO baseline for the light tenants: their stream alone
    solo = _mk_engine(None)
    t0 = time.time()
    sreqs = [solo.submit(p, max_new_tokens=light_new, top_k=0,
                         tenant_id=f'light{i % 3}')
             for i, p in enumerate(light)]
    while solo.scheduler.has_work:
        solo.step()
    solo_p99 = percentile_of(
        [r.finish_time - r.submit_time for r in sreqs], 99)
    solo.shutdown()

    fcfs_rec, fcfs_outs = _run(None)
    # the heavy quota BILLS every admit (tokens_billed lands in the
    # record) but is sized not to bind on this stream: a binding quota
    # deliberately idles decode slots (rate limiting), which would
    # measure the quota policy, not the scheduler's work conservation
    # — the aggregate-throughput bar compares schedulers. Binding-
    # quota deferral behavior is covered in tests/test_serving_tenants.
    heavy_bill = heavy_n * (heavy_len + heavy_new)
    tenants = {'heavy': {'priority': 0,
                         'quota_tokens_per_s': float(heavy_bill),
                         'burst_tokens': float(heavy_bill),
                         'weight': 0.2},
               'light0': {'priority': 1, 'weight': 1.0},
               'light1': {'priority': 1, 'weight': 1.0},
               'light2': {'priority': 1, 'weight': 1.0}}
    slo_rec, slo_outs = _run(tenants)
    slo_light_p99 = (slo_rec['light']['e2e_ms']['p99'] or 0.0) / 1000.0
    return {
        'scheduler_comparison': {'fcfs': fcfs_rec, 'slo': slo_rec},
        'heavy_requests': heavy_n,
        'light_requests': light_n,
        'decode_slots': batch,
        'page_size': page_size,
        'solo_light_p99_e2e_ms': (round(solo_p99 * 1000.0, 3)
                                  if solo_p99 is not None else None),
        'light_p99_vs_solo':
            (slo_light_p99 / solo_p99 if solo_p99 else None),
        'aggregate_decode_vs_fcfs':
            (slo_rec['decode_tokens_per_sec']
             / fcfs_rec['decode_tokens_per_sec']
             if fcfs_rec['decode_tokens_per_sec'] else None),
        'light_p99_fcfs_over_slo':
            ((fcfs_rec['light']['e2e_ms']['p99'] or 0)
             / (slo_rec['light']['e2e_ms']['p99'] or 1)),
        # greedy tokens are scheduler-invariant: same stream, same
        # outputs per request, under FCFS and the SLO scheduler
        'outputs_identical_fcfs_vs_slo': fcfs_outs == slo_outs,
        'backend': jax.default_backend(),
    }


def _retry(fn, attempts=3):
    """The tunneled chip's remote-compile channel occasionally drops a
    response mid-read (transient 'response body closed' /
    'read body' JaxRuntimeError); retry so one hiccup doesn't blank a
    config's numbers in the round record."""
    last = None
    for i in range(attempts):
        try:
            return fn()
        except Exception as e:           # noqa: BLE001
            last = e
            transient = any(tok in repr(e) for tok in (
                'remote_compile', 'read body', 'response body',
                'UNAVAILABLE', 'DEADLINE'))
            if not transient or i == attempts - 1:
                raise
            time.sleep(5 * (i + 1))
    raise last


# ---------------------------------------------------------------------------
# leg orchestration — each leg runs in a FRESH subprocess (r5 regression:
# one process accumulated every leg's device state until RESOURCE_EXHAUSTED
# blanked 4 of 5 BASELINE configs; a leg now gets a clean XLA client and
# its engines are shut down before it reports)
# ---------------------------------------------------------------------------
LEGS = {
    'gpt_adamw': lambda: bench_gpt_1p3b('adamw'),
    'gpt_sgd': lambda: bench_gpt_1p3b('sgd'),
    'bert_base_zero2_bf16': bench_bert_config3,
    'lenet_mnist': bench_lenet_config1,
    'resnet50_dp_bf16': bench_resnet50_config2,
    'deepfm_ps': bench_deepfm_ps_config5,
    'ps_scale_ssd': bench_ps_scale,
    'gpt_serve_throughput': bench_gpt_serve,
    'gpt_serve_cluster': bench_gpt_serve_cluster,
    'gpt_serve_tenants': bench_gpt_serve_tenants,
}

_LEG_SENTINEL = 'LEG_RESULT:'


def _attach_telemetry(r):
    """Per-leg compile/device-memory telemetry (each leg is its own
    process now, so the numbers are leg-scoped, not accumulated).
    With BENCH_NUMERICS=1 the numerics sub-dict carries real grad-norm
    and nonfinite-count numbers (stat taps add one host sync per step,
    so the flag is off for headline measurements)."""
    try:
        from paddle_tpu.profiler import StepTelemetry
        snap = StepTelemetry(publish=False).snapshot()
        numerics = snap.get('numerics') or {}
        r['telemetry'] = {
            'compile_seconds_total': round(snap['compile_seconds_total'],
                                           2),
            'compiles_total': int(snap['compiles_total']),
            'device_memory': snap['device_memory'],
            'numerics': {
                'grad_norm_global': numerics.get('grad_norm_global'),
                'nonfinite_total': numerics.get('nonfinite_total'),
                'nonfinite_steps': numerics.get('nonfinite_steps'),
                'amp_skipped_steps': numerics.get('amp_skipped_steps'),
            },
            # gradient-comm model from the bucketed engines + persistent
            # compile cache (docs/performance.md) — the ISSUE 4
            # comm-bytes-drop acceptance number lives under
            # comm.comm_bytes_drop_vs_per_param_psum
            'comm': snap.get('comm'),
            # overlap schedule view (ISSUE 10): exposed vs hidden comm
            # seconds, groups/prefetch/chunk — also inside comm, but
            # surfaced top-level so the legs contract can assert it
            'comm_overlap': (snap.get('comm') or {}).get(
                'comm_overlap'),
            'compile_cache': snap.get('compile_cache'),
            # ptpu_serve_* view — only the serving leg publishes these
            'serve': snap.get('serve'),
            # fused-primitive routing counters (ISSUE 8)
            'pallas': snap.get('pallas'),
            # tuned-remat view (ISSUE 12): active policy per engine,
            # boundary-tag counts, per-site activation bytes
            'remat': snap.get('remat'),
            # async-dispatch view (ISSUE 13): per-site host gap/depth +
            # DeviceLoader prefetch totals
            'host': snap.get('host'),
            # pipeline schedule census (ISSUE 14): active schedule /
            # virtual stages / modeled bubble fraction
            'pipeline': snap.get('pipeline'),
            # step-time ledger (ISSUE 16): reconciled wall decomposition
            # + model/hardware TFLOP/s + MFU per engine
            'ledger': snap.get('ledger'),
        }
    except Exception as e:
        r['telemetry'] = {'error': repr(e)[:200]}
    try:
        # per-leg memory census: per-phase high-water marks + live-buffer
        # walk — the optimizer-state-sharding savings show up here
        from paddle_tpu.core import memory as _mem
        acct = _mem.accountant()
        r['memory'] = {
            'sample': acct.sample(count_buffers=True),
            'phases': {k: {f: v.get(f) for f in
                           ('high_water', 'max_delta', 'calls')}
                       for k, v in acct.phases().items()},
        }
    except Exception as e:
        r['memory'] = {'error': repr(e)[:200]}
    return r


def run_leg(name):
    """Child entry: run one leg, print its JSON on a sentinel line."""
    if os.environ.get('BENCH_NUMERICS') == '1':
        # opt-in: thread numerics taps through the leg's compiled steps
        # so the record carries per-leg grad-norm / nonfinite telemetry
        from paddle_tpu.core import flags as _flags
        _flags.set_flags({'FLAGS_tensor_stats': True})
    r = _attach_telemetry(_retry(LEGS[name]))
    print(_LEG_SENTINEL + json.dumps(r), flush=True)


def _leg_in_subprocess(name, timeout=5400, attempts=3):
    """Run one leg in a fresh subprocess so it gets a clean XLA client.

    The TPU runtime can lag a beat releasing the chip after the
    PREVIOUS leg's process exits (the r5 regression's tail: every leg
    after the first died RESOURCE_EXHAUSTED even though each had its
    own process) — so a leg whose child bombs with a resource error is
    re-spawned after a backoff instead of being written off."""
    import subprocess
    last_tail = ''
    for i in range(attempts):
        p = subprocess.run(
            [sys.executable, '-u', os.path.abspath(__file__),
             '--leg', name],
            capture_output=True, text=True, timeout=timeout)
        for line in reversed((p.stdout or '').splitlines()):
            if line.startswith(_LEG_SENTINEL):
                r = json.loads(line[len(_LEG_SENTINEL):])
                if isinstance(r, dict):
                    r['attempts'] = i + 1
                return r
        last_tail = ((p.stdout or '') + (p.stderr or ''))[-400:]
        transient = any(tok in last_tail for tok in (
            'RESOURCE_EXHAUSTED', 'ResourceExhausted', 'UNAVAILABLE',
            'DEADLINE'))
        if transient and i < attempts - 1:
            time.sleep(15 * (i + 1))    # let the runtime release the chip
            continue
        break
    raise RuntimeError(
        f"bench leg {name} produced no result (rc={p.returncode}): "
        f"{last_tail}")


# the top-level legs every round record must carry (r5 regression +
# the ISSUE 10 self-check: the r05 record buried satellite results —
# and their errors — inside the headline leg's detail dict)
EXPECTED_LEGS = ('gpt1.3b_adamw', 'gpt1.3b_sgd', 'bert_base_zero2_bf16',
                 'lenet_mnist', 'resnet50_dp_bf16', 'deepfm_ps',
                 'ps_scale_ssd', 'gpt_serve_throughput',
                 'gpt_serve_cluster', 'gpt_serve_tenants')


def _check_legs(result):
    """Leg self-check (ISSUE 10): every result lands TOP-level under
    result.legs — never nested under another leg's detail — and the
    headline leg carries telemetry.comm_overlap. Raises on violation
    so a regressed record shape fails the round loudly instead of
    silently burying legs again."""
    legs = result.get('legs')
    assert isinstance(legs, dict), 'result.legs missing'
    missing = [k for k in EXPECTED_LEGS if k not in legs]
    assert not missing, f'legs missing from result.legs: {missing}'

    def _no_nested_legs(d, path):
        for k, v in d.items():
            assert k != 'legs', \
                f'leg buried under {"/".join(path)}/legs'
            if isinstance(v, dict):
                _no_nested_legs(v, path + (k,))

    for name, leg in legs.items():
        assert isinstance(leg, dict), f'leg {name} is not a dict'
        _no_nested_legs(leg, (name,))
    detail = result.get('detail')
    if isinstance(detail, dict):
        _no_nested_legs(detail, ('detail',))
    # headline telemetry carries the overlap view (dryrun twin asserts
    # exposed < total; at dp=1 the gauges report the modeled schedule
    # with enabled=false — presence is the contract here). A telemetry
    # collection error is its own visible record, not a shape bug.
    tel = legs['gpt1.3b_adamw'].get('telemetry') or {}
    assert 'comm_overlap' in tel or 'error' in tel, \
        'headline leg telemetry lacks comm_overlap'
    # the activation-economy view (ISSUE 12): the headline leg must
    # carry the remat record (policy + boundary counts + census) both
    # in detail and in telemetry
    assert 'remat' in tel or 'error' in tel, \
        'headline leg telemetry lacks remat'
    assert 'remat' in legs['gpt1.3b_adamw'] or 'error' in \
        legs['gpt1.3b_adamw'], 'headline leg lacks the remat record'
    # the pipeline-schedule record shape (ISSUE 14): any leg or detail
    # carrying a `pipeline` record — the schedule census bench legs and
    # telemetry attach — must look like schedule_model()/
    # pipeline_snapshot() output, so a future pipeline leg is validated
    # like the host/remat records
    def _check_pipeline_record(rec, where):
        assert isinstance(rec, dict), \
            f'{where}: pipeline record is not a dict'
        for key in ('schedule', 'virtual_stages', 'accumulate_steps',
                    'ticks', 'chunk_ticks', 'bubble_fraction'):
            assert key in rec, f'{where}: pipeline record lacks {key}'
        assert rec['schedule'] in ('1F1B', 'F-then-B', 'interleaved'), \
            f"{where}: unknown schedule {rec['schedule']!r}"
        assert 0.0 <= rec['bubble_fraction'] < 1.0, \
            f"{where}: bubble_fraction out of range"
        assert int(rec['virtual_stages']) >= 1, where

    for name, leg in legs.items():
        for holder, where in ((leg, f'legs.{name}'),
                              (leg.get('telemetry') or {},
                               f'legs.{name}.telemetry'),
                              (leg.get('detail') or {},
                               f'legs.{name}.detail')):
            rec = holder.get('pipeline') if isinstance(holder, dict) \
                else None
            if rec is not None:
                _check_pipeline_record(rec, where)
    if isinstance(detail, dict) and detail.get('pipeline') is not None:
        _check_pipeline_record(detail['pipeline'], 'detail')
    # the multi-tenant serving view (ISSUE 15): the tenants leg must
    # carry both scheduler runs, the acceptance ratios, and the
    # ladder timeline; the cluster leg must carry the retry-hint
    # accuracy record the structured RouterRejected satellite added
    tleg = legs.get('gpt_serve_tenants') or {}
    if 'error' not in tleg:
        cmp_ = tleg.get('scheduler_comparison')
        assert isinstance(cmp_, dict) and 'fcfs' in cmp_ \
            and 'slo' in cmp_, 'tenants leg lacks scheduler_comparison'
        for side in ('fcfs', 'slo'):
            for key in ('decode_tokens_per_sec', 'light', 'heavy',
                        'ladder', 'per_tenant'):
                assert key in cmp_[side], \
                    f'tenants leg {side} record lacks {key}'
        assert 'light_p99_vs_solo' in tleg \
            and 'aggregate_decode_vs_fcfs' in tleg, \
            'tenants leg lacks the acceptance ratios'
        assert 'timeline' in cmp_['slo']['ladder'], \
            'tenants leg lacks the ladder timeline'
        assert tleg.get('outputs_identical_fcfs_vs_slo') is True, \
            'SLO scheduler changed greedy outputs'
    cleg = legs.get('gpt_serve_cluster') or {}
    if 'error' not in cleg:
        assert 'retry_hint' in cleg, \
            'cluster leg lacks the retry-hint accuracy record'
    # the async-dispatch view (ISSUE 13): the headline leg must carry
    # detail.host with the dispatch window, prefetch depth, and the
    # sync-vs-windowed host-gap comparison incl. host_bound_fraction
    headline = legs['gpt1.3b_adamw']
    if 'error' not in headline:
        hostrec = headline.get('host')
        assert isinstance(hostrec, dict), 'headline leg lacks detail.host'
        assert 'dispatch_window' in hostrec and 'prefetch' in hostrec, \
            'detail.host lacks window/prefetch knobs'
        assert 'host_bound_fraction' in (hostrec.get('windowed') or {}), \
            'detail.host.windowed lacks host_bound_fraction'
        assert 'sync_loop' in hostrec, \
            'detail.host lacks the sync_loop comparison record'
    # the step-time ledger (ISSUE 16): the headline leg must carry the
    # reconciled decomposition — components sum to within 10% of the
    # measured wall (residue is one of them, surfaced separately) —
    # and the model-TFLOP/s account with the remat recompute factor
    if 'error' not in headline:
        led = headline.get('ledger')
        assert isinstance(led, dict), 'headline leg lacks detail.ledger'
        comps = led.get('components')
        assert isinstance(comps, dict), 'detail.ledger lacks components'
        for key in ('compute', 'exposed_comm', 'bubble', 'host_gap',
                    'residue'):
            assert key in comps, f'detail.ledger.components lacks {key}'
        wall = led.get('wall_seconds') or 0.0
        assert wall > 0.0, 'detail.ledger lacks wall_seconds'
        total = sum(comps.values())
        assert abs(total - wall) <= 0.10 * wall, \
            f'ledger components sum {total:.6f}s vs wall {wall:.6f}s ' \
            f'(off by more than 10%)'
        assert 'model_tflops' in led, 'detail.ledger lacks model_tflops'
        assert 'recompute_factor' in (led.get('flops') or {}), \
            'detail.ledger lacks the remat recompute factor'
        assert 'ledger' in (headline.get('telemetry') or {}) \
            or 'error' in (headline.get('telemetry') or {}), \
            'headline leg telemetry lacks ledger'
    # the serving goodput ledger (ISSUE 17): the throughput leg must
    # carry the reconciled serve-step decomposition — five components
    # summing to within 10% of the measured iteration wall (residue
    # surfaced, never hidden) — a real host_bound_fraction, and the
    # goodput account whose identity holds exactly
    sleg = legs.get('gpt_serve_throughput') or {}
    if 'error' not in sleg:
        sled = sleg.get('ledger')
        assert isinstance(sled, dict), 'serve leg lacks ledger'
        scomps = sled.get('components')
        assert isinstance(scomps, dict), 'serve ledger lacks components'
        for key in ('compute', 'host_fetch', 'schedule', 'page_stream',
                    'residue'):
            assert key in scomps, f'serve ledger components lack {key}'
        swall = sled.get('wall_seconds') or 0.0
        assert swall > 0.0, 'serve ledger lacks wall_seconds'
        stotal = sum(scomps.values())
        assert abs(stotal - swall) <= 0.10 * swall, \
            f'serve ledger components sum {stotal:.6f}s vs wall ' \
            f'{swall:.6f}s (off by more than 10%)'
        assert sled.get('host_bound_fraction') is not None, \
            'serve ledger lacks host_bound_fraction'
        sgp = sleg.get('goodput')
        assert isinstance(sgp, dict), 'serve leg lacks goodput'
        assert sgp['delivered_tokens'] + sgp['wasted_tokens'] \
            == sgp['emitted_tokens'], \
            'serve goodput identity broken (delivered + wasted != emitted)'
        sroof = sleg.get('roofline')
        assert isinstance(sroof, dict), 'serve leg lacks roofline'
        assert 'decode_bytes_per_iteration' in sroof, \
            'serve roofline lacks decode_bytes_per_iteration'
        # fused decode windows (ISSUE 19): the small-batch record —
        # the same stream at fused k in {1, 4, 8}, token-identical,
        # with decode tok/s and host_bound_fraction side by side, and
        # the k>1 legs actually fusing
        sb = sleg.get('small_batch')
        assert isinstance(sb, dict), 'serve leg lacks small_batch'
        assert sb.get('outputs_identical') is True, \
            'small_batch outputs differ across fused k'
        per_k = sb.get('per_k')
        assert isinstance(per_k, dict) and set(per_k) == {'1', '4',
                                                          '8'}, \
            'small_batch.per_k must carry k in {1, 4, 8}'
        for k, r in per_k.items():
            for key in ('decode_tokens_per_sec', 'host_bound_fraction',
                        'fused_windows', 'fused_iterations',
                        'fused_tokens', 'decode_steps'):
                assert key in r, f'small_batch.per_k[{k}] lacks {key}'
            if k == '1':
                assert r['fused_windows'] == 0, \
                    'per-token leg reported fused windows'
            else:
                assert r['fused_windows'] > 0, \
                    f'fused k={k} leg never fused'
                assert r['fused_tokens'] <= r['fused_iterations'] \
                    * sb['decode_slots'], \
                    f'small_batch k={k} token overcount'
        assert isinstance(
            sleg.get('small_batch_decode_tokens_per_sec'),
            (int, float)), 'serve leg lacks flat small-batch tok/s'
        assert isinstance(sleg.get('fused_speedup_vs_per_token'),
                          (int, float)), \
            'serve leg lacks fused_speedup_vs_per_token'
        # tiered KV cache (ISSUE 20): the oversubscribed record — a
        # device pool below its concurrent contexts with the host tier
        # underneath, token-identical to the sized-to-fit run, with
        # real spill traffic and resurrect TTFT beating recompute
        ov = sleg.get('oversubscribed')
        assert isinstance(ov, dict), 'serve leg lacks oversubscribed'
        assert ov.get('outputs_identical') is True, \
            'oversubscribed outputs differ from sized-to-fit'
        assert ov.get('resurrect_outputs_identical') is True, \
            'resurrected stream outputs differ'
        assert ov.get('oversubscription', 0) > 1.0, \
            'oversubscribed leg did not oversubscribe the pool'
        tr = ov.get('tiered')
        assert isinstance(tr, dict), 'oversubscribed lacks tiered rec'
        for key in ('device_pages', 'host_pages', 'tokens_per_sec',
                    'decode_tokens_per_sec', 'slo', 'spilled_pages',
                    'spilled_bytes', 'fetched_pages', 'fetched_bytes',
                    'resurrected_pages'):
            assert key in tr, f'oversubscribed.tiered lacks {key}'
        assert tr['spilled_pages'] > 0, \
            'oversubscribed leg never spilled to the host tier'
        assert isinstance(ov.get('resurrect_ttft_ms'), (int, float)) \
            and isinstance(ov.get('recompute_ttft_ms'), (int, float)), \
            'oversubscribed lacks the TTFT pair'
        assert ov['resurrect_ttft_ms'] < ov['recompute_ttft_ms'], \
            'resurrect-from-host TTFT did not beat recompute ' \
            f"({ov['resurrect_ttft_ms']}ms vs " \
            f"{ov['recompute_ttft_ms']}ms)"
        assert isinstance(
            sleg.get('oversubscribed_decode_tokens_per_sec'),
            (int, float)), 'serve leg lacks flat oversubscribed tok/s'
    # the telemetry time axis (ISSUE 18): the headline and serve legs
    # carry the downsampled history-ring block + the alert summary, and
    # a clean leg must not have fired a critical rule — an alert there
    # is a real regression (pool saturation, degrade ladder, dead
    # publish cadence), not record noise
    for name in ('gpt1.3b_adamw', 'gpt_serve_throughput'):
        leg = legs.get(name) or {}
        if 'error' in leg:
            continue
        arec = leg.get('alerts')
        assert isinstance(arec, dict), f'{name} leg lacks alerts summary'
        for key in ('rules', 'evals', 'fired_total', 'fired_critical',
                    'active'):
            assert key in arec, f'{name} leg alerts summary lacks {key}'
        assert arec['fired_critical'] == 0, \
            f"{name}: critical alert fired on a clean leg " \
            f"({arec['fired_by_severity']}, active={arec['active']})"
        srec = leg.get('series')
        assert isinstance(srec, dict) and srec, \
            f'{name} leg lacks the history-ring series block'
        for sk, sv in srec.items():
            assert 't' in sv and 'v' in sv and len(sv['t']) == \
                len(sv['v']), f'{name}.series.{sk} torn'

    def _check_goodput_identity(gp, where):
        if not isinstance(gp, dict):
            return
        assert gp['delivered_tokens'] + gp['wasted_tokens'] \
            == gp['emitted_tokens'], \
            f'{where}: goodput identity broken'

    if 'error' not in cleg:
        _check_goodput_identity(cleg.get('cluster_goodput'),
                                'cluster leg')
    if 'error' not in tleg:
        for side in ('fcfs', 'slo'):
            _check_goodput_identity(
                (tleg.get('scheduler_comparison') or {})
                .get(side, {}).get('goodput'), f'tenants leg {side}')
    # record stamps (ISSUE 16): schema version + round id at top level
    assert result.get('schema_version'), 'result lacks schema_version'
    assert result.get('round'), 'result lacks round id'
    return True


def _round_floats(r, ndigits=2):
    if isinstance(r, float):
        return round(r, ndigits)
    if isinstance(r, dict):
        return {k: _round_floats(v, ndigits) for k, v in r.items()}
    if isinstance(r, list):
        return [_round_floats(v, ndigits) for v in r]
    return r


def main():
    # BENCH_INPROC=1 keeps the legacy single-process mode (debugging)
    inproc = os.environ.get('BENCH_INPROC') == '1'

    def run(name):
        if inproc:
            return _attach_telemetry(_retry(LEGS[name]))
        return _leg_in_subprocess(name)

    g = run('gpt_adamw')
    detail = {
        'ms_per_step': round(g['ms_per_step'], 1),
        'tokens_per_sec': round(g['tokens_per_sec'], 1),
        'tflops': round(g['tflops'], 2),
        'params': g['params'],
        'seq_len': g['seq_len'],
        'microbatches': g['microbatches'],
        'optimizer': 'adamw_bf16_moments',
        # ISSUE 13: async step pipeline — dispatch window/prefetch depth
        # + host-gap before (sync_loop) vs after (windowed) + the
        # host_bound_fraction BENCH_r06 reads (health_dump host)
        'host': g.get('host'),
        # ISSUE 16: the reconciled step-wall ledger + MFU account
        # (bench_compare renders two rounds of these side by side)
        'ledger': g.get('ledger'),
        # ISSUE 8: which fused Pallas primitives were active in the
        # headline step (health_dump pallas renders this)
        'fused_primitives': g.get('fused_primitives'),
        'live_buffers_after_shutdown':
            g.get('live_buffers_after_shutdown'),
        'live_bytes_after_shutdown': g.get('live_bytes_after_shutdown'),
        'memory': g.get('memory'),
    }
    # every leg reports at TOP level (result.legs.<name>), errors
    # included — the r5 record buried the satellite legs (and their
    # RESOURCE_EXHAUSTED errors) inside the headline leg's detail dict
    legs = {'gpt1.3b_adamw': dict(detail)}
    for key, src in (
            ('gpt1.3b_sgd', 'gpt_sgd'),
            ('bert_base_zero2_bf16', 'bert_base_zero2_bf16'),
            ('lenet_mnist', 'lenet_mnist'),
            ('resnet50_dp_bf16', 'resnet50_dp_bf16'),
            ('deepfm_ps', 'deepfm_ps'),
            ('ps_scale_ssd', 'ps_scale_ssd'),
            ('gpt_serve_throughput', 'gpt_serve_throughput'),
            ('gpt_serve_cluster', 'gpt_serve_cluster'),
            ('gpt_serve_tenants', 'gpt_serve_tenants'),
    ):
        try:
            r = run(src)
            if src == 'gpt_sgd':
                r = {k: r[k] for k in ('mfu', 'ms_per_step',
                                       'tokens_per_sec', 'memory')
                     if k in r}
            elif src == 'bert_base_zero2_bf16':
                r = {k: r[k] for k in ('samples_per_sec', 'ms_per_step',
                                       'mfu', 'memory', 'host')
                     if k in r}
            elif src == 'gpt_serve_throughput':
                # serving telemetry rides with its own leg's child
                r.setdefault('telemetry_serve',
                             (r.pop('telemetry', None) or {}).get(
                                 'serve'))
                r.pop('memory', None)
            legs[key] = _round_floats(
                r, 4 if src in ('gpt_sgd', 'bert_base_zero2_bf16',
                                'gpt_serve_throughput',
                                'gpt_serve_cluster',
                                'gpt_serve_tenants') else 2)
        except Exception as e:       # headline must still print
            legs[key] = {'error': repr(e)[:200]}
    # per-leg compile/memory telemetry comes from the headline child
    # (each leg is its own process — no cross-leg accumulation)
    detail['telemetry'] = g.get('telemetry', {})
    # the legs snapshot was taken before telemetry landed in detail —
    # the top-level contract says every leg carries its own
    legs['gpt1.3b_adamw']['telemetry'] = detail['telemetry']
    result = {
        # record contract (ISSUE 16): schema_version gates what
        # bench_compare may assume about the shape; round identifies
        # the bench round without relying on the artifact filename
        'schema_version': BENCH_SCHEMA_VERSION,
        'round': os.environ.get('BENCH_ROUND') or _next_round_id(),
        'metric': 'gpt1.3b_adamw_trainstep_mfu',
        'value': round(g['mfu'], 4),
        'unit': 'fraction_of_v5e_peak',
        'vs_baseline': round(g['mfu'] / TARGET_MFU, 4),
        'legs': legs,
        'detail': detail,
    }
    _check_legs(result)
    print(json.dumps(result))


if __name__ == '__main__':
    if len(sys.argv) >= 3 and sys.argv[1] == '--leg':
        run_leg(sys.argv[2])
    else:
        main()
