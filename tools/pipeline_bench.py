"""Pipeline schedule overhead measurement (VERDICT r2 #10 / r3 #1 evidence;
ISSUE 14 interleaved legs).

Runs the SAME model through the SPMD pipeline schedules at pp=4 on the
virtual 8-device CPU mesh and reports steady-state step times, per-tick
steady-state times, and the static schedule model's bubble fraction
(docs/performance.md#pipeline-schedules).

Schedules measured per scale:
  * 1F1B (activation-stashing; section_worker.cc:147-184 parity) — the
    v=1 baseline: T = A + 2*(pp-1) ticks, every masked warm-up/drain
    tick burns a FULL stage's fwd+bwd.
  * 1F1B recompute memory mode (stage-input buffer only, +1 fwd FLOPs).
  * F-then-B (scan transposition, O(A) boundary activations).
  * interleaved v=2 / v=... (arXiv:2104.04473): each stage holds v
    round-robin model chunks, so a masked tick burns 1/v of a stage —
    modeled bubble_fraction drops from (pp-1)/(A+pp-1) to
    (pp-1)/(A*v+pp-1) at iso (pp, A), at ~v x ppermute boundary
    crossings. The sweep records the model beside the measured
    ms_per_step/ms_per_tick so the shrink is a recorded number.

The A sweep (schedule x v x A) runs on the small scale where the extra
compiles are cheap; 'small' (hidden=128) is dispatch-bound on CPU,
'big' (hidden=512) is compute-bound — the regime a real TPU slice runs
in, where the FLOP accounting dominates.

Usage: python tools/pipeline_bench.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                          # noqa: E402
import __graft_entry__ as _graft                            # noqa: E402

# same virtual-CPU forcing the driver's dryrun uses (handles the axon
# plugin force-registering the tunneled chip)
_graft._ensure_virtual_devices(8)


def measure(schedule, memory_mode='stash', pp=4, A=8, steps=3, big=True,
            virtual_stages=None):
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        SpmdPipelineEngine)
    import paddle_tpu.distributed.fleet as fleet_mod
    fleet_mod.fleet._hcg = None

    paddle.seed(0)
    topology_runtime.build_mesh(['dp', 'pp'], [1, pp])
    if big:
        cfg = GPTConfig(vocab_size=512, hidden_size=512, num_layers=8,
                        num_heads=8, max_seq_len=256, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        L, mb = 256, 2
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=8,
                        num_heads=4, max_seq_len=128, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        L, mb = 128, 1
    embed, blocks, head = build_gpt_pipeline(cfg)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[])
    eng = SpmdPipelineEngine(embed, blocks, head, opt,
                             accumulate_steps=A, use_remat=True,
                             schedule=schedule, memory_mode=memory_mode,
                             virtual_stages=virtual_stages)
    model = eng._sched_model       # the engine's own schedule census
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (A * mb, L)).astype('int32')
    labels = np.roll(ids, -1, 1).astype('int32')
    data = (Tensor(ids), Tensor(labels))
    loss = eng.train_batch(data)       # compile
    float(loss)
    t0 = time.time()
    for _ in range(steps):
        loss = eng.train_batch(data)
    float(loss)
    ms = (time.time() - t0) / steps * 1000
    eng.shutdown()
    return {'ms_per_step': round(ms, 1),
            'ms_per_tick': round(ms / model['ticks'], 3),
            'loss': round(float(loss), 4),
            'pipeline': model}


def main():
    r = {}
    for scale, big in (('big', True), ('small', False)):
        sec = {}
        legs = [('1F1B', '1F1B', 'stash', None),
                ('1F1B_recompute', '1F1B', 'recompute', None),
                ('F-then-B', 'F-then-B', 'stash', None),
                ('interleaved_v2', 'interleaved', 'stash', 2)]
        if not big:
            legs.append(
                ('interleaved_v2_recompute', 'interleaved', 'recompute',
                 2))
        for name, sched, mode, v in legs:
            sec[name] = measure(sched, memory_mode=mode, big=big,
                                steps=3 if big else 5, virtual_stages=v)
        sec['ratio_1f1b_over_fthenb'] = round(
            sec['1F1B']['ms_per_step'] / sec['F-then-B']['ms_per_step'], 3)
        sec['ratio_recompute_over_fthenb'] = round(
            sec['1F1B_recompute']['ms_per_step']
            / sec['F-then-B']['ms_per_step'], 3)
        sec['ratio_interleaved_v2_over_1f1b'] = round(
            sec['interleaved_v2']['ms_per_step']
            / sec['1F1B']['ms_per_step'], 3)
        sec['bubble_drop_v2_vs_v1'] = round(
            sec['1F1B']['pipeline']['bubble_fraction']
            - sec['interleaved_v2']['pipeline']['bubble_fraction'], 4)
        r[scale] = sec
    # schedule x v x A sweep (model + steady per-tick time) on the
    # cheap scale: the modeled bubble must shrink monotonically in v at
    # iso (pp, A) and in A at iso v
    sweep = []
    for A in (8, 16):
        for sched, v in (('1F1B', None), ('interleaved', 2)):
            m = measure(sched, A=A, big=False, steps=3,
                        virtual_stages=v)
            sweep.append({'schedule': m['pipeline']['schedule'],
                          'virtual_stages': m['pipeline']
                          ['virtual_stages'],
                          'A': A,
                          'ms_per_step': m['ms_per_step'],
                          'ms_per_tick': m['ms_per_tick'],
                          'bubble_fraction': round(
                              m['pipeline']['bubble_fraction'], 4)})
    r['sweep'] = sweep
    r['note'] = ('stash-1F1B = SectionWorker store-activations schedule: '
                 'A+pp-1 fwd + A+pp-1 bwd (same totals as F-then-B, '
                 'save-dots backward), O(pp) in-flight window; '
                 'interleaved_v2 = Megatron virtual stages: masked ticks '
                 'cost 1/v stage, modeled bubble (pp-1)/(A*v+pp-1), '
                 '~v x ppermute crossings '
                 '(docs/performance.md#pipeline-schedules)')
    print(json.dumps(r))


if __name__ == '__main__':
    main()
