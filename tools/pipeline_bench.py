"""Pipeline schedule overhead measurement (VERDICT r2 #10 evidence).

Runs the SAME model through the 1F1B and F-then-B SPMD schedules at pp=4
on the virtual 8-device CPU mesh and reports steady-state step times plus
the analytic FLOPs note: this 1F1B recomputes each stage's forward from
the saved input inside its backward tick (jax.vjp from x_saved —
spmd_pipeline.py tick()), so its stage FLOPs are fwd + (fwd + bwd) ≈
1.5× an activation-stashing 1F1B (section_worker.cc:147-184 stores, does
not recompute); F-then-B here uses jax.checkpoint (same full-remat cost),
so the schedule comparison isolates schedule overhead, not remat policy.

Usage: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
       python tools/pipeline_bench.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                          # noqa: E402
import __graft_entry__ as _graft                            # noqa: E402

# same virtual-CPU forcing the driver's dryrun uses (handles the axon
# plugin force-registering the tunneled chip)
_graft._ensure_virtual_devices(8)


def measure(schedule, pp=4, A=8, steps=5):
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        SpmdPipelineEngine)
    import paddle_tpu.distributed.fleet as fleet_mod
    fleet_mod.fleet._hcg = None

    paddle.seed(0)
    topology_runtime.build_mesh(['dp', 'pp'], [1, pp])
    cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=8,
                    num_heads=4, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    embed, blocks, head = build_gpt_pipeline(cfg)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[])
    eng = SpmdPipelineEngine(embed, blocks, head, opt,
                             accumulate_steps=A, use_remat=True,
                             schedule=schedule)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (A, 128)).astype('int32')
    labels = np.roll(ids, -1, 1).astype('int32')
    data = (Tensor(ids), Tensor(labels))
    loss = eng.train_batch(data)       # compile
    float(loss)
    t0 = time.time()
    for _ in range(steps):
        loss = eng.train_batch(data)
    float(loss)
    return (time.time() - t0) / steps * 1000, float(loss)


def main():
    r = {}
    for sched in ('1F1B', 'F-then-B'):
        ms, loss = measure(sched)
        r[sched] = {'ms_per_step': round(ms, 1), 'loss': round(loss, 4)}
    r['ratio_1f1b_over_fthenb'] = round(
        r['1F1B']['ms_per_step'] / r['F-then-B']['ms_per_step'], 3)
    r['note'] = ('recompute-1F1B stage FLOPs ~1.5x activation-stashing '
                 '1F1B; in-flight window 2*pp-1 vs Megatron pp')
    print(json.dumps(r))


if __name__ == '__main__':
    main()
