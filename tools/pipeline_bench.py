"""Pipeline schedule overhead measurement (VERDICT r2 #10 / r3 #1 evidence).

Runs the SAME model through the 1F1B and F-then-B SPMD schedules at pp=4
on the virtual 8-device CPU mesh and reports steady-state step times.

The 1F1B default is activation-STASHING (section_worker.cc:147-184 parity:
SectionWorker stores each microbatch's forward activations and replays
backward from them): the forward sub-step runs under jax.vjp, the
pullback's tick-variant residual leaves ride a circular O(pp)-slot buffer,
and the warm-up/drain ticks cond-skip the absent sub-step — so total work
is A+pp-1 forwards + A+pp-1 backwards, exactly F-then-B's, with a
save-dots backward (cheaper than F-then-B's full-remat backward). The
legacy 'recompute' memory mode (backward re-runs the stage forward from
the saved stage input, fwd+(fwd+bwd) FLOPs) is measured for comparison.

Two model scales: 'small' (hidden=128, dispatch-bound on CPU — schedule
overhead shows up as per-tick op count) and 'big' (hidden=512,
compute-bound — the regime a real TPU slice runs in, where the FLOP
accounting dominates).

Usage: python tools/pipeline_bench.py
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np                                          # noqa: E402
import __graft_entry__ as _graft                            # noqa: E402

# same virtual-CPU forcing the driver's dryrun uses (handles the axon
# plugin force-registering the tunneled chip)
_graft._ensure_virtual_devices(8)


def measure(schedule, memory_mode='stash', pp=4, A=8, steps=3, big=True):
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        SpmdPipelineEngine)
    import paddle_tpu.distributed.fleet as fleet_mod
    fleet_mod.fleet._hcg = None

    paddle.seed(0)
    topology_runtime.build_mesh(['dp', 'pp'], [1, pp])
    if big:
        cfg = GPTConfig(vocab_size=512, hidden_size=512, num_layers=8,
                        num_heads=8, max_seq_len=256, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        L, mb = 256, 2
    else:
        cfg = GPTConfig(vocab_size=256, hidden_size=128, num_layers=8,
                        num_heads=4, max_seq_len=128, hidden_dropout=0.0,
                        attn_dropout=0.0, use_flash_attention=False)
        L, mb = 128, 1
    embed, blocks, head = build_gpt_pipeline(cfg)
    opt = paddle.optimizer.SGD(learning_rate=1e-3, parameters=[])
    eng = SpmdPipelineEngine(embed, blocks, head, opt,
                             accumulate_steps=A, use_remat=True,
                             schedule=schedule, memory_mode=memory_mode)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (A * mb, L)).astype('int32')
    labels = np.roll(ids, -1, 1).astype('int32')
    data = (Tensor(ids), Tensor(labels))
    loss = eng.train_batch(data)       # compile
    float(loss)
    t0 = time.time()
    for _ in range(steps):
        loss = eng.train_batch(data)
    float(loss)
    return (time.time() - t0) / steps * 1000, float(loss)


def main():
    r = {}
    for scale, big in (('big', True), ('small', False)):
        sec = {}
        for name, sched, mode in (('1F1B', '1F1B', 'stash'),
                                  ('1F1B_recompute', '1F1B', 'recompute'),
                                  ('F-then-B', 'F-then-B', 'stash')):
            ms, loss = measure(sched, memory_mode=mode, big=big,
                               steps=3 if big else 5)
            sec[name] = {'ms_per_step': round(ms, 1),
                         'loss': round(loss, 4)}
        sec['ratio_1f1b_over_fthenb'] = round(
            sec['1F1B']['ms_per_step'] / sec['F-then-B']['ms_per_step'], 3)
        sec['ratio_recompute_over_fthenb'] = round(
            sec['1F1B_recompute']['ms_per_step']
            / sec['F-then-B']['ms_per_step'], 3)
        r[scale] = sec
    r['note'] = ('stash-1F1B = SectionWorker store-activations schedule: '
                 'A+pp-1 fwd + A+pp-1 bwd (same totals as F-then-B, '
                 'save-dots backward), O(pp) in-flight window')
    print(json.dumps(r))


if __name__ == '__main__':
    main()
