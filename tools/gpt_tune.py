"""GPT-1.3B headline variants on one chip.

Usage: python tools/gpt_tune.py packed|bhld
(compare the packed transpose-free causal flash route vs the BHLD one
on the exact bench.py configuration).
"""
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np

V5E_PEAK_TFLOPS = 197.0


def run(variant='packed'):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core import flags
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.distributed import topology_runtime
    from paddle_tpu.models.gpt import GPTConfig, build_gpt_pipeline
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        SpmdPipelineEngine)
    import paddle_tpu.distributed.fleet as fm

    flags.set_flags({'FLAGS_flash_packed_causal': variant == 'packed'})
    fm.fleet._hcg = None
    topology_runtime.build_mesh(['dp', 'pp'], [1, 1])
    paddle.seed(0)
    L = 2048
    cfg = GPTConfig(vocab_size=50304, hidden_size=2048, num_layers=24,
                    num_heads=16, max_seq_len=L, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=True)
    embed, blocks, head = build_gpt_pipeline(cfg)
    layers = [embed, head] + blocks
    for layer in layers:
        for p in layer.parameters():
            if p.data.dtype == jnp.float32:
                p.data = p.data.astype(jnp.bfloat16)
    n_params = sum(int(np.prod(p.shape))
                   for layer in layers for p in layer.parameters())
    opt = paddle.optimizer.SGD(learning_rate=1e-4, parameters=[],
                               multi_precision=False)
    A, mb = 4, 2
    eng = SpmdPipelineEngine(embed, blocks, head, opt, accumulate_steps=A,
                             use_remat=True, schedule='1F1B',
                             grad_accum_dtype='param')
    for layer in layers:
        for p in layer.parameters():
            p._data = jnp.zeros((1,), jnp.bfloat16)
    rng = np.random.RandomState(0)
    ids = rng.randint(0, cfg.vocab_size, (A * mb, L)).astype('int32')
    data = (Tensor(ids), Tensor(np.roll(ids, -1, 1).astype('int32')))
    loss = eng.train_batch(data)
    assert np.isfinite(float(loss))
    n = 5
    dt = float('inf')
    for _ in range(3):
        t0 = time.time()
        for _ in range(n):
            loss = eng.train_batch(data)
        float(loss)
        dt = min(dt, (time.time() - t0) / n)
    tokens = A * mb * L
    flops = 6 * n_params * tokens + \
        12 * cfg.num_layers * cfg.hidden_size * L * tokens
    mfu = flops / dt / 1e12 / V5E_PEAK_TFLOPS
    print(f"{variant}: ms={dt*1000:.1f} mfu={mfu:.4f} "
          f"loss={float(loss):.4f}")
    return mfu


if __name__ == '__main__':
    run(sys.argv[1] if len(sys.argv) > 1 else 'packed')
