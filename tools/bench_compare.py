#!/usr/bin/env python
"""Cross-round bench regression tracking (ISSUE 16).

Load any two BENCH_r*.json artifacts, line their legs up, and emit
per-leg metric deltas with regression/improvement verdicts against a
relative threshold — plus the step-time ledger breakdown side by side
when either round carries one — so a bench round produces attributable
numbers instead of a flat headline.

    python tools/bench_compare.py BENCH_r04.json BENCH_r05.json
    python tools/bench_compare.py A.json B.json --json --threshold 0.05
    python tools/bench_compare.py --selftest

Record shapes handled:
  * the driver wrapper {n, cmd, rc, tail, parsed} (parsed is the bench
    record) or a bare bench.py stdout record;
  * schema v2 (ISSUE 16): top-level `legs` dict + schema_version/round
    stamps + the headline `detail.ledger` record;
  * legacy r04/r05 records: no `legs` — satellite legs nest inside
    `detail` beside the headline scalars (the normalizer lifts both
    into one legs dict, headline under 'gpt1.3b_adamw').

Verdicts: rel = (new - old) / old per metric; |rel| <= threshold is
'flat', beyond it the metric's direction (higher-is-better tok/s vs
lower-is-better ms) decides 'improvement' or 'regression'. With
--strict the process exits 1 when any regression is found.
"""
import argparse
import json
import os
import sys

# metric -> direction ('higher'|'lower' is better). Anything numeric
# and shared but unlisted is reported as 'info' (delta, no verdict).
METRIC_DIRECTION = {
    'mfu': 'higher',
    'tflops': 'higher',
    'tokens_per_sec': 'higher',
    'samples_per_sec': 'higher',
    'images_per_sec': 'higher',
    'steps_per_sec': 'higher',
    'decode_tokens_per_sec': 'higher',
    'requests_per_sec': 'higher',
    'build_rows_per_sec': 'higher',
    'pull_rows_per_sec': 'higher',
    'push_rows_per_sec': 'higher',
    'ms_per_step': 'lower',
    'pull_ms': 'lower',
    'push_ms': 'lower',
    'dense_ms': 'lower',
    'ttft_p50_ms': 'lower',
    'ttft_p99_ms': 'lower',
    'tpot_p50_ms': 'lower',
    'e2e_p99_ms': 'lower',
    # serving goodput ledger & decode roofline (ISSUE 17)
    'goodput_fraction': 'higher',
    'host_bound_fraction': 'lower',
    'hbm_gbps': 'higher',
    'mbu': 'higher',
    # fused decode windows (ISSUE 19): small-batch decode headline
    'small_batch_decode_tokens_per_sec': 'higher',
    'small_batch_host_bound_fraction': 'lower',
    'fused_speedup_vs_per_token': 'higher',
    # tiered KV cache (ISSUE 20): oversubscribed serving headline
    'oversubscribed_decode_tokens_per_sec': 'higher',
    'resurrect_ttft_speedup': 'higher',
}
DEFAULT_THRESHOLD = 0.02
HEADLINE_LEG = 'gpt1.3b_adamw'
SERVE_LEG = 'gpt_serve_throughput'

# legacy detail keys that are records riding with the headline, not
# satellite legs of their own
_NON_LEG_DETAIL = frozenset((
    'host', 'remat', 'ledger', 'memory', 'telemetry', 'pipeline',
    'fused_primitives', 'comm', 'comm_overlap'))


def load_record(path):
    """The bench record out of a driver artifact (or bare stdout)."""
    with open(path) as f:
        doc = json.load(f)
    rec = doc.get('parsed') if isinstance(doc, dict) and 'parsed' in doc \
        else doc
    if not isinstance(rec, dict) or 'metric' not in rec:
        raise ValueError(f'{path}: not a bench record (no metric)')
    return rec


def normalize(rec):
    """-> {round, schema_version, metric, value, legs, ledger}."""
    detail = rec.get('detail') or {}
    legs = rec.get('legs')
    if not isinstance(legs, dict):
        # legacy shape: satellite legs nest inside detail; the headline
        # scalars ARE detail. Lift both.
        legs = {}
        headline = {}
        for k, v in detail.items():
            if isinstance(v, dict) and k not in _NON_LEG_DETAIL:
                legs[k] = v
            elif isinstance(v, (int, float)) or k == 'optimizer':
                headline[k] = v
        if isinstance(rec.get('value'), (int, float)):
            headline.setdefault('mfu', rec['value'])
        legs[HEADLINE_LEG] = headline
    ledger = None
    head = legs.get(HEADLINE_LEG)
    if isinstance(head, dict) and isinstance(head.get('ledger'), dict):
        ledger = head['ledger']
    elif isinstance(detail.get('ledger'), dict):
        ledger = detail['ledger']
    # serving twin (ISSUE 17): the throughput leg's serve-step ledger
    # + goodput + decode roofline, rendered side by side like the
    # training ledger above
    serve = legs.get(SERVE_LEG)
    serve_ledger = None
    if isinstance(serve, dict) and isinstance(serve.get('ledger'), dict):
        serve_ledger = {
            'ledger': serve['ledger'],
            'goodput': serve.get('goodput'),
            'roofline': serve.get('roofline'),
        }
    return {
        'round': rec.get('round'),
        'schema_version': rec.get('schema_version', 1),
        'metric': rec.get('metric'),
        'value': rec.get('value'),
        'legs': legs,
        'ledger': ledger,
        'serve_ledger': serve_ledger,
    }


def _verdict(direction, rel, threshold):
    if abs(rel) <= threshold:
        return 'flat'
    better = rel > 0 if direction == 'higher' else rel < 0
    return 'improvement' if better else 'regression'


def compare_legs(a, b, threshold=DEFAULT_THRESHOLD):
    """Per-leg metric deltas. Returns a list of leg dicts:
    {leg, status, metrics: [{name, old, new, rel, verdict}]}."""
    out = []
    for leg in sorted(set(a['legs']) | set(b['legs'])):
        la, lb = a['legs'].get(leg), b['legs'].get(leg)
        if la is None or lb is None:
            out.append({'leg': leg,
                        'status': 'added' if la is None else 'removed',
                        'metrics': []})
            continue
        if 'error' in la or 'error' in lb:
            which = ('both' if 'error' in la and 'error' in lb
                     else ('old' if 'error' in la else 'new'))
            out.append({'leg': leg, 'status': f'error({which})',
                        'metrics': []})
            continue
        rows = []
        for name in sorted(set(la) & set(lb)):
            va, vb = la[name], lb[name]
            if not (isinstance(va, (int, float))
                    and isinstance(vb, (int, float))):
                continue
            if not va:
                continue
            direction = METRIC_DIRECTION.get(name)
            rel = (vb - va) / abs(va)
            rows.append({
                'name': name, 'old': va, 'new': vb,
                'rel': round(rel, 4),
                'verdict': (_verdict(direction, rel, threshold)
                            if direction else 'info'),
            })
        out.append({'leg': leg, 'status': 'compared', 'metrics': rows})
    return out


def compare(a, b, threshold=DEFAULT_THRESHOLD):
    """The full comparison document for two normalized records."""
    legs = compare_legs(a, b, threshold)
    verdicts = [m['verdict'] for leg in legs for m in leg['metrics']]
    return {
        'old_round': a['round'], 'new_round': b['round'],
        'old_metric': {'name': a['metric'], 'value': a['value']},
        'new_metric': {'name': b['metric'], 'value': b['value']},
        'threshold': threshold,
        'legs': legs,
        'ledger': {'old': a['ledger'], 'new': b['ledger']},
        'serve_ledger': {'old': a.get('serve_ledger'),
                         'new': b.get('serve_ledger')},
        'regressions': verdicts.count('regression'),
        'improvements': verdicts.count('improvement'),
        'flat': verdicts.count('flat'),
    }


# ---------------------------------------------------------------------------
# rendering
# ---------------------------------------------------------------------------
_MARK = {'regression': '!! regression', 'improvement': '++ improvement',
         'flat': '   flat', 'info': '   info'}


def render(cmp_doc):
    old_r = cmp_doc.get('old_round') or 'old'
    new_r = cmp_doc.get('new_round') or 'new'
    out = [f'== bench compare {old_r} -> {new_r} ' + '=' * 30]
    om, nm = cmp_doc['old_metric'], cmp_doc['new_metric']
    out.append(f"headline: {om['name']} {om['value']} -> "
               f"{nm['name']} {nm['value']}   (threshold "
               f"{cmp_doc['threshold'] * 100:.1f}%)")
    for leg in cmp_doc['legs']:
        if leg['status'] != 'compared':
            out.append(f"  {leg['leg']:<24} [{leg['status']}]")
            continue
        out.append(f"  {leg['leg']}:")
        for m in leg['metrics']:
            out.append(
                f"    {m['name']:<22} {m['old']:>12.4g} -> "
                f"{m['new']:>12.4g}  {m['rel'] * 100:>+7.2f}%  "
                f"{_MARK.get(m['verdict'], m['verdict'])}")
    led = cmp_doc.get('ledger') or {}
    la, lb = led.get('old'), led.get('new')
    if la or lb:
        out.append('  step-time ledger (per-step seconds, '
                   f'{old_r} | {new_r}):')
        ca = (la or {}).get('components') or {}
        cb = (lb or {}).get('components') or {}

        def _f(v):
            return f'{v * 1e3:10.3f}ms' if isinstance(
                v, (int, float)) else '         --'

        out.append(f"    {'wall':<14} "
                   f"{_f((la or {}).get('wall_seconds'))} | "
                   f"{_f((lb or {}).get('wall_seconds'))}")
        for c in ('compute', 'exposed_comm', 'bubble', 'host_gap',
                  'residue'):
            out.append(f'    {c:<14} {_f(ca.get(c))} | {_f(cb.get(c))}')
        for key in ('model_tflops', 'hardware_tflops', 'mfu'):
            va = (la or {}).get(key)
            vb = (lb or {}).get(key)
            if va is not None or vb is not None:
                fa = f'{va:.4g}' if isinstance(va, (int, float)) else '--'
                fb = f'{vb:.4g}' if isinstance(vb, (int, float)) else '--'
                out.append(f'    {key:<14} {fa:>12} | {fb:>12}')
    sled = cmp_doc.get('serve_ledger') or {}
    sa, sb = sled.get('old'), sled.get('new')
    if sa or sb:
        out.append('  serve ledger (per-iteration seconds, '
                   f'{old_r} | {new_r}):')
        acct_a = (sa or {}).get('ledger') or {}
        acct_b = (sb or {}).get('ledger') or {}
        ca = acct_a.get('components') or {}
        cb = acct_b.get('components') or {}

        def _f(v):
            return f'{v * 1e3:10.3f}ms' if isinstance(
                v, (int, float)) else '         --'

        out.append(f"    {'wall':<14} "
                   f"{_f(acct_a.get('wall_seconds'))} | "
                   f"{_f(acct_b.get('wall_seconds'))}")
        for c in ('compute', 'host_fetch', 'schedule', 'page_stream',
                  'residue'):
            out.append(f'    {c:<14} {_f(ca.get(c))} | {_f(cb.get(c))}')

        def _g(v, fmt='{:.4g}'):
            return fmt.format(v) if isinstance(v, (int, float)) else '--'

        gp_a = (sa or {}).get('goodput') or {}
        gp_b = (sb or {}).get('goodput') or {}
        rf_a = (sa or {}).get('roofline') or {}
        rf_b = (sb or {}).get('roofline') or {}
        for label, va, vb in (
                ('goodput_frac', gp_a.get('goodput_fraction'),
                 gp_b.get('goodput_fraction')),
                ('wasted_tokens', gp_a.get('wasted_tokens'),
                 gp_b.get('wasted_tokens')),
                ('host_bound', acct_a.get('host_bound_fraction'),
                 acct_b.get('host_bound_fraction')),
                ('hbm_gbps', rf_a.get('hbm_gbps'), rf_b.get('hbm_gbps')),
                ('mbu', rf_a.get('mbu'), rf_b.get('mbu'))):
            if va is not None or vb is not None:
                out.append(f'    {label:<14} {_g(va):>12} | {_g(vb):>12}')
    out.append(f"verdicts: {cmp_doc['regressions']} regression(s), "
               f"{cmp_doc['improvements']} improvement(s), "
               f"{cmp_doc['flat']} flat")
    return '\n'.join(out)


# ---------------------------------------------------------------------------
# selftest
# ---------------------------------------------------------------------------
def _repo_root():
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def selftest():
    # 1) synthetic v2 pair: delta math, verdict signs, ledger rendering
    def _rec(ms, toks, mfu, compute):
        return {'schema_version': 2, 'round': f'r{int(ms)}',
                'metric': 'm', 'value': mfu,
                'legs': {HEADLINE_LEG: {
                    'ms_per_step': ms, 'tokens_per_sec': toks,
                    'mfu': mfu,
                    'ledger': {'wall_seconds': ms / 1e3,
                               'components': {'compute': compute,
                                              'exposed_comm': 0.01,
                                              'bubble': 0.02,
                                              'host_gap': 0.005,
                                              'residue': 0.001},
                               'model_tflops': 100.0, 'mfu': mfu}}},
                'detail': {}}

    a = normalize(_rec(1000.0, 16000.0, 0.50, 0.9))
    b = normalize(_rec(800.0, 20000.0, 0.625, 0.7))
    doc = compare(a, b, threshold=0.02)
    head = {m['name']: m for leg in doc['legs'] for m in leg['metrics']
            if leg['leg'] == HEADLINE_LEG}
    assert head['ms_per_step']['verdict'] == 'improvement', head
    assert head['tokens_per_sec']['verdict'] == 'improvement', head
    assert abs(head['ms_per_step']['rel'] - (-0.2)) < 1e-9, head
    assert doc['ledger']['old'] and doc['ledger']['new']
    text = render(doc)
    assert 'step-time ledger' in text and 'compute' in text
    rev = compare(b, a, threshold=0.02)
    assert rev['regressions'] >= 2, 'reversed compare must regress'

    # 1b) synthetic serve-ledger pair (ISSUE 17): goodput_fraction is
    # higher-is-better, host_bound_fraction lower-is-better, and the
    # serve ledger/goodput/roofline render side by side
    def _srec(round_id, gf, hbf, mbu):
        return {'schema_version': 2, 'round': round_id,
                'metric': 'm', 'value': 0.5,
                'legs': {
                    HEADLINE_LEG: {'ms_per_step': 100.0},
                    SERVE_LEG: {
                        'decode_tokens_per_sec': 5000.0,
                        'goodput_fraction': gf,
                        'host_bound_fraction': hbf,
                        'hbm_gbps': 400.0 * (1.0 + mbu),
                        'mbu': mbu,
                        'ledger': {
                            'wall_seconds': 0.010,
                            'host_bound_fraction': hbf,
                            'components': {'compute': 0.006,
                                           'host_fetch': 0.002,
                                           'schedule': 0.001,
                                           'page_stream': 0.0005,
                                           'residue': 0.0005}},
                        'goodput': {'emitted_tokens': 1000,
                                    'delivered_tokens': int(gf * 1000),
                                    'wasted_tokens':
                                        1000 - int(gf * 1000),
                                    'goodput_fraction': gf},
                        'roofline': {'decode_bytes_per_iteration':
                                     1 << 20,
                                     'hbm_gbps': 400.0 * (1.0 + mbu),
                                     'mbu': mbu}}},
                'detail': {}}

    sa = normalize(_srec('sA', 0.80, 0.20, 0.30))
    sb = normalize(_srec('sB', 0.95, 0.10, 0.40))
    sdoc = compare(sa, sb, threshold=0.02)
    srows = {m['name']: m for leg in sdoc['legs']
             for m in leg['metrics'] if leg['leg'] == SERVE_LEG}
    assert srows['goodput_fraction']['verdict'] == 'improvement', srows
    assert srows['host_bound_fraction']['verdict'] == 'improvement', \
        srows
    assert srows['mbu']['verdict'] == 'improvement', srows
    srev = compare(sb, sa, threshold=0.02)
    srev_rows = {m['name']: m for leg in srev['legs']
                 for m in leg['metrics'] if leg['leg'] == SERVE_LEG}
    assert srev_rows['goodput_fraction']['verdict'] == 'regression'
    assert srev_rows['host_bound_fraction']['verdict'] == 'regression'
    stext = render(sdoc)
    assert 'serve ledger' in stext and 'page_stream' in stext, stext
    assert 'goodput_frac' in stext and 'host_bound' in stext, stext

    # 2) the real r04 -> r05 artifacts: legacy-shape normalization and
    # the asserted regression verdict (r05's headline MFU dropped 2.3%,
    # past the 2% default threshold)
    root = _repo_root()
    r04 = os.path.join(root, 'BENCH_r04.json')
    r05 = os.path.join(root, 'BENCH_r05.json')
    a = normalize(load_record(r04))
    b = normalize(load_record(r05))
    assert HEADLINE_LEG in a['legs'] and HEADLINE_LEG in b['legs']
    doc = compare(a, b)
    head = {m['name']: m for leg in doc['legs'] for m in leg['metrics']
            if leg['leg'] == HEADLINE_LEG}
    assert head['mfu']['verdict'] == 'regression', head.get('mfu')
    assert head['ms_per_step']['verdict'] == 'regression', \
        head.get('ms_per_step')
    assert doc['regressions'] >= 1
    text = render(doc)
    assert 'regression' in text
    print(text)
    print('bench_compare selftest OK')
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('old', nargs='?', help='older BENCH_r*.json')
    ap.add_argument('new', nargs='?', help='newer BENCH_r*.json')
    ap.add_argument('--threshold', type=float, default=DEFAULT_THRESHOLD,
                    help='relative delta past which a verdict is '
                         'rendered (default 0.02)')
    ap.add_argument('--json', action='store_true',
                    help='emit the comparison document as JSON')
    ap.add_argument('--strict', action='store_true',
                    help='exit 1 when any regression is found')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.old or not args.new:
        ap.error('need two BENCH_r*.json paths (or --selftest)')
    doc = compare(normalize(load_record(args.old)),
                  normalize(load_record(args.new)),
                  threshold=args.threshold)
    if args.json:
        print(json.dumps(doc, indent=2))
    else:
        print(render(doc))
    if args.strict and doc['regressions']:
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
