"""Loss-parity harness: bf16 training must track the fp32 reference.

The BASELINE north star says "loss-curve-matching"; this harness trains the
same GPT config on the same data in fp32 and in bf16 (fp32 Adam masters)
and compares the curves. Run as a script for a JSON report (bf16 leg on
the default backend — the TPU chip under axon — fp32 leg likewise);
tests/test_loss_parity.py runs both legs on CPU for CI determinism.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def run_curve(dtype='float32', steps=40, seed=0, lr=3e-3, batch=8,
              seq_len=128):
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.jit import TrainStep
    from paddle_tpu.models.gpt import (GPTConfig, GPTForCausalLM,
                                       GPTPretrainingCriterion)

    paddle.seed(seed)
    cfg = GPTConfig(vocab_size=512, hidden_size=128, num_layers=4,
                    num_heads=4, max_seq_len=seq_len, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    if dtype == 'bfloat16':
        for p in model.parameters():
            if p.data.dtype == jnp.float32:
                p.data = p.data.astype(jnp.bfloat16)
    crit = GPTPretrainingCriterion(cfg)
    opt = paddle.optimizer.AdamW(learning_rate=lr,
                                 parameters=model.parameters(),
                                 weight_decay=0.01, multi_precision=True)

    def loss_fn(m, ids, labels):
        return crit(m(ids), labels)

    step = TrainStep(model, loss_fn, opt)
    rng = np.random.RandomState(7)
    # one fixed batch: the curve measures optimization fidelity, and a
    # memorizable target gives a steep, comparison-friendly descent
    ids = rng.randint(0, cfg.vocab_size, (batch, seq_len)).astype('int32')
    labels = np.roll(ids, -1, 1).astype('int32')
    t_ids, t_labels = Tensor(ids), Tensor(labels)
    losses = []
    for _ in range(steps):
        losses.append(float(step(t_ids, t_labels)))
    return losses


def compare(steps=40, rel_tol=0.05):
    fp32 = np.array(run_curve('float32', steps))
    bf16 = np.array(run_curve('bfloat16', steps))
    rel = np.abs(bf16 - fp32) / np.maximum(np.abs(fp32), 1e-6)
    report = {
        'steps': steps,
        'fp32_first': round(float(fp32[0]), 4),
        'fp32_last': round(float(fp32[-1]), 4),
        'bf16_last': round(float(bf16[-1]), 4),
        'max_rel_gap': round(float(rel.max()), 5),
        'mean_rel_gap': round(float(rel.mean()), 5),
        'fp32_decreased': bool(fp32[-1] < fp32[0]),
        'bf16_decreased': bool(bf16[-1] < bf16[0]),
        'pass': bool(rel.max() < rel_tol
                     and fp32[-1] < fp32[0] and bf16[-1] < bf16[0]),
    }
    return report


if __name__ == '__main__':
    print(json.dumps(compare()))
