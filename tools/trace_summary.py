#!/usr/bin/env python
"""trace_summary — summarize a paddle_tpu.profiler exported trace.

Reads either exporter format (chrome-trace `traceEvents` or the raw
`spans` JSON) and prints:

  * the top-N spans by total duration (calls, total ms, avg us, share);
  * a compile-vs-execute breakdown from span categories (compile =
    trace/lower/XLA-compile spans; execute = executor/jit dispatches;
    plus dataloader / collective / serve / other buckets).

It also reads SERVING request traces (the JSON-lines files
`ServingEngine.export_trace` writes, schema paddle_tpu.serve_trace/1
through /4) and prints the per-request SLO table: queue-wait, TTFT,
TPOT, e2e, preemptions, pages high-water, delivered/wasted tokens —
plus cross-request percentiles and the goodput aggregate (ISSUE 17).
Serve traces are detected by their schema header (content sniff, not
file extension); `--serve` forces that mode.

Several serve-trace files MERGE into one cross-replica table (ISSUE
11): pass each replica's export and requests render prefixed with
their replica id (the v2 `route` events name it; older files fall
back to the file stem), with SLO percentiles over the whole cluster:

    python tools/trace_summary.py --serve r0.jsonl r1.jsonl

Usage:
    python tools/trace_summary.py TRACE.json [--top 15] [--json]
    python tools/trace_summary.py SERVE_TRACE.jsonl [...] [--json]
    python tools/trace_summary.py --selftest    # CI smoke: generate a
                                                # tiny trace, summarize it
"""
import argparse
import json
import os
import sys


CATEGORY_BUCKETS = {
    'compile': 'compile',
    'executor': 'execute',
    'jit': 'execute',
    'train': 'execute',
    'optimizer': 'execute',
    'dataloader': 'dataloader',
    'collective': 'collective',
    'serve': 'serve',
    'serve_request': 'serve',
}


def load_spans(path):
    """Normalize either export format to [{name, cat, dur, ts}]."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and 'spans' in doc:
        return [s for s in doc['spans'] if 'dur' in s]
    events = doc.get('traceEvents', doc) if isinstance(doc, dict) else doc
    return [{'name': e.get('name', '?'), 'cat': e.get('cat', ''),
             'dur': e.get('dur', 0), 'ts': e.get('ts', 0)}
            for e in events if e.get('ph') == 'X']


def summarize(spans, top=15):
    agg, buckets = {}, {}
    total = 0
    for s in spans:
        dur = int(s.get('dur') or 0)
        total += dur
        a = agg.setdefault(s['name'], {'calls': 0, 'total_us': 0})
        a['calls'] += 1
        a['total_us'] += dur
        bucket = CATEGORY_BUCKETS.get(s.get('cat') or '', 'other')
        buckets[bucket] = buckets.get(bucket, 0) + dur
    rows = sorted(agg.items(), key=lambda kv: -kv[1]['total_us'])[:top]
    return {
        'span_count': len(spans),
        'total_us': total,
        'top_spans': [
            {'name': n, 'calls': a['calls'], 'total_us': a['total_us'],
             'avg_us': a['total_us'] / a['calls'],
             'share': (a['total_us'] / total) if total else 0.0}
            for n, a in rows],
        'buckets_us': dict(sorted(buckets.items(),
                                  key=lambda kv: -kv[1])),
    }


def render(summary):
    out = []
    total = summary['total_us']
    out.append(f"spans: {summary['span_count']}   "
               f"total: {total / 1000.0:.3f} ms")
    out.append('')
    out.append('-- compile vs execute ' + '-' * 38)
    for bucket, us in summary['buckets_us'].items():
        share = (us / total * 100) if total else 0.0
        out.append(f'{bucket:<12} {us / 1000.0:>12.3f} ms  {share:5.1f}%')
    out.append('')
    out.append('-- top spans ' + '-' * 47)
    out.append(f"{'name':<36} {'calls':>6} {'total_ms':>10} "
               f"{'avg_us':>9} {'share':>6}")
    for r in summary['top_spans']:
        out.append(f"{r['name'][:36]:<36} {r['calls']:>6} "
                   f"{r['total_us'] / 1000.0:>10.3f} "
                   f"{r['avg_us']:>9.1f} {r['share'] * 100:>5.1f}%")
    return '\n'.join(out)


# ---------------------------------------------------------------------------
# serving request traces (JSON-lines, paddle_tpu.serve_trace/1 – /6)
# ---------------------------------------------------------------------------
def summarize_serve(paths):
    """Per-request table + cross-request SLO percentiles from one or
    several serve-trace JSON-lines files. Multiple files are merged
    into one cross-replica table: request ids prefix with the replica
    (route-event replica_id, else the file stem — per-replica files
    restart ids at 0, so the prefix IS the disambiguator), and the
    percentiles aggregate the whole cluster's requests. Schema-v3
    traces (ISSUE 15) additionally group the percentile table BY
    TENANT (`percentiles_by_tenant`) — the per-tenant SLO view the
    multi-tenant scheduler is judged on. Schema-v4 traces (ISSUE 17)
    price each request's delivered vs wasted tokens (preempt-destroyed
    prefill recompute + rejected/discarded spec drafts); the `goodput`
    aggregate sums them across the table. v1-v3 merges are unchanged —
    their recompute/discard fields reconstruct as zeros."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.serving.request_trace import (load_trace,
                                                  percentile_of,
                                                  reconstruct)
    if isinstance(paths, str):
        paths = [paths]
    multi = len(paths) > 1
    rows, dropped, schema = [], 0, None
    for i, path in enumerate(paths):
        header, events = load_trace(path)
        schema = schema or header.get('schema')
        dropped += header.get('dropped_events', 0)
        fallback = os.path.splitext(os.path.basename(path))[0]
        for r in sorted(reconstruct(events).values(),
                        key=lambda r: r['req']):
            if multi and r.get('replica_id') is None:
                r['replica_id'] = fallback
            if multi:
                r['req'] = f"{r['replica_id']}:{r['req']}"
            rows.append(r)
    pct = {}
    for key in ('queue_wait_s', 'ttft_s', 'tpot_s', 'e2e_s'):
        vals = [r[key] for r in rows]
        pct[key] = {f'p{q}': percentile_of(vals, q) for q in (50, 90, 99)}
    by_tenant = {}
    if any(r.get('tenant_id') is not None for r in rows):
        tenants = sorted({r.get('tenant_id') or '-' for r in rows})
        for tid in tenants:
            trows = [r for r in rows
                     if (r.get('tenant_id') or '-') == tid]
            by_tenant[tid] = {
                'requests': len(trows),
                # cluster-wide tenant visibility (ISSUE 18): how many
                # replicas this tenant's requests landed on — each one
                # holds a SEPARATE quota bucket, so replicas > 1 means
                # the tenant's effective quota is multiplied until the
                # ROADMAP quota-sharing fix ships
                'replicas': len({r.get('replica_id') or '-'
                                 for r in trows}),
                'quota_defers': sum(r.get('quota_defers', 0)
                                    for r in trows),
                'deadline_misses': sum(1 for r in trows
                                       if r.get('deadline_miss')),
            }
            for key in ('queue_wait_s', 'e2e_s'):
                vals = [r[key] for r in trows]
                by_tenant[tid][key] = {
                    f'p{q}': percentile_of(vals, q)
                    for q in (50, 90, 99)}
    # cross-request goodput aggregate (schema v4, ISSUE 17): totals of
    # the per-request delivered/wasted pricing — emitted is their sum
    # by construction, mirroring the engine ledger identity
    delivered = sum(r.get('delivered_tokens', 0) for r in rows)
    wasted = sum(r.get('wasted_tokens', 0) for r in rows)
    goodput = {
        'delivered_tokens': delivered,
        'wasted_tokens': wasted,
        'emitted_tokens': delivered + wasted,
        'recompute_tokens': sum(r.get('recompute_tokens', 0)
                                for r in rows),
        'goodput_fraction': (delivered / (delivered + wasted)
                             if delivered + wasted else None),
    }
    return {'schema': schema, 'files': len(paths),
            'dropped_events': dropped,
            'requests': rows, 'percentiles': pct,
            'percentiles_by_tenant': by_tenant,
            'goodput': goodput}


def _fmt_ms(v):
    return f'{v * 1000.0:.2f}' if v is not None else '-'


def render_serve(s):
    rows = s['requests']
    out = [f"serve trace: {len(rows)} requests"
           + (f" across {s['files']} replica files"
              if s.get('files', 1) > 1 else '')
           + (f"   ({s['dropped_events']} events dropped at cap)"
              if s.get('dropped_events') else '')]
    out.append('')
    # cluster columns only when any request was router-placed
    # (schema v2 route events / merged per-replica files)
    routed = any(r.get('replica_id') is not None for r in rows)
    tenanted = any(r.get('tenant_id') is not None for r in rows)
    # host-tier resurrects (schema v6, ISSUE 20): the column renders
    # only when some request resurrected, so v1-v5 tables are
    # byte-identical to before
    tiered = any(r.get('resurrected_tokens', 0) for r in rows)
    extra_hdr = (f" {'resurr':>6}" if tiered else '') \
        + (f" {'tenant':>8} {'prio':>4}" if tenanted else '') \
        + (f" {'replica':>8} {'routed':>12}" if routed else '')
    out.append(f"{'req':>8} {'state':<9} {'prompt':>6} {'gen':>5} "
               f"{'queue_ms':>9} {'ttft_ms':>9} {'tpot_ms':>9} "
               f"{'e2e_ms':>9} {'preempt':>7} {'pages_hw':>8} "
               f"{'cached':>6} {'spec':>9} "
               f"{'deliv':>6} {'wasted':>6}" + extra_hdr)
    for r in rows:
        prop = r.get('spec_proposed', 0)
        spec = (f"{r.get('spec_accepted', 0)}/{prop}" if prop else '-')
        extra = (f" {r.get('resurrected_tokens', 0):>6}"
                 if tiered else '') \
            + (f" {str(r.get('tenant_id') or '-'):>8} "
               f"{r.get('priority', 0):>4}" if tenanted else '') \
            + (f" {str(r.get('replica_id') or '-'):>8} "
               f"{str(r.get('router_decision') or '-'):>12}"
               if routed else '')
        out.append(
            f"{r['req']:>8} {r['state'] or '?':<9} "
            f"{r['prompt_tokens'] if r['prompt_tokens'] is not None else '?':>6} "
            f"{r['tokens_generated']:>5} "
            f"{_fmt_ms(r['queue_wait_s']):>9} {_fmt_ms(r['ttft_s']):>9} "
            f"{_fmt_ms(r['tpot_s']):>9} {_fmt_ms(r['e2e_s']):>9} "
            f"{r['preemptions']:>7} {r['pages_high_water']:>8} "
            f"{r.get('prefix_cached_tokens', 0):>6} {spec:>9} "
            f"{r.get('delivered_tokens', 0):>6} "
            f"{r.get('wasted_tokens', 0):>6}" + extra)
    # cross-request prefix/spec aggregates (ISSUE 9): prompt tokens
    # served from cache, and draft-token acceptance over the stream
    cached = sum(r.get('prefix_cached_tokens', 0) for r in rows)
    prompt = sum(r['prompt_tokens'] or 0 for r in rows)
    prop = sum(r.get('spec_proposed', 0) for r in rows)
    acc = sum(r.get('spec_accepted', 0) for r in rows)
    if cached:
        out.append('')
        out.append(f"prefix cache: {cached}/{prompt} prompt tokens "
                   f"served from cache "
                   f"({100.0 * cached / max(prompt, 1):.1f}% hit-rate)")
    if prop:
        if not cached:
            out.append('')
        out.append(f"speculative decode: {acc}/{prop} draft tokens "
                   f"accepted ({100.0 * acc / prop:.1f}% acceptance)")
    # host-tier resurrect aggregate (schema v6, ISSUE 20): prompt
    # tokens restored from spilled host pages instead of re-prefilled
    res_tok = sum(r.get('resurrected_tokens', 0) for r in rows)
    res_pages = sum(r.get('resurrected_pages', 0) for r in rows)
    if res_tok:
        if not cached and not prop:
            out.append('')
        out.append(f"host tier: {res_tok}/{prompt} prompt tokens "
                   f"resurrected from spilled pages "
                   f"({res_pages} pages fetched)")
    # goodput aggregate (schema v4, ISSUE 17) — only rendered once any
    # request priced waste, so v1-v3 tables look exactly as before
    gp = s.get('goodput') or {}
    if gp.get('wasted_tokens'):
        out.append('')
        out.append(
            f"goodput: {gp['delivered_tokens']}/{gp['emitted_tokens']} "
            f"tokens delivered "
            f"({100.0 * gp['goodput_fraction']:.1f}%), "
            f"{gp['wasted_tokens']} wasted "
            f"({gp['recompute_tokens']} preempt-recompute)")
    out.append('')
    out.append('-- SLO percentiles (ms) ' + '-' * 36)
    for key, label in (('queue_wait_s', 'queue wait'),
                       ('ttft_s', 'ttft'), ('tpot_s', 'tpot'),
                       ('e2e_s', 'e2e')):
        p = s['percentiles'][key]
        out.append(f"{label:<12} p50 {_fmt_ms(p['p50']):>9}  "
                   f"p90 {_fmt_ms(p['p90']):>9}  "
                   f"p99 {_fmt_ms(p['p99']):>9}")
    # per-tenant SLO grouping (schema v3, ISSUE 15)
    by_tenant = s.get('percentiles_by_tenant') or {}
    if by_tenant:
        out.append('')
        out.append('-- SLO percentiles by tenant (ms) ' + '-' * 26)
        out.append(f"{'tenant':<12} {'n':>4} {'reps':>4} "
                   f"{'defer':>5} {'dl-miss':>7} "
                   f"{'qwait p50':>10} {'qwait p99':>10} "
                   f"{'e2e p50':>9} {'e2e p99':>9}")
        for tid, row in sorted(by_tenant.items()):
            qw, e2e = row['queue_wait_s'], row['e2e_s']
            out.append(
                f"{tid[:12]:<12} {row['requests']:>4} "
                f"{row.get('replicas', 1):>4} "
                f"{row['quota_defers']:>5} "
                f"{row['deadline_misses']:>7} "
                f"{_fmt_ms(qw['p50']):>10} {_fmt_ms(qw['p99']):>10} "
                f"{_fmt_ms(e2e['p50']):>9} {_fmt_ms(e2e['p99']):>9}")
        reps = {row.get('replicas', 1) for row in by_tenant.values()}
        if max(reps, default=1) > 1:
            out.append('note: reps > 1 — each replica holds a '
                       'separate quota bucket for that tenant '
                       '(effective quota multiplies until cluster '
                       'quota sharing ships)')
    return '\n'.join(out)


def _looks_like_serve_trace(path):
    # content sniff, NOT extension: fleet workerlogs are .jsonl too and
    # must not render as an empty "serve trace: 0 requests" table
    try:
        with open(path) as f:
            first = f.readline().strip()
        doc = json.loads(first)
        return isinstance(doc, dict) and (
            doc.get('schema', '').startswith('paddle_tpu.serve_trace')
            or ('event' in doc and 'req' in doc))
    except (OSError, ValueError):
        return False


def _serve_selftest():
    """Drive a deterministic-clock tracer through a preempt/resume
    lifecycle, export, summarize, assert the derived SLOs."""
    import tempfile
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    from paddle_tpu.serving.request_trace import RequestTracer

    t = [0.0]

    def clock():
        t[0] += 0.001
        return t[0]

    tr = RequestTracer(clock=clock)
    tr.record(7, 'submit', t=1.0, prompt_tokens=5, max_new_tokens=4)
    tr.record(7, 'admit', t=1.5, slot=0)
    tr.record(7, 'prefix_hit', t=1.55, cached_tokens=4, pages=1)
    tr.record(7, 'prefill_chunk', t=1.6, tokens=5, prefilled=5, pages=1)
    tr.record(7, 'first_token', t=2.0, tokens_generated=1, pages=1)
    tr.record(7, 'preempt', t=2.1, pages_released=1,
              tokens_generated=1)
    tr.record(7, 'resume', t=2.5, slot=1)
    # v4 (ISSUE 17): the resume chunk re-derives the 5 positions the
    # preemption destroyed; the verify burst drops one accepted token
    # past eos — both priced as waste
    tr.record(7, 'prefill_chunk', t=2.6, tokens=6, prefilled=6, pages=2,
              recompute_tokens=5)
    for i, td in enumerate((2.8, 3.0)):
        tr.record(7, 'decode', t=td, tokens_generated=2 + i, pages=2)
    tr.record(7, 'spec_verify', t=3.1, proposed=3, accepted=1,
              discarded=1)
    tr.record(7, 'decode', t=3.2, tokens_generated=4, pages=2)
    tr.record(7, 'retire', t=3.2, tokens_generated=4, preemptions=1)
    with tempfile.TemporaryDirectory() as d:
        p = os.path.join(d, 'serve.jsonl')
        tr.export_jsonl(p)
        assert _looks_like_serve_trace(p)
        s = summarize_serve(p)
    (r,) = s['requests']
    assert r['queue_wait_s'] == 0.5 and r['ttft_s'] == 1.0, r
    assert r['preemptions'] == 1 and r['tokens_generated'] == 4, r
    assert abs(r['tpot_s'] - (3.2 - 2.0) / 3) < 1e-12, r
    assert r['e2e_s'] == 2.2 and r['pages_high_water'] == 2, r
    assert r['prefix_cached_tokens'] == 4, r
    assert r['spec_proposed'] == 3 and r['spec_accepted'] == 1, r
    # v4 goodput pricing: delivered = (11 computed - 5 recompute)
    # prefill + 3 decode (4 generated, first rides the prefill column);
    # wasted = 5 recompute + 2 rejected drafts + 1 discarded
    assert r['delivered_tokens'] == 9 and r['wasted_tokens'] == 8, r
    gp = s['goodput']
    assert gp['delivered_tokens'] + gp['wasted_tokens'] \
        == gp['emitted_tokens'] == 17, gp
    assert gp['recompute_tokens'] == 5, gp
    assert abs(s['percentiles']['ttft_s']['p50'] - 1.0) < 1e-12
    text = render_serve(s)
    assert 'prefix cache: 4/5' in text, text
    assert 'speculative decode: 1/3' in text, text
    assert 'goodput: 9/17 tokens delivered' in text, text
    assert 'deliv' in text and 'wasted' in text, text
    print(text)

    # cross-replica merge (ISSUE 11): two per-replica exports with v2
    # route events fold into one table, req ids replica-prefixed
    tr2 = RequestTracer(clock=clock)
    for rid, replica, decision in ((0, 'r0', 'affinity'),
                                   (0, 'r1', 'least_loaded')):
        t_ = tr2 if replica == 'r1' else RequestTracer(clock=clock)
        if replica == 'r0':
            tr0 = t_
        t_.record(rid, 'submit', t=1.0, prompt_tokens=3)
        t_.record(rid, 'route', t=1.01, replica_id=replica,
                  router_decision=decision)
        t_.record(rid, 'admit', t=1.2)
        t_.record(rid, 'first_token', t=1.5, tokens_generated=1)
        t_.record(rid, 'retire', t=1.8, tokens_generated=2)
    with tempfile.TemporaryDirectory() as d:
        p0 = os.path.join(d, 'r0.jsonl')
        p1 = os.path.join(d, 'r1.jsonl')
        tr0.export_jsonl(p0)
        tr2.export_jsonl(p1)
        m = summarize_serve([p0, p1])
    assert m['files'] == 2 and len(m['requests']) == 2, m
    assert {r['req'] for r in m['requests']} == {'r0:0', 'r1:0'}, m
    assert {r['router_decision'] for r in m['requests']} == \
        {'affinity', 'least_loaded'}, m
    mtext = render_serve(m)
    assert 'replica' in mtext and 'r0' in mtext and 'r1' in mtext, mtext
    print(mtext)

    # tenant grouping (schema v3, ISSUE 15): tenant columns on the
    # per-request table, percentile block grouped by tenant, engine-
    # scope degrade_stage events skipped by reconstruction
    tr3 = RequestTracer(clock=clock)
    for rid, tid, prio in ((0, 'heavy', 0), (1, 'light', 2)):
        tr3.record(rid, 'submit', t=1.0 + rid, prompt_tokens=3,
                   tenant_id=tid, priority=prio)
        if tid == 'heavy':
            tr3.record(rid, 'quota_defer', t=1.1, tenant_id=tid,
                       bill_tokens=8, retry_after_s=0.5)
        tr3.record(rid, 'admit', t=1.2 + rid)
        tr3.record(rid, 'first_token', t=1.5 + rid,
                   tokens_generated=1)
        tr3.record(rid, 'deadline_miss', t=1.7 + rid, e2e_s=0.8,
                   deadline_s=0.5)
        tr3.record(rid, 'retire', t=1.8 + rid, tokens_generated=2)
    tr3.record(-1, 'degrade_stage', t=1.05, from_stage=0, stage=1,
               stage_name='shed_spec', pressure=0.9)
    with tempfile.TemporaryDirectory() as d:
        p3 = os.path.join(d, 'tenants.jsonl')
        tr3.export_jsonl(p3)
        s3 = summarize_serve(p3)
    assert len(s3['requests']) == 2, s3      # engine event skipped
    byt = s3['percentiles_by_tenant']
    assert set(byt) == {'heavy', 'light'}, byt
    assert byt['heavy']['quota_defers'] == 1, byt
    assert byt['light']['deadline_misses'] == 1, byt
    assert abs(byt['light']['e2e_s']['p50'] - 0.8) < 1e-12, byt
    ttext = render_serve(s3)
    assert 'tenant' in ttext and 'by tenant' in ttext, ttext
    assert 'heavy' in ttext and 'light' in ttext, ttext
    print(ttext)
    print('trace_summary serve selftest: OK')


def _selftest():
    """CI smoke: record a trace through the real tracer, export both
    formats, summarize, and assert the breakdown is sane."""
    import tempfile
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import paddle_tpu.profiler as prof

    prof.use_native_recorder(False)
    results = []
    p = prof.Profiler(on_trace_ready=lambda pr: results.append(
        pr.profiler_result))
    p.start()
    with prof.RecordEvent('executor::build_program', event_type='compile'):
        with prof.RecordEvent('executor::compile', event_type='compile'):
            sum(range(20000))
    for _ in range(3):
        with prof.RecordEvent('executor::run', event_type='executor'):
            sum(range(5000))
        with prof.RecordEvent('dataloader::next', event_type='dataloader'):
            pass
    p.stop()
    prof.use_native_recorder(True)

    with tempfile.TemporaryDirectory() as d:
        ok = True
        for fname, export in (
                ('t.trace.json', results[0].export_chrome_tracing),
                ('t.json', results[0].export_json)):
            path = os.path.join(d, fname)
            export(path)
            s = summarize(load_spans(path))
            assert s['span_count'] == 8, s
            assert s['buckets_us'].get('compile', 0) > 0, s
            assert s['buckets_us'].get('execute', 0) > 0, s
            assert s['buckets_us'].get('dataloader', 0) >= 0, s
            names = [r['name'] for r in s['top_spans']]
            assert 'executor::run' in names, names
            ok = ok and bool(render(s))
        print(render(s))
    _serve_selftest()
    print('trace_summary selftest: OK')
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('trace', nargs='*', help='exported trace JSON '
                    '(profiler spans/chrome, or serve-trace .jsonl '
                    'files — several serve traces merge into one '
                    'cross-replica table)')
    ap.add_argument('--top', type=int, default=15,
                    help='how many spans to list')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable output')
    ap.add_argument('--serve', action='store_true',
                    help='force serve-trace (per-request SLO) mode')
    ap.add_argument('--selftest', action='store_true',
                    help='generate a synthetic trace and summarize it')
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.trace:
        ap.error('trace path required (or --selftest)')
    if args.serve or all(_looks_like_serve_trace(p)
                         for p in args.trace):
        s = summarize_serve(args.trace)
        print(json.dumps(s) if args.json else render_serve(s))
        return 0
    if len(args.trace) > 1:
        ap.error('multiple trace files only merge in --serve mode')
    summary = summarize(load_spans(args.trace[0]), top=args.top)
    print(json.dumps(summary) if args.json else render(summary))
    return 0


if __name__ == '__main__':
    sys.exit(main())
