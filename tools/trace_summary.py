#!/usr/bin/env python
"""trace_summary — summarize a paddle_tpu.profiler exported trace.

Reads either exporter format (chrome-trace `traceEvents` or the raw
`spans` JSON) and prints:

  * the top-N spans by total duration (calls, total ms, avg us, share);
  * a compile-vs-execute breakdown from span categories (compile =
    trace/lower/XLA-compile spans; execute = executor/jit dispatches;
    plus dataloader / collective / other buckets).

Usage:
    python tools/trace_summary.py TRACE.json [--top 15] [--json]
    python tools/trace_summary.py --selftest    # CI smoke: generate a
                                                # tiny trace, summarize it
"""
import argparse
import json
import os
import sys


CATEGORY_BUCKETS = {
    'compile': 'compile',
    'executor': 'execute',
    'jit': 'execute',
    'train': 'execute',
    'optimizer': 'execute',
    'dataloader': 'dataloader',
    'collective': 'collective',
}


def load_spans(path):
    """Normalize either export format to [{name, cat, dur, ts}]."""
    with open(path) as f:
        doc = json.load(f)
    if isinstance(doc, dict) and 'spans' in doc:
        return [s for s in doc['spans'] if 'dur' in s]
    events = doc.get('traceEvents', doc) if isinstance(doc, dict) else doc
    return [{'name': e.get('name', '?'), 'cat': e.get('cat', ''),
             'dur': e.get('dur', 0), 'ts': e.get('ts', 0)}
            for e in events if e.get('ph') == 'X']


def summarize(spans, top=15):
    agg, buckets = {}, {}
    total = 0
    for s in spans:
        dur = int(s.get('dur') or 0)
        total += dur
        a = agg.setdefault(s['name'], {'calls': 0, 'total_us': 0})
        a['calls'] += 1
        a['total_us'] += dur
        bucket = CATEGORY_BUCKETS.get(s.get('cat') or '', 'other')
        buckets[bucket] = buckets.get(bucket, 0) + dur
    rows = sorted(agg.items(), key=lambda kv: -kv[1]['total_us'])[:top]
    return {
        'span_count': len(spans),
        'total_us': total,
        'top_spans': [
            {'name': n, 'calls': a['calls'], 'total_us': a['total_us'],
             'avg_us': a['total_us'] / a['calls'],
             'share': (a['total_us'] / total) if total else 0.0}
            for n, a in rows],
        'buckets_us': dict(sorted(buckets.items(),
                                  key=lambda kv: -kv[1])),
    }


def render(summary):
    out = []
    total = summary['total_us']
    out.append(f"spans: {summary['span_count']}   "
               f"total: {total / 1000.0:.3f} ms")
    out.append('')
    out.append('-- compile vs execute ' + '-' * 38)
    for bucket, us in summary['buckets_us'].items():
        share = (us / total * 100) if total else 0.0
        out.append(f'{bucket:<12} {us / 1000.0:>12.3f} ms  {share:5.1f}%')
    out.append('')
    out.append('-- top spans ' + '-' * 47)
    out.append(f"{'name':<36} {'calls':>6} {'total_ms':>10} "
               f"{'avg_us':>9} {'share':>6}")
    for r in summary['top_spans']:
        out.append(f"{r['name'][:36]:<36} {r['calls']:>6} "
                   f"{r['total_us'] / 1000.0:>10.3f} "
                   f"{r['avg_us']:>9.1f} {r['share'] * 100:>5.1f}%")
    return '\n'.join(out)


def _selftest():
    """CI smoke: record a trace through the real tracer, export both
    formats, summarize, and assert the breakdown is sane."""
    import tempfile
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import paddle_tpu.profiler as prof

    prof.use_native_recorder(False)
    results = []
    p = prof.Profiler(on_trace_ready=lambda pr: results.append(
        pr.profiler_result))
    p.start()
    with prof.RecordEvent('executor::build_program', event_type='compile'):
        with prof.RecordEvent('executor::compile', event_type='compile'):
            sum(range(20000))
    for _ in range(3):
        with prof.RecordEvent('executor::run', event_type='executor'):
            sum(range(5000))
        with prof.RecordEvent('dataloader::next', event_type='dataloader'):
            pass
    p.stop()
    prof.use_native_recorder(True)

    with tempfile.TemporaryDirectory() as d:
        ok = True
        for fname, export in (
                ('t.trace.json', results[0].export_chrome_tracing),
                ('t.json', results[0].export_json)):
            path = os.path.join(d, fname)
            export(path)
            s = summarize(load_spans(path))
            assert s['span_count'] == 8, s
            assert s['buckets_us'].get('compile', 0) > 0, s
            assert s['buckets_us'].get('execute', 0) > 0, s
            assert s['buckets_us'].get('dataloader', 0) >= 0, s
            names = [r['name'] for r in s['top_spans']]
            assert 'executor::run' in names, names
            ok = ok and bool(render(s))
        print(render(s))
    print('trace_summary selftest: OK')
    return 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('trace', nargs='?', help='exported trace JSON')
    ap.add_argument('--top', type=int, default=15,
                    help='how many spans to list')
    ap.add_argument('--json', action='store_true',
                    help='machine-readable output')
    ap.add_argument('--selftest', action='store_true',
                    help='generate a synthetic trace and summarize it')
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.trace:
        ap.error('trace path required (or --selftest)')
    summary = summarize(load_spans(args.trace), top=args.top)
    print(json.dumps(summary) if args.json else render(summary))
    return 0


if __name__ == '__main__':
    sys.exit(main())
