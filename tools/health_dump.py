#!/usr/bin/env python
"""health_dump — render paddle_tpu diagnostics artifacts.

Reads any of the JSON artifacts the diagnostics layer writes and prints
the human post-mortem:

  * hang reports (`flight_recorder.rank*.json` from the HangWatchdog):
    cross-rank journal frontier, per-rank last-completed / first-missing
    collective seq, stalled-rank verdict, recent journal tail;
  * bare per-rank flight-recorder dumps (`FlightRecorder.dump()`);
  * OOM reports (`oom_report.rank*.json` from core.memory.oom_guard):
    per-phase high-water table, top live buffers with origin phases,
    suspect phase;
  * numerics artifacts (`numerics_report.rank*.json` from
    core.numerics — NaN/Inf localization with op/tensor stats — and
    `divergence_report.rank*.json` from the cross-rank divergence
    sentinel);
  * rank-aware JSON-lines logs (`workerlog.<rank>.jsonl`): pretty-print
    the last events, filterable with --level;
  * gradient-comm gauges + compile-cache traffic (`comm` subcommand)
    from a StepTelemetry snapshot or bench record
    (docs/performance.md).

  * serving-engine gauges (`serve` subcommand): ptpu_serve_* decode
    throughput / TTFT / batch+page occupancy / preemptions plus the
    SLO percentile histograms (queue-wait / TTFT / TPOT / e2e
    p50/p90/p99) and the scheduler-timeline summary, from a
    StepTelemetry snapshot or bench record (docs/serving.md);
  * stalled-request watchdog artifacts (`serve_report.req*.json` from
    the serving engine's deadline watchdog): request journal tail,
    scheduler-timeline tail, pool census — rendered via the default
    ARTIFACT.json path.

  * Pallas fused-primitive routing (`pallas` subcommand):
    ptpu_pallas_{kernel,fallback}_invocations_total per primitive —
    which fused kernels the compiled steps actually picked vs
    reference fallbacks (docs/performance.md#fused-primitives).

  * memory census (`mem` subcommand): per-phase high-water table plus
    the compiled-program ACTIVATION bytes line
    (ptpu_mem_activation_bytes — XLA buffer-assignment temp bytes per
    compile site, the resident set remat policies shrink;
    docs/performance.md#remat-policy) from a bench record's `memory`
    section.

  * async-dispatch host-gap view (`host` subcommand): dispatch window /
    DeviceLoader prefetch depth knobs, per-site host gap + dispatch
    depth, host_bound_fraction, and the bench legs' sync-vs-windowed
    comparison (docs/performance.md#async-dispatch) from a bench record
    or telemetry snapshot.

  * multi-tenant serving (`tenants` subcommand): per-tenant SLO table
    (priority, quota deferrals, charged preemptions, deadline
    rejects/misses, tenant-labeled queue-wait/e2e percentiles) plus
    the graceful-degradation ladder's current stage and pressure
    (docs/serving.md#multi-tenant), from a serve snapshot or bench
    record.

  * alert rules & metric history (`alerts` subcommand): the AlertManager
    rule table (state, severity, last value vs threshold), the recent
    fire/resolve transition tail, downsampled history-ring sparklines
    per series, and stale metric-section flags
    (docs/observability.md#time-series--alerts), from an AlertManager
    snapshot/report, a router cluster_snapshot, or a bench record.

Usage:
    python tools/health_dump.py ARTIFACT.json [--json] [--level ERROR]
    python tools/health_dump.py numerics ARTIFACT.json [--json]
    python tools/health_dump.py comm SNAPSHOT.json [--json]
    python tools/health_dump.py serve SNAPSHOT.json [--json]
    python tools/health_dump.py pallas SNAPSHOT.json [--json]
    python tools/health_dump.py mem RECORD.json [--json]
    python tools/health_dump.py host RECORD.json [--json]
    python tools/health_dump.py alerts SNAPSHOT.json [--json]
    python tools/health_dump.py --selftest           # CI smoke
    python tools/health_dump.py numerics --selftest  # numerics CI smoke
    python tools/health_dump.py comm --selftest      # comm CI smoke
    python tools/health_dump.py serve --selftest     # serving CI smoke
    python tools/health_dump.py tenants --selftest   # tenancy CI smoke
    python tools/health_dump.py cluster --selftest   # cluster CI smoke
    python tools/health_dump.py pallas --selftest    # pallas CI smoke
    python tools/health_dump.py mem --selftest       # mem CI smoke
    python tools/health_dump.py host --selftest      # async CI smoke
    python tools/health_dump.py pp --selftest        # pipeline CI smoke
    python tools/health_dump.py alerts --selftest    # alerts CI smoke
"""
import argparse
import json
import os
import sys


def _repo_root_on_path():
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))


def classify(doc):
    if isinstance(doc, dict):
        kind = doc.get('kind')
        if kind in ('hang_report', 'flight_recorder', 'oom_report',
                    'numerics_report', 'divergence_report',
                    'serve_report'):
            return kind
        if 'entries' in doc and 'seq' in doc:
            return 'flight_recorder'
        if 'ranks' in doc and 'analysis' in doc:
            return 'hang_report'
        if 'top_buffers' in doc or 'phases' in doc:
            return 'oom_report'
        if 'fingerprint_labels' in doc:
            return 'divergence_report'
        if 'op' in doc and ('output' in doc or 'tensors' in doc):
            return 'numerics_report'
        if 'timeline_tail' in doc and 'trace' in doc:
            return 'serve_report'
    return None


def render(doc):
    _repo_root_on_path()
    kind = classify(doc)
    if kind in ('hang_report', 'flight_recorder'):
        from paddle_tpu.distributed.flight_recorder import render_dump
        return render_dump(doc)
    if kind == 'oom_report':
        from paddle_tpu.core.memory import render_oom_report
        return render_oom_report(doc)
    if kind == 'numerics_report':
        from paddle_tpu.core.numerics import render_numerics_report
        return render_numerics_report(doc)
    if kind == 'divergence_report':
        from paddle_tpu.core.numerics import render_divergence_report
        return render_divergence_report(doc)
    if kind == 'serve_report':
        from paddle_tpu.serving.request_trace import render_serve_report
        return render_serve_report(doc)
    raise ValueError(
        "unrecognized artifact: expected a hang report, flight-recorder "
        "dump, OOM report, numerics report, divergence report, or "
        "serving serve_report (see docs/observability.md#diagnostics)")


def render_log(path, level=None, tail=50):
    _repo_root_on_path()
    from paddle_tpu.distributed.fleet.utils.log_util import parse_line
    want = level.upper() if level else None
    rows = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                doc = parse_line(line)
            except ValueError:
                continue
            if want and doc.get('level') != want:
                continue
            rows.append(doc)
    out = []
    for d in rows[-tail:]:
        fields = d.get('fields') or {}
        out.append(
            f"{d.get('iso', '?')} {d.get('level', '?'):<7} "
            f"rank{d.get('rank')}/{d.get('role')} "
            + (f"step={d.get('step')} " if d.get('step') is not None
               else '')
            + (f"[{d['event']}] " if d.get('event') else '')
            + str(d.get('msg', ''))
            + (' ' + ' '.join(f'{k}={v}' for k, v in fields.items())
               if fields else ''))
    return '\n'.join(out) if out else '(no matching log lines)'


# ---------------------------------------------------------------------------
def _selftest():
    """CI smoke: drive the REAL recorder/accountant APIs end to end —
    journal a hang scenario, synthesize an OOM, write JSON logs — and
    assert each artifact renders with the load-bearing facts."""
    import tempfile
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    from paddle_tpu.distributed import flight_recorder as fr
    from paddle_tpu.core import memory as mem
    from paddle_tpu.distributed.fleet.utils import log_util

    # -- hang report: rank 0 blocked in gseq=3, rank 1 never entered it
    r0 = fr.FlightRecorder(capacity=4, rank=0)
    r1 = fr.FlightRecorder(capacity=4, rank=1)
    for g in range(3):
        for r in (r0, r1):
            with r.span('all_reduce', gseq=g, nbytes=64):
                pass
    r0.record_enqueue('all_reduce', gseq=3, nbytes=64)   # never completes
    dumps = {0: r0.dump(), 1: r1.dump()}
    ana = fr.analyze(dumps)
    assert ana['frontier_gseq'] == 3, ana
    assert ana['stalled_ranks'] == [1], ana
    assert any('rank 1 never entered all_reduce gseq=3' in s
               for s in ana['summary']), ana['summary']
    report = {'kind': 'hang_report', 'reason': 'selftest',
              'ranks': {str(k): v for k, v in dumps.items()},
              'analysis': ana}
    text = render(report)
    assert 'never entered all_reduce gseq=3' in text
    assert 'PENDING' in text

    # ring wraparound is visible in the dump (capacity 4, 4 entries kept)
    assert len(dumps[0]['entries']) == 4 and dumps[0]['dropped'] == 0
    for g in range(10):
        with r1.span('barrier', gseq=4 + g):
            pass
    d1 = r1.dump()
    assert len(d1['entries']) == 4 and d1['dropped'] > 0

    with tempfile.TemporaryDirectory() as td:
        # -- OOM report from a synthetic RESOURCE_EXHAUSTED
        mem.reset()
        import jax.numpy as jnp
        with mem.phase('engine.init', census=True):
            keep = jnp.ones((256, 256), jnp.float32)
        try:
            with mem.oom_guard('selftest.site',
                               report_path=os.path.join(td, 'oom.json')):
                raise RuntimeError(
                    'RESOURCE_EXHAUSTED: Out of memory allocating '
                    '8589934592 bytes')
        except mem.DeviceOOMError as e:
            oom = e.report
            assert oom['suspect_phase'] == 'engine.init', oom
            assert oom['top_buffers'], oom
        else:
            raise AssertionError('oom_guard did not convert the error')
        with open(os.path.join(td, 'oom.json')) as f:
            text = render(json.load(f))
        assert 'suspect phase: engine.init' in text, text
        assert 'RESOURCE_EXHAUSTED' in (oom['error'] or ''), oom
        del keep

        # -- JSON-lines log round trip through the renderer
        os.environ['FLEET_LOG_DIR'] = td
        try:
            log_util.configure(force=True)
            log_util.log_json('selftest_event', level='error',
                              step_ms=12.5)
            log_path = os.path.join(
                td, f"workerlog."
                f"{os.environ.get('PADDLE_TRAINER_ID', '0') or 0}.jsonl")
            assert os.path.exists(log_path), os.listdir(td)
            rendered = render_log(log_path, level='error')
            assert 'selftest_event' in rendered, rendered
        finally:
            os.environ.pop('FLEET_LOG_DIR', None)
            log_util.configure(force=True)
    print('health_dump selftest: OK')
    return 0


def _numerics_selftest():
    """CI smoke for the numerics observatory: fused stats vs numpy, an
    eager deferred-guard trip with op localization, and both artifact
    kinds through classify/render."""
    import numpy as np
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import paddle_tpu as paddle
    from paddle_tpu.core import numerics as num

    # -- fused stats agree with numpy
    a = np.array([1.0, -2.0, 0.0, np.nan, np.inf, 3.0], np.float32)
    st = num.tensor_stats(a)
    assert st.nan_count == 1 and st.inf_count == 1 and st.zero_count == 1
    fin = a[np.isfinite(a)]
    assert abs(st.l2_norm - np.sqrt((fin ** 2).sum())) < 1e-4, st

    # -- deferred eager guard: one sync at flush, replay names the op
    paddle.set_flags({'FLAGS_check_nan_inf': True,
                      'FLAGS_check_nan_inf_deferred': True})
    try:
        x = paddle.to_tensor([0.5, 2.0])
        y = paddle.log(x - 1.0)          # log(-0.5) -> nan
        _ = y * 3.0
        try:
            num.flush(site='selftest', step=1)
        except num.NumericsError as e:
            report = e.report
        else:
            raise AssertionError('deferred guard did not trip')
    finally:
        paddle.set_flags({'FLAGS_check_nan_inf': False,
                          'FLAGS_check_nan_inf_deferred': False})
        num.reset()
    assert report['op'] == 'log', report
    assert classify(report) == 'numerics_report'
    text = render(report)
    assert 'first nonfinite op: log' in text, text

    # -- divergence artifact renders with the offending rank
    div = {'kind': 'divergence_report', 'step': 4,
           'first_divergent_step': 4, 'rank': 0, 'world_size': 2,
           'fingerprint_labels': list(num.FINGERPRINT_LABELS),
           'ranks': {'0': [1.0, 2.0, 3.0], '1': [1.0, 2.5, 3.0]},
           'offending_ranks': [1], 'consensus_ranks': [0]}
    assert classify(div) == 'divergence_report'
    text = render(div)
    assert 'first divergent step: 4' in text
    assert 'rank 1' in text and 'divergent' in text
    print('health_dump numerics selftest: OK')
    return 0


def _find_comm(doc):
    """Accepts a StepTelemetry snapshot, a bench leg record, or a bench
    round record; returns (comm dict, compile_cache dict)."""
    if not isinstance(doc, dict):
        return None, None
    for path in ((), ('telemetry',), ('detail', 'telemetry'),
                 ('parsed', 'detail', 'telemetry')):
        d = doc
        ok = True
        for k in path:
            d = d.get(k) if isinstance(d, dict) else None
            if d is None:
                ok = False
                break
        if ok and isinstance(d, dict) and (d.get('comm')
                                           or d.get('compile_cache')):
            return d.get('comm'), d.get('compile_cache')
    return None, None


def _fmt_bytes(n):
    for unit in ('B', 'KB', 'MB', 'GB', 'TB'):
        if abs(n) < 1024 or unit == 'TB':
            return f'{n:.1f}{unit}' if unit != 'B' else f'{int(n)}B'
        n /= 1024.0
    return f'{n:.1f}TB'


def render_comm(comm, cache=None):
    """Human rendering of the ptpu_comm_* gauges + compile-cache
    traffic (the per-step comm model of docs/performance.md)."""
    out = ['gradient-communication model (per rank, per step)']
    comm = comm or {}
    buckets = comm.get('ptpu_comm_buckets') or {}
    shards = comm.get('ptpu_comm_shards') or {}
    en = comm.get('ptpu_comm_enabled') or {}
    pads = comm.get('ptpu_comm_bucket_pad_elements') or {}
    per_op = comm.get('ptpu_comm_bytes_per_step') or {}
    modeled = comm.get('ptpu_comm_modeled_bytes_per_step') or {}
    frac = comm.get('ptpu_comm_compressed_fraction') or {}
    drop = comm.get('comm_bytes_drop_vs_per_param_psum') or {}
    breakdown = comm.get('comm_wire_breakdown') or {}
    pay_factor = comm.get('comm_payload_factor_vs_per_param_psum') or {}
    blocks = comm.get('ptpu_comm_block_elements') or {}
    engines = sorted({k.split(',')[0].split('=', 1)[1]
                      for k in list(buckets) + list(modeled)
                      if '=' in k})
    if not engines:
        out.append('  (no ptpu_comm_* gauges in this snapshot)')
    for eng in engines:
        key = f'engine={eng}'
        out.append(f'  engine {eng}: '
                   f'{int(buckets.get(key, 0))} buckets, '
                   f'{int(shards.get(key, 0))} shards'
                   + (' [rs/ag compiled in]' if en.get(key)
                      else ' [modeled only]'))
        rs = per_op.get(f'{key},op=reduce_scatter')
        ag = per_op.get(f'{key},op=all_gather')
        if rs is not None:
            out.append(f'    reduce_scatter {_fmt_bytes(rs)}  '
                       f'all_gather {_fmt_bytes(ag or 0)}  '
                       f'pad {int(pads.get(key, 0))} elems')
        base = modeled.get(f'{key},scheme=per_param_psum_fp32')
        new = modeled.get(f'{key},scheme=bucketed')
        if base and new is not None:
            out.append(f'    wire bytes: per-param psum(fp32) '
                       f'{_fmt_bytes(base)} -> bucketed '
                       f'{_fmt_bytes(new)} '
                       f'({100 * drop.get(eng, 1 - new / base):.1f}% '
                       'drop)')
        if key in frac:
            out.append(f'    compressed fraction: {frac[key]:.2f}')
        wb = breakdown.get(eng)
        if wb:
            blk = int(blocks.get(key, 0))
            out.append(
                f"    wire breakdown: payload "
                f"{_fmt_bytes(wb['payload_bytes'])} + scales "
                f"{_fmt_bytes(wb['scale_bytes'])} + pad "
                f"{_fmt_bytes(wb['pad_bytes'])} = "
                f"{_fmt_bytes(wb['total_bytes'])}"
                + (f'  (block {blk} elems)' if blk else ''))
            if eng in pay_factor:
                out.append(f'    payload factor vs per-param '
                           f'psum(fp32): {pay_factor[eng]:.2f}x')
        # comm/compute overlap (ISSUE 10): schedule shape + the
        # modeled exposed-vs-hidden split (docs/performance.md
        # #comm-overlap)
        co = (comm.get('comm_overlap') or {}).get(eng)
        if co:
            if co.get('enabled'):
                out.append(
                    f"    comm overlap: ON — {co.get('groups', 0)} "
                    f"groups, {co.get('groups_in_flight', 0)} in "
                    f"flight (prefetch {co.get('prefetch_depth', 0)}"
                    + (f", chunk {co['chunk_elements']} elems"
                       if co.get('chunk_elements') else '') + ')')
            else:
                out.append('    comm overlap: off (every comm byte '
                           'exposed)')
            tot = co.get('total_comm_seconds', 0.0)
            out.append(
                f"    modeled comm: exposed "
                f"{co.get('exposed_comm_seconds', 0.0):.2e}s / hidden "
                f"{co.get('hidden_comm_seconds', 0.0):.2e}s of "
                f"{tot:.2e}s"
                + (f"  ({100 * co.get('hidden_comm_seconds', 0.0) / tot:.0f}% hidden)"
                   if tot else ''))
    if cache:
        out.append('persistent compile cache: '
                   + ('enabled at ' + str(cache.get('dir'))
                      if cache.get('enabled') else 'disabled'))
        out.append(f"  requests {cache.get('requests', 0)}  "
                   f"hits {cache.get('hits', 0)}  "
                   f"misses {cache.get('misses', 0)}  "
                   f"compile-seconds saved "
                   f"{cache.get('seconds_saved', 0.0)}")
    return '\n'.join(out)


def _comm_selftest():
    """CI smoke: publish real gauges through core.bucketing, snapshot
    via StepTelemetry, render, and assert the load-bearing numbers."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax.numpy as jnp
    from paddle_tpu.core import bucketing as B
    from paddle_tpu.profiler import StepTelemetry

    layout = B.BucketLayout.build(
        {'w': ((2048,), jnp.bfloat16), 'b': ((512,), jnp.bfloat16)},
        pad_to=8)
    B.publish_comm_gauges(layout, engine='selftest', n_shards=8,
                          comm_dtype=jnp.bfloat16, enabled=True)
    # int8 block-scaled wire (ISSUE 7): payload 4x below the fp32
    # psum baseline, scale + pad overhead reported beside it
    B.publish_comm_gauges(layout, engine='selftest_int8', n_shards=8,
                          comm_dtype='int8', enabled=True, block=256)
    # overlapped schedule (ISSUE 10): layer-grouped buckets, modeled
    # exposed < total comm seconds when enabled with >1 group
    ov_layout = B.BucketLayout.build(
        {'l.0.w': ((2048,), jnp.bfloat16),
         'l.1.w': ((2048,), jnp.bfloat16),
         'head.w': ((512,), jnp.bfloat16)},
        group_fn=B.layer_group_fn, pad_to=8)
    B.publish_overlap_gauges(ov_layout, engine='selftest', n_shards=8,
                             comm_dtype=jnp.bfloat16, enabled=True,
                             prefetch=2, chunk=1024)
    snap = StepTelemetry(publish=False).snapshot()
    comm, cache = _find_comm({'telemetry': {
        'comm': snap['comm'], 'compile_cache': snap['compile_cache']}})
    assert comm, 'StepTelemetry snapshot carries no comm section'
    drop = comm['comm_bytes_drop_vs_per_param_psum']['selftest']
    assert drop >= 0.40, drop   # the ISSUE 4 acceptance bar at bf16
    factor = comm['comm_payload_factor_vs_per_param_psum'][
        'selftest_int8']
    assert factor >= 4.0, factor   # the ISSUE 7 acceptance bar at int8
    wb = comm['comm_wire_breakdown']['selftest_int8']
    assert wb['scale_bytes'] > 0, wb
    assert wb['total_bytes'] == wb['payload_bytes'] \
        + wb['scale_bytes'] + wb['pad_bytes'], wb
    co = comm['comm_overlap']['selftest']
    assert co['enabled'] and co['groups'] == 3, co
    assert co['exposed_comm_seconds'] < co['total_comm_seconds'], co
    text = render_comm(comm, cache)
    assert 'engine selftest' in text, text
    assert 'drop' in text and 'reduce_scatter' in text, text
    assert 'wire breakdown' in text and 'payload factor' in text, text
    assert 'comm overlap: ON' in text and 'hidden' in text, text
    assert 'compile cache' in text, text
    print(text)
    print('health_dump comm selftest: OK')
    return 0


def comm_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py comm',
        description='render ptpu_comm_* gauges / compile-cache traffic '
                    'from a StepTelemetry snapshot or bench record')
    ap.add_argument('artifact', nargs='?',
                    help='StepTelemetry snapshot / bench record JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _comm_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    comm, cache = _find_comm(doc)
    if comm is None and cache is None:
        raise ValueError(
            'no comm/compile_cache telemetry in this artifact (expected '
            'a StepTelemetry snapshot or a bench record with '
            'detail.telemetry.comm — see docs/performance.md)')
    if args.json:
        print(json.dumps({'comm': comm, 'compile_cache': cache},
                         indent=2))
    else:
        print(render_comm(comm, cache))
    return 0


def _find_serve(doc):
    """Accepts a StepTelemetry snapshot, a bench record, or a bare
    serve_snapshot dict; returns the ptpu_serve_* dict or None."""
    if not isinstance(doc, dict):
        return None
    if any(k.startswith('ptpu_serve_') for k in doc):
        return doc
    for path in (('serve',), ('telemetry', 'serve'),
                 ('detail', 'telemetry', 'serve'),
                 ('parsed', 'detail', 'telemetry', 'serve'),
                 ('legs', 'gpt_serve_throughput', 'telemetry_serve'),
                 ('parsed', 'legs', 'gpt_serve_throughput',
                  'telemetry_serve')):
        d = doc
        for k in path:
            d = d.get(k) if isinstance(d, dict) else None
        if isinstance(d, dict) and any(k.startswith('ptpu_serve_')
                                       for k in d):
            return d
    return None


def render_serve(s):
    """Human rendering of the ptpu_serve_* gauges (docs/serving.md
    metrics table)."""
    def v(name, default=0):
        return s.get(f'ptpu_serve_{name}', default)
    out = ['serving engine (continuous batching over the paged KV pool)']
    out.append(
        f"  decode throughput: {v('decode_tokens_per_sec'):.1f} tok/s "
        f"over {int(v('decode_steps_total'))} batched steps "
        f"({int(v('decode_tokens_total'))} tokens)")
    ttft = s.get('ptpu_serve_ttft_seconds') or {}
    mean_ms = ttft.get('mean_ms')
    out.append(
        f"  time-to-first-token: "
        + (f"{mean_ms:.1f} ms mean over {ttft.get('count', 0)} requests"
           if mean_ms is not None else "(no completed requests)"))
    out.append(
        f"  batch occupancy: {100 * v('batch_occupancy'):.1f}% of "
        f"{int(v('batch_slots'))} decode slots; "
        f"{int(v('requests_in_flight'))} in flight, "
        f"{int(v('requests_waiting'))} waiting")
    out.append(
        f"  KV pool: {int(v('kv_pages_in_use'))}/"
        f"{int(v('kv_pages_total'))} pages in use "
        f"({100 * v('kv_page_utilization'):.1f}% mean), "
        f"high water {int(v('kv_pages_high_water'))}")
    if v('kv_pool_bytes'):
        out.append(
            f"  KV pool bytes: {_fmt_bytes(v('kv_pool_bytes'))} "
            f"({_fmt_bytes(v('kv_bytes_per_token'))}/token across "
            f"layers — int8 pools carry scale buffers in this number)")
    out.append(
        f"  lifetime: {int(v('requests_completed_total'))}/"
        f"{int(v('requests_submitted_total'))} requests completed, "
        f"{int(v('preemptions_total'))} preemptions, "
        f"{int(v('prefill_tokens_total'))} prefill tokens in "
        f"{int(v('prefill_chunks_total'))} chunks")
    # prefix cache + speculative decode (ISSUE 9)
    hits, misses = int(v('prefix_hits')), int(v('prefix_misses'))
    if hits or misses:
        rate = s.get('prefix_hit_rate')
        if rate is None and hits + misses:
            rate = hits / (hits + misses)
        out.append(
            f"  prefix cache: {hits} hits / {misses} misses "
            f"({100 * (rate or 0):.1f}% hit-rate), "
            f"{int(v('prefix_hit_tokens_total'))} prompt tokens served "
            f"from cache; {int(v('prefix_shared_pages'))} shared + "
            f"{int(v('prefix_cached_pages'))} cached pages now")
    prop = int(v('spec_proposed_tokens_total'))
    if prop:
        acc = int(v('spec_accepted_tokens_total'))
        rate = s.get('spec_acceptance_rate')
        if rate is None:
            rate = acc / prop
        out.append(
            f"  speculative decode: {acc}/{prop} draft tokens accepted "
            f"({100 * rate:.1f}% acceptance)")
    # fused decode windows (ISSUE 19): k iterations per dispatch,
    # one host fetch per window
    fw = int(v('fused_windows_total'))
    if fw:
        fi = int(v('fused_iterations_total'))
        out.append(
            f"  fused decode: {fi} iterations in {fw} windows "
            f"(mean k {fi / fw:.1f}, configured "
            f"{int(v('fused_k')) or 1}), "
            f"{int(v('fused_tokens_total'))} tokens — "
            f"one host fetch per window")
    # tiered KV cache (ISSUE 20): host-RAM spill tier under the paged
    # pool — rendered only when the engine attached a host tier, so
    # tierless dumps are unchanged
    if 'ptpu_serve_tier_host_pages' in s:
        out.append(
            f"  host KV tier: {int(v('tier_host_used_pages'))}/"
            f"{int(v('tier_host_pages'))} host pages used, "
            f"{int(v('tier_resident_pages'))} resident in the radix "
            f"chain, {int(v('tier_spill_inflight_pages'))} spill "
            f"in flight")
        sp, fp = int(v('tier_spilled_pages_total')), \
            int(v('tier_fetched_pages_total'))
        if sp or fp:
            out.append(
                f"  tier transfers: {sp} pages "
                f"({_fmt_bytes(v('tier_spilled_bytes_total'))}) "
                f"spilled, {fp} pages "
                f"({_fmt_bytes(v('tier_fetched_bytes_total'))}) "
                f"fetched back; {int(v('tier_resurrected_pages_total'))} "
                f"pages / {int(v('tier_resurrected_tokens_total'))} "
                f"tokens resurrected instead of re-prefilled")
    # SLO percentile section (bucket-interpolated p50/p90/p99 from the
    # ptpu_serve_* histograms — docs/serving.md#slo-metrics)
    slo_rows = []
    for name, label in (('queue_wait_seconds', 'queue wait'),
                        ('ttft_seconds', 'ttft'),
                        ('tpot_seconds', 'tpot'),
                        ('e2e_seconds', 'e2e')):
        h = s.get(f'ptpu_serve_{name}') or {}
        if h.get('count') and h.get('p50_ms') is not None:
            slo_rows.append(
                f"    {label:<12} p50 {h['p50_ms']:>9.2f}  "
                f"p90 {h['p90_ms']:>9.2f}  p99 {h['p99_ms']:>9.2f}  "
                f"(n={h['count']})")
    if slo_rows:
        out.append('  SLO percentiles (ms, bucket-interpolated):')
        out.extend(slo_rows)
    pre = s.get('ptpu_serve_preemptions_per_request') or {}
    if pre.get('count'):
        out.append(
            f"  preemptions/request: p50 {pre.get('p50', 0):.1f} "
            f"p90 {pre.get('p90', 0):.1f} p99 {pre.get('p99', 0):.1f}")
    tl = s.get('timeline') or {}
    if tl.get('iterations'):
        out.append(
            f"  scheduler timeline (last {tl.get('window', 0)} of "
            f"{tl['iterations']} iterations): "
            f"occupancy {100 * tl.get('mean_occupancy', 0):.1f}%, "
            f"pool {100 * tl.get('mean_pool_utilization', 0):.1f}%, "
            f"{tl.get('prefill_tokens', 0)} prefill + "
            f"{tl.get('decode_tokens', 0)} decode tokens, "
            f"{tl.get('admissions', 0)} admissions, "
            f"{tl.get('preemptions', 0)} preemptions, "
            f"max waiting {tl.get('max_waiting', 0)}")
    # serving step-wall ledger + goodput + decode roofline (ISSUE 17):
    # serve_snapshot() merges these beside the gauges when an engine's
    # ledger has observed iterations — same renderer as the engine's
    if s.get('ledger') or s.get('goodput') or s.get('roofline'):
        _repo_root_on_path()
        from paddle_tpu.serving.ledger import render_serve_ledger
        out.append(render_serve_ledger(
            {'ledger': s.get('ledger') or {},
             'goodput': s.get('goodput') or {},
             'roofline': s.get('roofline') or {}}))
    return '\n'.join(out)


def _serve_selftest():
    """CI smoke: drive the REAL serving engine end to end on the CPU
    fallback path — mixed-length prompts through continuous batching —
    then assert the full observatory: gauges + SLO percentiles +
    timeline through StepTelemetry, JSON-lines/chrome trace export
    with engine-equivalent reconstruction, and the stalled-request
    watchdog's serve_report artifact (ISSUE 6)."""
    import tempfile
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import ServingEngine, ServingConfig
    from paddle_tpu.serving.request_trace import load_trace, reconstruct
    from paddle_tpu.profiler import StepTelemetry

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    # shared system prompt so the prefix cache hits, and speculative
    # decoding on so acceptance shows up in gauges/rendering (ISSUE 9)
    system = list(rng.randint(1, 64, 8))
    prompts = [system + list(rng.randint(1, 64, n)) for n in (3, 7, 5)]
    eng = ServingEngine(model, ServingConfig(page_size=8,
                                             max_batch_size=2,
                                             prefill_chunk=8,
                                             spec_k=4))
    outs = eng.generate(prompts, max_new_tokens=4, top_k=0)
    assert all(len(o) == len(p) + 4 for o, p in zip(outs, prompts))
    snap = StepTelemetry(publish=False).snapshot()
    serve = _find_serve({'telemetry': {'serve': snap['serve']}})
    assert serve, 'StepTelemetry snapshot carries no serve section'
    assert serve['ptpu_serve_requests_completed_total'] == 3, serve
    assert serve['ptpu_serve_decode_tokens_per_sec'] > 0, serve
    assert serve['ptpu_serve_ttft_seconds'].get('p99_ms') is not None
    assert serve['ptpu_serve_e2e_seconds']['count'] == 3, serve
    assert serve['timeline']['iterations'] > 0, serve
    # ISSUE 9: prefix hit-rate + spec acceptance reach the snapshot
    assert serve['ptpu_serve_prefix_hits'] >= 2, serve
    assert serve['prefix_hit_rate'] is not None, serve
    assert serve['ptpu_serve_prefix_hit_tokens_total'] >= 16, serve
    # serving ledger + goodput + roofline (ISSUE 17): the live engine's
    # ledger reaches the snapshot — components reconcile, the goodput
    # identity holds, and the decode roofline reports absolute GB/s
    led = serve.get('ledger')
    assert led and 'serve' in led, serve.keys()
    acct = led['serve']
    assert acct['wall_seconds'] > 0, acct
    assert set(acct['components']) == {
        'compute', 'host_fetch', 'schedule', 'page_stream',
        'residue'}, acct
    assert acct['host_bound_fraction'] is not None, acct
    gp = serve.get('goodput')
    assert gp and gp['delivered_tokens'] + gp['wasted_tokens'] \
        == gp['emitted_tokens'], gp
    roof = (serve.get('roofline') or {}).get('serve')
    assert roof and roof['decode_bytes_per_iteration'] > 0, roof
    text = render_serve(serve)
    assert 'decode throughput' in text and 'time-to-first-token' in text
    assert '3/3 requests completed' in text, text
    assert 'SLO percentiles' in text and 'scheduler timeline' in text
    assert 'prefix cache:' in text and 'hit-rate' in text, text
    if serve.get('ptpu_serve_spec_proposed_tokens_total'):
        assert 'speculative decode:' in text, text
    assert 'serving ledger' in text and 'goodput:' in text, text
    assert 'roofline[serve]' in text, text

    # -- trace export round-trips and reconstructs the engine's truth
    with tempfile.TemporaryDirectory() as td:
        paths = eng.export_trace(
            jsonl_path=os.path.join(td, 'serve.jsonl'),
            chrome_path=os.path.join(td, 'serve.trace.json'))
        _hdr, events = load_trace(paths['jsonl'])
        table = reconstruct(events)
        assert len(table) == 3, table
        for req in eng.scheduler.finished:
            r = table[req.id]
            assert r['tokens_generated'] == len(req.generated), r
            assert r['preemptions'] == req.preemptions, r
            assert abs(r['ttft_s'] - (req.first_token_time
                                      - req.submit_time)) < 1e-9, r
        with open(paths['chrome']) as f:
            doc = json.load(f)
        assert any(e.get('cat') == 'serve_request'
                   for e in doc['traceEvents']), 'no request tracks'
    eng.shutdown()

    # -- fused decode windows (ISSUE 19): the window counters reach
    # the gauges and the renderer draws the fused-window line
    eng2 = ServingEngine(model, ServingConfig(page_size=8,
                                              max_batch_size=4,
                                              prefill_chunk=8,
                                              fused_k=4))
    outs2 = eng2.generate(prompts, max_new_tokens=6, top_k=0)
    assert all(len(o) == len(p) + 6 for o, p in zip(outs2, prompts))
    st2 = eng2.stats()
    assert st2['fused_windows_total'] > 0, st2
    snap2 = StepTelemetry(publish=False).snapshot()
    serve2 = _find_serve({'telemetry': {'serve': snap2['serve']}})
    assert serve2['ptpu_serve_fused_windows_total'] \
        == st2['fused_windows_total'], serve2
    assert serve2['ptpu_serve_fused_k'] == 4, serve2
    text2 = render_serve(serve2)
    assert 'fused decode:' in text2 and 'one host fetch' in text2, text2
    eng2.shutdown()

    # -- tiered KV cache (ISSUE 20): spill a finished request's pages
    # to the host tier, resurrect them on the repeat prompt, and assert
    # the tier gauges/counters reach the snapshot and the renderer
    # draws the host-tier lines. Also: the tierless engines above must
    # NOT have published tier gauges (checked on serve2's keys)
    assert not any('tier' in k for k in serve2), serve2.keys()
    eng3 = ServingEngine(model, ServingConfig(page_size=8,
                                              max_batch_size=2,
                                              prefill_chunk=8,
                                              host_tier_pages=16))
    long_prompt = list(rng.randint(1, 64, 17))
    out_a = eng3.generate([long_prompt], max_new_tokens=4, top_k=0)
    spilled = eng3.pool.spill_lru(sync=True)
    assert spilled >= 2, spilled
    out_b = eng3.generate([long_prompt], max_new_tokens=4, top_k=0)
    assert out_a == out_b, (out_a, out_b)
    st3 = eng3.pool.stats()
    assert st3['tier_spilled_pages_total'] >= 2, st3
    assert st3['tier_resurrected_pages_total'] >= 2, st3
    snap3 = StepTelemetry(publish=False).snapshot()
    serve3 = _find_serve({'telemetry': {'serve': snap3['serve']}})
    assert serve3['ptpu_serve_tier_host_pages'] == 16, serve3
    assert serve3['ptpu_serve_tier_spilled_pages_total'] >= 2, serve3
    assert serve3['ptpu_serve_tier_fetched_pages_total'] >= 2, serve3
    assert serve3['ptpu_serve_tier_resurrected_tokens_total'] >= 16, \
        serve3
    text3 = render_serve(serve3)
    assert 'host KV tier:' in text3 and 'tier transfers:' in text3, text3
    assert 'resurrected instead of re-prefilled' in text3, text3
    eng3.shutdown()

    # -- stalled-request watchdog: deterministic clock, a request aged
    # past the deadline produces a serve_report that classifies/renders
    t = {'now': 0.0}

    def fake_clock():
        t['now'] += 1e-6
        return t['now']

    with tempfile.TemporaryDirectory() as td:
        eng2 = ServingEngine(model, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=8,
            request_deadline_s=5.0, report_dir=td, clock=fake_clock))
        eng2.submit(prompts[0], max_new_tokens=2)
        t['now'] += 10.0                 # age it past the deadline
        eng2.step()                      # watchdog fires this sweep
        report = eng2.last_serve_report
        assert report and report['kind'] == 'serve_report', report
        assert report['request']['age_s'] > 5.0, report['request']
        assert report['trace'] and report['pool'], report
        assert classify(report) == 'serve_report'
        rendered = render(report)
        assert 'SERVE REPORT' in rendered and 'deadline' in rendered
        assert report['path'] and os.path.exists(report['path']), report
        with open(report['path']) as f:
            assert classify(json.load(f)) == 'serve_report'
        while eng2.scheduler.has_work:   # drain; request still finishes
            eng2.step()
        eng2.shutdown()
    print(text)
    print('health_dump serve selftest: OK')
    return 0


def serve_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py serve',
        description='render ptpu_serve_* serving gauges from a '
                    'StepTelemetry snapshot or bench record')
    ap.add_argument('artifact', nargs='?',
                    help='StepTelemetry snapshot / bench record JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _serve_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    serve = _find_serve(doc)
    if serve is None:
        raise ValueError(
            'no serving telemetry in this artifact (expected a '
            'StepTelemetry snapshot with a serve section or a bench '
            'record with legs.gpt_serve_throughput — docs/serving.md)')
    if args.json:
        print(json.dumps(serve, indent=2))
    else:
        print(render_serve(serve))
    return 0


def _find_tenants(doc):
    """Locate a serve section that carries the multi-tenant layer
    (ISSUE 15): serve_snapshot()['tenants'] / ['tenancy'] or the
    bench gpt_serve_tenants leg's telemetry."""
    s = _find_serve(doc)
    if s is not None and ('tenants' in s or 'tenancy' in s
                          or 'ptpu_serve_degrade_stage' in s):
        return s
    if not isinstance(doc, dict):
        return None
    for path in (('legs', 'gpt_serve_tenants', 'telemetry_serve'),
                 ('parsed', 'legs', 'gpt_serve_tenants',
                  'telemetry_serve')):
        d = doc
        for k in path:
            d = d.get(k) if isinstance(d, dict) else None
        if isinstance(d, dict) and ('tenants' in d or 'tenancy' in d):
            return d
    return None


def render_tenants(s):
    """Human rendering of the per-tenant SLO layer: current ladder
    stage + pressure, then one row per tenant (policy, lifetime
    accounting, queue-wait/e2e percentiles from the tenant-labeled
    histograms) — docs/serving.md#multi-tenant."""
    ten = s.get('tenancy') or {}
    stage = int(s.get('ptpu_serve_degrade_stage',
                      ten.get('degrade_stage', 0)))
    names = ('normal', 'shed_spec', 'shrink_prefill', 'weighted_evict')
    out = ['multi-tenant serving (SLO-aware scheduler)']
    out.append(
        f"  degradation ladder: stage {stage} "
        f"({names[stage] if 0 <= stage < 4 else '?'}), pressure "
        f"{s.get('ptpu_serve_degrade_pressure', ten.get('pressure', 0.0)):.3f}, "
        f"{int(ten.get('stage_transitions', 0))} transitions")
    out.append(
        f"  quota deferrals {int(s.get('ptpu_serve_quota_deferrals', 0))}, "
        f"charged preemptions "
        f"{int(s.get('ptpu_serve_preemptions_charged', 0))}, "
        f"deadline rejects {int(s.get('ptpu_serve_deadline_rejects', 0))}"
        f" / misses {int(s.get('ptpu_serve_deadline_misses', 0))}")
    tenants = s.get('tenants') or {}
    if not tenants:
        out.append('  (no per-tenant traffic recorded)')
        return '\n'.join(out)
    out.append(
        f"  {'tenant':<12} {'prio':>4} {'done/sub':>9} {'defer':>5} "
        f"{'chg':>4} {'dl-rej':>6} {'dl-miss':>7} "
        f"{'qwait p99':>10} {'e2e p99':>10} {'bucket':>8}")
    for tid in sorted(tenants):
        row = tenants[tid]
        qw = (row.get('queue_wait') or {}).get('p99_ms')
        e2e = (row.get('e2e') or {}).get('p99_ms')
        lvl = row.get('bucket_level')
        out.append(
            f"  {tid[:12]:<12} {row.get('priority', 0):>4} "
            f"{row.get('completed', 0):>4}/{row.get('submitted', 0):<4} "
            f"{row.get('quota_deferrals', 0):>5} "
            f"{row.get('preemptions_charged', 0):>4} "
            f"{row.get('deadline_rejects', 0):>6} "
            f"{row.get('deadline_misses', 0):>7} "
            f"{(f'{qw:.1f}ms' if qw is not None else '-'):>10} "
            f"{(f'{e2e:.1f}ms' if e2e is not None else '-'):>10} "
            f"{(f'{lvl:.1f}' if lvl is not None else '-'):>8}")
    return '\n'.join(out)


def _tenants_selftest():
    """CI smoke: drive the REAL engine with a tenant policy map on a
    deterministic clock — a quota'd bulk tenant deferring behind its
    bucket while a priority tenant admits — then assert the tenant
    gauges/histograms reach serve_snapshot() and render, and walk a
    DegradeLadder through its stages to check the transition gauges."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import (ServingEngine, ServingConfig,
                                    DegradeLadder)
    from paddle_tpu.serving import metrics as serve_metrics

    paddle.seed(0)
    cfg = GPTConfig(vocab_size=64, hidden_size=32, num_layers=2,
                    num_heads=2, max_seq_len=64, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    model = GPTForCausalLM(cfg)
    model.eval()
    t = {'now': 0.0}

    def clk():
        t['now'] += 1e-5
        return t['now']

    rng = np.random.RandomState(0)
    eng = ServingEngine(model, ServingConfig(
        page_size=8, max_batch_size=2, prefill_chunk=8, clock=clk,
        tenants={'bulk': {'priority': 0, 'quota_tokens_per_s': 1.0,
                          'burst_tokens': 12.0, 'weight': 0.2},
                 'gold': {'priority': 2, 'weight': 2.0}}))
    reqs = [eng.submit(list(rng.randint(1, 64, 6)), max_new_tokens=4,
                       top_k=0, tenant_id=tid)
            for tid in ('bulk', 'bulk', 'gold')]
    steps = 0
    while eng.scheduler.has_work and steps < 400:
        eng.step()
        steps += 1
        if steps == 50:
            t['now'] += 30.0        # refill bulk's bucket mid-run
    assert all(r.state == 'finished' for r in reqs), \
        [r.state for r in reqs]
    st = eng.stats()
    assert st['quota_deferrals_total'] >= 1, st['quota_deferrals_total']
    assert st['tenancy']['tenants']['bulk']['quota_deferrals'] >= 1
    snap = serve_metrics.serve_snapshot()
    assert 'tenants' in snap and 'bulk' in snap['tenants'], \
        sorted(snap)
    assert snap['ptpu_serve_quota_deferrals'] >= 1, snap
    assert snap['tenants']['bulk'].get('e2e', {}).get('count') == 2, \
        snap['tenants']['bulk']
    text = render_tenants(snap)
    assert 'bulk' in text and 'gold' in text, text
    assert 'degradation ladder' in text, text
    eng.shutdown()

    # ladder walk-up/down with the transition gauge
    lad = DegradeLadder(window=2, up=(0.5, 0.7, 0.9),
                        down=(0.3, 0.5, 0.7), hold=2, clock=clk)
    for _ in range(8):
        lad.observe(1.0, 10, 2)
    assert lad.stage == 3, lad.stage
    serve_metrics.publish_degrade_stage(lad.stage, lad.pressure())
    snap = serve_metrics.serve_snapshot()
    assert snap['ptpu_serve_degrade_stage'] == 3, snap
    for _ in range(3 * 2 + 4):
        lad.observe(0.0, 0, 2)
    assert lad.stage == 0, lad.stage
    assert lad.transitions >= 6, lad.transitions
    text = render_tenants(snap)
    assert 'stage 3' in text, text
    print(text)
    print('health_dump tenants selftest: OK')
    return 0


def tenants_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py tenants',
        description='render the per-tenant SLO table + degradation '
                    'ladder stage from a serve snapshot or bench '
                    'record (docs/serving.md#multi-tenant)')
    ap.add_argument('artifact', nargs='?',
                    help='StepTelemetry snapshot / bench record JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _tenants_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    s = _find_tenants(doc)
    if s is None:
        raise ValueError(
            'no multi-tenant serving telemetry in this artifact '
            '(expected a serve section with tenants/tenancy keys — '
            'docs/serving.md#multi-tenant)')
    if args.json:
        print(json.dumps(s, indent=2))
    else:
        print(render_tenants(s))
    return 0


def _find_pallas(doc):
    """Locate the pallas routing section in a StepTelemetry snapshot or
    bench record ({'routes': {...}, 'active': [...]})."""
    if not isinstance(doc, dict):
        return None
    if 'routes' in doc and 'active' in doc:
        return doc
    for key in ('pallas', 'fused_primitives', 'telemetry', 'detail'):
        sub = doc.get(key)
        found = _find_pallas(sub)
        if found is not None:
            return found
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_pallas(leg)
            if found is not None:
                return found
    return None


def render_pallas(pallas):
    """Human view of the Pallas primitive routing counters — which
    fused kernels the traces picked vs reference fallbacks, so a
    silently-degraded route (e.g. the fused optimizer step falling back
    to the XLA chain) is one glance away."""
    out = ['Pallas fused primitives (trace-time routing decisions)']
    routes = pallas.get('routes') or {}
    for prim in sorted(routes):
        c = routes[prim]
        k, f = int(c.get('kernel', 0)), int(c.get('fallback', 0))
        verdict = 'KERNEL' if k and not f else \
            ('fallback' if f and not k else 'mixed')
        out.append(f'  {prim:<18} kernel {k:<6} fallback {f:<6} '
                   f'[{verdict}]')
    active = pallas.get('active') or []
    out.append('active (kernel route taken at least once): '
               + (', '.join(active) if active else '(none)'))
    return '\n'.join(out)


def _pallas_selftest():
    """CI smoke: force the fused routes on the CPU mesh (interpret
    mode), run one fused primitive of each family, and assert the
    routing counters + renderer show them as active."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax.numpy as jnp
    from paddle_tpu.core import flags
    from paddle_tpu.core import bucketing as B
    from paddle_tpu.core.tensor import Tensor
    from paddle_tpu.ops.pallas import fused_norm, scaffold
    from paddle_tpu.profiler import StepTelemetry
    import paddle_tpu as paddle

    flags.set_flags({'FLAGS_fused_optimizer': True,
                     'FLAGS_fused_layer_norm': True})
    try:
        x = jnp.ones((8, 33), jnp.float32)
        fused_norm.use_fused()          # route decision
        fused_norm.fused_layer_norm(x, jnp.ones((33,)),
                                    jnp.zeros((33,)), 1e-5)
        opt = paddle.optimizer.AdamW(learning_rate=0.01, parameters=[])
        p = jnp.ones((200,), jnp.float32)
        st = {k: jnp.asarray(v) for k, v in opt.init_state(
            Tensor(jnp.zeros((200,), jnp.float32))).items()}
        assert B.shard_update(opt, p, p * 0.1, st,
                              jnp.asarray(0.01))[0].shape == (200,)
        B.grad_stats(p)
    finally:
        flags.set_flags({'FLAGS_fused_optimizer': None,
                         'FLAGS_fused_layer_norm': None})
    snap = StepTelemetry(publish=False).snapshot()
    pallas = _find_pallas({'telemetry': {'pallas': snap['pallas']}})
    assert pallas, 'StepTelemetry snapshot carries no pallas section'
    for prim in ('layer_norm', 'optimizer_step', 'grad_stats'):
        assert prim in pallas['active'], (prim, pallas)
    text = render_pallas(pallas)
    assert 'optimizer_step' in text and 'KERNEL' in text, text
    assert 'active' in text, text
    print(text)
    print('health_dump pallas selftest: OK')
    return 0


def pallas_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py pallas',
        description='render ptpu_pallas_* fused-primitive routing '
                    'counters from a StepTelemetry snapshot or bench '
                    'record (docs/performance.md#fused-primitives)')
    ap.add_argument('artifact', nargs='?',
                    help='StepTelemetry snapshot / bench record JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _pallas_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    pallas = _find_pallas(doc)
    if pallas is None:
        raise ValueError(
            'no pallas routing telemetry in this artifact (expected a '
            'StepTelemetry snapshot with a pallas section or a bench '
            'record with detail.fused_primitives — '
            'docs/performance.md#fused-primitives)')
    if args.json:
        print(json.dumps(pallas, indent=2))
    else:
        print(render_pallas(pallas))
    return 0


def _find_cluster(doc):
    """Locate a cluster-router snapshot ({'placements': ..,
    'replicas': ..}) in a bench record / telemetry artifact."""
    if not isinstance(doc, dict):
        return None
    if 'placements' in doc and 'replicas' in doc:
        return doc
    for key in ('router', 'cluster', 'telemetry', 'detail'):
        found = _find_cluster(doc.get(key))
        if found is not None:
            return found
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_cluster(leg)
            if found is not None:
                return found
    return None


def render_cluster(c):
    """Human view of a router snapshot: placement counters (the
    ptpu_route_* family), per-replica occupancy, drain events —
    docs/serving.md#disaggregated-serving."""
    out = ['CLUSTER ROUTER — placement decisions']
    pl = c.get('placements') or {}
    hr = c.get('affinity_hit_rate')
    out.append(f"  affinity      {pl.get('affinity', 0):<6}"
               + (f" (hit-rate {100.0 * hr:.1f}%)"
                  if hr is not None else ''))
    out.append(f"  least_loaded  {pl.get('least_loaded', 0)}")
    out.append(f"  spills        {pl.get('spill', 0)}")
    out.append(f"  rejects       {c.get('rejects', 0)}")
    out.append(f"  drains        {pl.get('drain', 0)}  "
               f"(resubmitted {pl.get('resubmit', 0)} requests)")
    reqs = c.get('requests')
    if reqs is not None:
        out.append(f"  requests      {c.get('requests_done', 0)}"
                   f"/{reqs} done")
    out.append('replicas:')
    for rid, r in sorted((c.get('replicas') or {}).items()):
        occ = r.get('mean_occupancy')
        flags = []
        if r.get('hung'):
            flags.append('HUNG')
        if r.get('drained'):
            flags.append('DRAINED')
        line = (f"  {rid}: queue {r.get('queue_depth', 0)} "
                f"(waiting {r.get('waiting', 0)}, in-flight "
                f"{r.get('in_flight', 0)})  ")
        if occ is not None:
            line += f"occupancy {occ:.2f}  "
        line += (f"decode {r.get('decode_tokens') or 0}t "
                 f"prefill {r.get('prefill_tokens') or 0}t  "
                 f"digest {r.get('digest_size', 0)} chains  "
                 f"routed {r.get('requests_routed', 0)}"
                 + (('  [' + ' '.join(flags) + ']') if flags else ''))
        out.append(line)
    evs = c.get('drain_events') or []
    if evs:
        out.append('drain events:')
        for e in evs:
            out.append(f"  replica {e.get('replica_id')}: "
                       f"{e.get('reason')} — resubmitted "
                       f"{e.get('resubmitted', 0)} in-flight")
    return '\n'.join(out)


def _cluster_selftest():
    """CI smoke: a 2-replica in-process cluster on the tiny GPT, a
    shared-prefix stream through the prefix-affinity router, then the
    renderer — asserts the affinity hit-rate is real (> 0) and the
    ptpu_route_* counters landed in the registry."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu.models.gpt import GPTConfig, GPTForCausalLM
    from paddle_tpu.serving import ServingConfig, ServingEngine
    from paddle_tpu.serving.cluster import (ClusterRouter,
                                            LocalReplica,
                                            cluster_snapshot)

    cfg = GPTConfig(vocab_size=128, hidden_size=32, num_layers=2,
                    num_heads=4, max_seq_len=128, hidden_dropout=0.0,
                    attn_dropout=0.0, use_flash_attention=False)
    paddle.seed(0)
    model = GPTForCausalLM(cfg)
    model.eval()
    rng = np.random.RandomState(0)
    sys_a = list(rng.randint(1, 128, 16))
    sys_b = list(rng.randint(1, 128, 16))
    prompts = [(sys_a if i % 2 == 0 else sys_b)
               + list(rng.randint(1, 128, 4)) for i in range(8)]
    replicas = [
        LocalReplica(ServingEngine(model, ServingConfig(
            page_size=8, max_batch_size=2, prefill_chunk=16)), rid)
        for rid in ('r0', 'r1')]
    router = ClusterRouter(replicas, page_size=8, max_queue=16)
    outs = router.serve(prompts, max_new_tokens=4, top_k=0)
    assert len(outs) == len(prompts)
    snap = router.snapshot()
    assert snap['affinity_hit_rate'] and snap['affinity_hit_rate'] > 0, \
        snap
    text = render_cluster(_find_cluster({'legs': {
        'gpt_serve_cluster': {'router': snap}}}))
    assert 'affinity' in text and 'hit-rate' in text, text
    assert 'r0' in text and 'r1' in text, text
    reg = cluster_snapshot()
    assert reg and reg.get('ptpu_route_affinity_hits_total', 0) > 0, reg
    router.shutdown()
    print(text)
    print('health_dump cluster selftest: OK')
    return 0


def cluster_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py cluster',
        description='render cluster-router placement counters, '
                    'per-replica occupancy and drain events from a '
                    'router snapshot or bench record '
                    '(docs/serving.md#disaggregated-serving)')
    ap.add_argument('artifact', nargs='?',
                    help='router snapshot / bench record JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _cluster_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    cluster = _find_cluster(doc)
    if cluster is None:
        raise ValueError(
            'no cluster-router snapshot in this artifact (expected '
            'a ClusterRouter.snapshot() dict or a bench record with '
            'legs.gpt_serve_cluster.router — '
            'docs/serving.md#disaggregated-serving)')
    if args.json:
        print(json.dumps(cluster, indent=2))
    else:
        print(render_cluster(cluster))
    return 0


def numerics_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py numerics',
        description='render numerics / divergence artifacts')
    ap.add_argument('artifact', nargs='?',
                    help='numerics_report / divergence_report JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _numerics_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    print(json.dumps(doc, indent=2) if args.json else render(doc))
    return 0


def _find_mem(doc):
    """Locate a memory-census section: a bench leg's `memory` dict
    ({'sample': ..., 'phases': ...}) or an accountant-style snapshot."""
    if not isinstance(doc, dict):
        return None
    if 'sample' in doc and 'phases' in doc:
        return doc
    for key in ('memory', 'detail', 'telemetry'):
        found = _find_mem(doc.get(key))
        if found is not None:
            return found
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_mem(leg)
            if found is not None:
                return found
    return None


def render_mem(memdoc):
    """Human view of a memory census: per-phase high-water + the
    compiled-program activation-bytes line (docs/performance.md
    #remat-policy)."""
    out = ['Memory census']
    sample = memdoc.get('sample') or {}
    out.append(
        f"  in_use {_fmt_bytes(sample.get('bytes_in_use'))}   "
        f"live buffers {sample.get('live_buffers')}   "
        f"live bytes {_fmt_bytes(sample.get('live_bytes'))}")
    phases = memdoc.get('phases') or {}
    if phases:
        out.append(f"  {'phase':<24} {'calls':>6} {'high_water':>12} "
                   f"{'max_delta':>12}")
        for name, ph in sorted(phases.items(),
                               key=lambda kv: -(kv[1].get('high_water')
                                                or 0)):
            out.append(
                f"  {name[:24]:<24} {ph.get('calls') or 0:>6} "
                f"{_fmt_bytes(ph.get('high_water')):>12} "
                f"{_fmt_bytes(ph.get('max_delta')):>12}")
    acts = sample.get('activation_bytes') or memdoc.get(
        'activation_bytes') or {}
    if acts:
        out.append('  activation bytes (compiled-program temp buffers, '
                   'XLA buffer assignment):')
        for site, n in acts.items():
            out.append(f"    {site:<24} {_fmt_bytes(n)}")
    else:
        out.append('  activation bytes: (none recorded — no AOT '
                   'compile site ran)')
    return '\n'.join(out)


def _mem_selftest():
    """CI smoke: phase brackets + an AOT compile -> activation-bytes
    gauge -> renderer."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import jax
    import jax.numpy as jnp
    from paddle_tpu.core import memory as mem
    mem.reset()
    with mem.phase('engine.init', census=True):
        x = jnp.ones((64, 64))
    exe = jax.jit(lambda a: (a @ a).sum()).lower(x).compile()
    stats = mem.record_compiled_memory('selftest.step', exe)
    assert stats and stats['activation_bytes'] >= 0, stats
    assert mem.activation_bytes().get('selftest.step') == \
        stats['activation_bytes']
    s = mem.sample(count_buffers=True)
    assert 'activation_bytes' in s and 'selftest.step' in \
        s['activation_bytes'], s
    doc = {'memory': {'sample': s, 'phases': mem.accountant().phases()}}
    found = _find_mem(doc)
    assert found is not None
    text = render_mem(found)
    assert 'activation bytes' in text and 'selftest.step' in text, text
    print(text)
    print('health_dump mem selftest: OK')
    return 0


def mem_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py mem',
        description='render the memory census (per-phase high water + '
                    'compiled-program activation bytes) from a bench '
                    'record (docs/performance.md#remat-policy)')
    ap.add_argument('artifact', nargs='?',
                    help='bench record / telemetry JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _mem_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    memdoc = _find_mem(doc)
    if memdoc is None:
        raise ValueError(
            'no memory census in this artifact (expected a bench record '
            "with a 'memory' section — bench.py attaches one per leg)")
    if args.json:
        print(json.dumps(memdoc, indent=2))
    else:
        print(render_mem(memdoc))
    return 0


def _find_host(doc):
    """Locate an async-dispatch section: a bench leg's `host` record
    ({'dispatch_window', 'windowed', 'sync_loop', ...}) or the
    telemetry 'host' snapshot ({'sites', 'prefetch'})."""
    if not isinstance(doc, dict):
        return None
    if 'dispatch_window' in doc and ('windowed' in doc
                                     or 'sync_loop' in doc):
        return doc
    if 'sites' in doc and 'prefetch' in doc:
        return doc
    for key in ('host', 'detail', 'telemetry'):
        found = _find_host(doc.get(key))
        if found is not None:
            return found
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_host(leg)
            if found is not None:
                return found
    return None


def _fmt_gap(v):
    if v is None:
        return '-'
    return f'{v * 1000.0:.3f}ms' if isinstance(v, (int, float)) else str(v)


def render_host(h):
    """Human view of the async step pipeline: dispatch window /
    prefetch depth knobs, the sync-vs-windowed host-gap comparison, and
    host_bound_fraction (docs/performance.md#async-dispatch)."""
    out = ['Async step pipeline (host-gap view)']
    if 'dispatch_window' in h:          # bench detail.host shape
        out.append(f"  dispatch window {h.get('dispatch_window')}   "
                   f"device_lr {h.get('device_lr', False)}")
        pf = h.get('prefetch') or {}
        out.append(
            f"  prefetch depth {pf.get('depth')}   batches "
            f"{pf.get('batches')}   stalls {pf.get('stalls')}   "
            f"h2d {_fmt_bytes(pf.get('h2d_bytes'))}   ring reuses "
            f"{pf.get('ring_reuses')}")
        win = h.get('windowed') or {}
        sync = h.get('sync_loop') or {}
        out.append(f"  {'loop':<10} {'steps':>6} {'host_gap':>10} "
                   f"{'host_bound':>11} {'depth':>6}")
        out.append(
            f"  {'sync':<10} {sync.get('steps') or 0:>6} "
            f"{_fmt_gap(sync.get('host_gap_seconds')):>10} "
            f"{_fmt_frac(sync.get('host_bound_fraction')):>11} "
            f"{'1':>6}")
        out.append(
            f"  {'windowed':<10} {win.get('steps') or 0:>6} "
            f"{_fmt_gap(win.get('host_gap_seconds')):>10} "
            f"{_fmt_frac(win.get('host_bound_fraction')):>11} "
            f"{win.get('dispatch_depth_mean') or 0:>6.2f}")
        reduced = h.get('host_gap_reduced')
        if reduced is not None:
            out.append(f"  host gap reduced vs sync loop: "
                       f"{'YES' if reduced else 'NO'}")
        return '\n'.join(out)
    # telemetry snapshot shape: per-site monitors + prefetch totals
    sites = h.get('sites') or {}
    if sites:
        out.append(f"  {'site':<10} {'steps':>6} {'host_gap':>10} "
                   f"{'host_bound':>11} {'depth':>6}")
        for site, s in sorted(sites.items()):
            out.append(
                f"  {site:<10} {s.get('steps') or 0:>6} "
                f"{_fmt_gap(s.get('host_gap_seconds')):>10} "
                f"{_fmt_frac(s.get('host_bound_fraction')):>11} "
                f"{s.get('dispatch_depth_mean') or 0:>6.2f}")
    else:
        out.append('  (no engine dispatched asynchronously)')
    pf = h.get('prefetch') or {}
    out.append(
        f"  prefetch: loaders {pf.get('loaders')}   batches "
        f"{pf.get('batches')}   stalls {pf.get('stalls')}   "
        f"h2d {_fmt_bytes(pf.get('h2d_bytes'))}   ring reuses "
        f"{pf.get('ring_reuses')}")
    return '\n'.join(out)


def _fmt_frac(v):
    if v is None:
        return '-'
    return f'{v:.3f}'


def _host_selftest():
    """CI smoke: a windowed TrainStep loop fed by a DeviceLoader ->
    host-gap monitor + prefetch gauges -> renderer."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.core import async_step as A
    from paddle_tpu.core.tensor import Tensor  # noqa: F401
    from paddle_tpu.io import DeviceLoader
    from paddle_tpu.jit import TrainStep

    A.reset_prefetch_totals()
    paddle.seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=model.parameters())
    step = TrainStep(model,
                     lambda m, x, y: nn.functional.cross_entropy(
                         m(x), y),
                     opt, dispatch_window=2)
    rng = np.random.RandomState(0)
    batches = [(rng.rand(8, 8).astype('float32'),
                rng.randint(0, 4, (8,)).astype('int64'))
               for _ in range(4)]
    loader = DeviceLoader(batches, engine=step)
    last = None
    for b in loader:
        last = step.train_step(*b)
    step.flush()
    assert np.isfinite(last.result())
    snap = A.host_snapshot()
    assert snap['sites'].get('jit', {}).get('steps') == 4, snap
    assert snap['prefetch']['batches'] >= 4, snap
    text = render_host(snap)
    assert 'jit' in text and 'prefetch' in text, text
    print(text)
    bench_shape = {
        'dispatch_window': 2, 'device_lr': False,
        'prefetch': loader.stats(),
        'windowed': dict(snap['sites']['jit']),
        'sync_loop': {'steps': 3, 'host_gap_seconds': 0.01,
                      'host_bound_fraction': 0.9, 'ms_per_step': 12.0},
        'host_gap_reduced': True,
    }
    doc = {'legs': {'gpt1.3b_adamw': {'host': bench_shape}}}
    found = _find_host(doc)
    assert found is bench_shape
    text = render_host(found)
    assert 'host gap reduced' in text and 'windowed' in text, text
    print(text)
    print('health_dump host selftest: OK')
    return 0


def host_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py host',
        description='render the async-dispatch host-gap view (dispatch '
                    'window, prefetch depth, sync-vs-windowed host gap, '
                    'host_bound_fraction) from a bench record or '
                    'telemetry snapshot '
                    '(docs/performance.md#async-dispatch)')
    ap.add_argument('artifact', nargs='?',
                    help='bench record / telemetry JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _host_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    hostdoc = _find_host(doc)
    if hostdoc is None:
        raise ValueError(
            'no async-dispatch section in this artifact (expected a '
            "bench record with detail.host or a telemetry snapshot "
            "with a 'host' section — ISSUE 13 bench legs attach one)")
    if args.json:
        print(json.dumps(hostdoc, indent=2))
    else:
        print(render_host(hostdoc))
    return 0


def _find_pp(doc):
    """Locate a pipeline-schedule census: a schedule_model()/
    pipeline_snapshot() record ({'schedule', 'bubble_fraction', ...})
    in a bench leg's `pipeline` section, telemetry, or a
    tools/pipeline_bench.py record (scale legs and `sweep` list
    entries)."""
    if isinstance(doc, list):
        for v in doc:
            found = _find_pp(v)
            if found is not None:
                return found
        return None
    if not isinstance(doc, dict):
        return None
    if 'bubble_fraction' in doc and 'schedule' in doc:
        return doc
    for key in ('pipeline', 'detail', 'telemetry'):
        found = _find_pp(doc.get(key))
        if found is not None:
            return found
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_pp(leg)
            if found is not None:
                return found
    # pipeline_bench.py record: scale legs / sweep entries
    for v in doc.values():
        if isinstance(v, (dict, list)):
            found = _find_pp(v)
            if found is not None:
                return found
    return None


def render_pp(p):
    """Human view of the pipeline schedule census: schedule/virtual
    stages, tick counts and the modeled bubble fraction
    (docs/performance.md#pipeline-schedules)."""
    v = int(p.get('virtual_stages') or 1)
    out = ['Pipeline schedule (bubble view)']
    out.append(
        f"  schedule {p.get('schedule')}   pp {p.get('pp')}   "
        f"virtual stages {v}   A {p.get('accumulate_steps')}"
        + (f"   memory {p['memory_mode']}" if p.get('memory_mode')
           else ''))
    out.append(
        f"  scan ticks {p.get('ticks')}   warmup "
        f"{p.get('warmup_ticks', '-')}   chunk sub-steps "
        f"{p.get('chunk_ticks')} (useful {p.get('useful_chunk_ticks')})")
    bf = p.get('bubble_fraction')
    out.append(
        f"  modeled bubble fraction "
        f"{_fmt_frac(bf)}   in-flight peak "
        f"{p.get('inflight_peak', '-')} microbatches/device")
    if p.get('ppermute_steps'):
        out.append(
            f"  ring traffic {p['ppermute_steps']} ppermute hops/step "
            f"(~{v}x boundary crossings vs v=1)")
    if p.get('ms_per_step') is not None:
        out.append(
            f"  measured {p['ms_per_step']}ms/step   "
            f"{p.get('ms_per_tick')}ms/tick steady-state")
    return '\n'.join(out)


def _pp_selftest():
    """CI smoke: schedule model -> ptpu_pp_* gauges -> snapshot ->
    renderer, and the interleaved bubble shrink at iso (pp, A)."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    from paddle_tpu.distributed.fleet.meta_parallel.spmd_pipeline import (
        schedule_model, publish_schedule_gauges, pipeline_snapshot)

    m1 = schedule_model('1F1B', 4, 8)
    m2 = schedule_model('interleaved', 4, 8, 2)
    assert m1['ticks'] == 8 + 2 * 3 and m1['slots_per_chunk'] == 7, m1
    assert m2['bubble_fraction'] < m1['bubble_fraction'], (m1, m2)
    # monotone in v at iso (pp, A)
    m4 = schedule_model('interleaved', 4, 8, 4)
    assert m4['bubble_fraction'] < m2['bubble_fraction']
    publish_schedule_gauges(m2, engine='pipeline')
    snap = pipeline_snapshot()
    assert snap and snap['schedule'] == 'interleaved' \
        and snap['virtual_stages'] == 2, snap
    assert abs(snap['bubble_fraction'] - m2['bubble_fraction']) < 1e-9
    text = render_pp(snap)
    assert 'bubble fraction' in text and 'interleaved' in text, text
    print(text)
    # bench-record shape: the leg's pipeline section is found and
    # rendered the same way
    doc = {'legs': {'pp_sched': {'ms_per_step': 12.0,
                                 'ms_per_tick': 0.5,
                                 'pipeline': m2}}}
    found = _find_pp(doc)
    assert found is m2, found
    text = render_pp({**found, 'ms_per_step': 12.0, 'ms_per_tick': 0.5})
    assert 'ms/step' in text, text
    print(text)
    print('health_dump pp selftest: OK')
    return 0


def pp_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py pp',
        description='render the pipeline schedule census (schedule, '
                    'virtual stages, tick counts, modeled bubble '
                    'fraction) from a bench record or telemetry '
                    'snapshot (docs/performance.md#pipeline-schedules)')
    ap.add_argument('artifact', nargs='?',
                    help='bench record / telemetry JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _pp_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    ppdoc = _find_pp(doc)
    if ppdoc is None:
        raise ValueError(
            'no pipeline-schedule census in this artifact (expected a '
            "record with a 'pipeline' section — pipeline engines "
            'publish one; tools/pipeline_bench.py records one per leg)')
    if args.json:
        print(json.dumps(ppdoc, indent=2))
    else:
        print(render_pp(ppdoc))
    return 0


def _find_ledger(doc):
    """Locate a step-time ledger account (ISSUE 16): either a single
    StepLedger.account() record ({'wall_seconds', 'components', ...})
    from a bench leg's `ledger` section, or a ledger_snapshot() map
    ({engine: account}) from telemetry. Returns {engine: account}."""
    if isinstance(doc, list):
        for v in doc:
            found = _find_ledger(v)
            if found is not None:
                return found
        return None
    if not isinstance(doc, dict):
        return None
    if 'wall_seconds' in doc and isinstance(doc.get('components'), dict):
        return {doc.get('engine', 'step'): doc}
    for key in ('ledger', 'detail', 'telemetry'):
        found = _find_ledger(doc.get(key))
        if found is not None:
            return found
    if doc and all(isinstance(v, dict) and 'wall_seconds' in v
                   and 'components' in v for v in doc.values()):
        return doc   # a ledger_snapshot() {engine: account} map
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_ledger(leg)
            if found is not None:
                return found
    return None


def _ledger_selftest():
    """CI smoke: tiny jitted train loop -> ptpu_ledger_* gauges ->
    snapshot -> renderer; reconciliation invariant; bench-record
    locator; straggler-report rendering."""
    _repo_root_on_path()
    os.environ.setdefault('JAX_PLATFORMS', 'cpu')
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn as nn
    from paddle_tpu import jit as pjit
    from paddle_tpu.core.ledger import (ledger_snapshot, render_ledger,
                                        render_straggler_report)

    class M(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc = nn.Linear(16, 4)

        def forward(self, x):
            return self.fc(x)

    m = M()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=m.parameters())
    ts = pjit.TrainStep(
        m, lambda model, x, y: ((model(x) - y) ** 2).mean(), opt)
    x = paddle.to_tensor(np.random.RandomState(0).randn(
        8, 16).astype('float32'))
    y = paddle.to_tensor(np.random.RandomState(1).randn(
        8, 4).astype('float32'))
    for _ in range(6):
        ts.train_step(x, y)
    ts.flush()
    snap = ledger_snapshot()
    assert snap and 'jit' in snap, snap
    a = snap['jit']
    comps = a['components']
    assert set(comps) == {'compute', 'exposed_comm', 'bubble',
                          'host_gap', 'residue'}, comps
    wall = a['wall_seconds']
    assert wall > 0 and abs(sum(comps.values()) - wall) <= 0.10 * wall, a
    assert a['tokens_per_step'] == 128, a
    text = render_ledger(snap)
    assert 'engine: jit' in text and 'compute' in text, text
    print(text)
    # bench-record shape: detail.ledger account is found + rendered
    acct = ts._ledger.account()
    doc = {'legs': {'gpt1.3b_adamw': {'ledger': acct}}}
    found = _find_ledger(doc)
    assert found and 'jit' in found, found
    print(render_ledger(found))
    # straggler artifact rendering (the 2-rank path writes these)
    report = {'kind': 'straggler_report', 'step': 50, 'world_size': 2,
              'threshold': 1.25, 'median_wall_seconds': 0.010,
              'ranks': {'0': 0.010, '1': 0.030},
              'relative_wall': {'0': 1.0, '1': 3.0},
              'offending_ranks': [1]}
    text = render_straggler_report(report)
    assert 'STRAGGLER' in text and 'rank 1' in text, text
    print(text)
    print('health_dump ledger selftest: OK')
    return 0


def ledger_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py ledger',
        description='render the step-time ledger (compute/exposed-comm/'
                    'bubble/host-gap/residue decomposition + model '
                    'TFLOP/s and MFU) from a bench record or telemetry '
                    'snapshot, or a straggler_report artifact '
                    '(docs/observability.md#step-time-ledger)')
    ap.add_argument('artifact', nargs='?',
                    help='bench record / telemetry / straggler_report '
                         'JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true')
    args = ap.parse_args(argv)
    if args.selftest:
        return _ledger_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    _repo_root_on_path()
    from paddle_tpu.core.ledger import (render_ledger,
                                        render_straggler_report)
    if isinstance(doc, dict) and doc.get('kind') == 'straggler_report':
        print(json.dumps(doc, indent=2) if args.json
              else render_straggler_report(doc))
        return 0
    led = _find_ledger(doc)
    if led is None:
        raise ValueError(
            'no step-time ledger in this artifact (expected a record '
            "with a 'ledger' section — the engines publish one via "
            'flush(); bench.py attaches it to the headline leg)')
    if args.json:
        print(json.dumps(led, indent=2))
    else:
        print(render_ledger(led))
    return 0


def _find_alerts(doc):
    """Locate an alert block (ISSUE 18): an AlertManager.snapshot() /
    report() dict ({'rules': [...], 'events': [...]}), an
    alert_report.*.json artifact, or a bench leg's compact `alerts`
    summary ({'fired_total': ..}) — wrapped so the renderer always
    sees the same shape."""
    if isinstance(doc, list):
        for v in doc:
            found = _find_alerts(v)
            if found is not None:
                return found
        return None
    if not isinstance(doc, dict):
        return None
    if isinstance(doc.get('rules'), list) and 'events' in doc:
        return doc
    if 'fired_total' in doc and 'fired_by_severity' in doc:
        return {'summary': doc, 'rules': [], 'events': []}
    for key in ('alerts', 'alert_report', 'telemetry', 'detail'):
        found = _find_alerts(doc.get(key))
        if found is not None:
            return found
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_alerts(leg)
            if found is not None:
                return found
    return None


def _find_series_block(doc):
    """Locate a MetricHistory.export() block ({'name{labels}':
    {'kind', 't', 'v', ...}}) for the sparkline strip."""
    if isinstance(doc, list):
        for v in doc:
            found = _find_series_block(v)
            if found is not None:
                return found
        return None
    if not isinstance(doc, dict):
        return None
    if doc and all(isinstance(v, dict) and 'v' in v and 't' in v
                   for v in doc.values()):
        return doc
    for key in ('series', 'telemetry', 'detail'):
        found = _find_series_block(doc.get(key))
        if found is not None:
            return found
    if 'legs' in doc:
        for leg in (doc['legs'] or {}).values():
            found = _find_series_block(leg)
            if found is not None:
                return found
    return None


_STALE_SECTION_S = 60.0


def _stale_sections(doc, now_age_bound=_STALE_SECTION_S):
    """Group a MetricsRegistry.snapshot()'s per-series publish ages by
    metric-family prefix and flag families whose FRESHEST series is
    older than the bound — the source engine stopped publishing
    (the staleness-stamp satellite)."""
    metrics = (doc or {}).get('metrics')
    if not isinstance(metrics, dict):
        return []
    fam_age = {}
    for name, m in metrics.items():
        ages = [s.get('age_s') for s in (m.get('series') or ())
                if isinstance(s, dict) and s.get('age_s') is not None]
        if not ages:
            continue
        parts = name.split('_')
        fam = '_'.join(parts[:2]) + '_*' if len(parts) > 2 else name
        best = min(ages)
        fam_age[fam] = min(fam_age.get(fam, best), best)
    return sorted((fam, age) for fam, age in fam_age.items()
                  if age > now_age_bound)


def render_alerts(a, series=None, registry_snap=None):
    """Human view of an alert block: per-rule state table, the capped
    transition ring, optional history sparklines and stale-section
    flags — docs/observability.md#time-series--alerts."""
    out = ['ALERTS — rule states'
           + (f" (source {a['source']})" if a.get('source') else '')]
    rules = a.get('rules') or []
    if rules:
        for r in rules:
            state = r.get('state', '?')
            mark = {'firing': '!!', 'pending': ' ~'}.get(state, '  ')
            lv = r.get('last_value')
            out.append(
                f"{mark} {r.get('rule', '?'):<24} {state:<8} "
                f"{r.get('severity', '?'):<8} "
                f"fired x{r.get('fired', 0)}"
                + (f"  last {lv:.4g}" if isinstance(lv, (int, float))
                   else '')
                + (f"  [{','.join(map(str, r['last_series']))}]"
                   if r.get('last_series') else ''))
    summ = a.get('summary')
    if summ:
        out.append(f"  fired {summ.get('fired_total', 0)} "
                   f"(critical {summ.get('fired_critical', 0)}); "
                   f"active: {summ.get('active') or 'none'}")
    evs = a.get('events') or []
    if evs:
        out.append('transitions:')
        for e in evs[-20:]:
            v = e.get('value')
            out.append(
                f"  t={e.get('t')}: {e.get('rule')} {e.get('event')} "
                f"({e.get('severity')})"
                + (f" value {v:.4g}" if isinstance(v, (int, float))
                   else '')
                + (f" on {e.get('metric')}" if e.get('metric') else ''))
    if series:
        _repo_root_on_path()
        from paddle_tpu.core.timeseries import sparkline
        out.append('history (downsampled):')
        for key in sorted(series)[:16]:
            s = series[key]
            vals = s.get('v') or []
            if not vals:
                continue
            out.append(f"  {key:<48} {sparkline(vals, width=24)} "
                       f"last {s.get('last'):.4g}"
                       if isinstance(s.get('last'), (int, float))
                       else f"  {key:<48} {sparkline(vals, width=24)}")
        if len(series) > 16:
            out.append(f"  ... {len(series) - 16} more series")
    stale = _stale_sections(registry_snap) if registry_snap else []
    if stale:
        out.append('STALE sections (no publish within '
                   f'{_STALE_SECTION_S:.0f}s — source engine quiet):')
        for fam, age in stale:
            out.append(f"  {fam:<32} freshest series {age:.1f}s old")
    if len(out) == 1:
        out.append('  (no rules or events in this artifact)')
    return '\n'.join(out)


def _alerts_selftest():
    """CI smoke: a gauge on a private registry with an injected clock
    walks a pool-pressure rule fire -> sustain -> hysteretic clear;
    the renderer shows the firing row, the transitions, a sparkline
    strip, and a stale-section flag — all deterministic."""
    _repo_root_on_path()
    from paddle_tpu.core import monitor as mon
    from paddle_tpu.core.alerts import AlertManager, AlertRule

    t = [0.0]
    prev_clock = mon.set_time_fn(lambda: t[0])  # publish stamps too
    reg = mon.MetricsRegistry()
    hist = reg.enable_history(capacity=64, clock=lambda: t[0])
    g = reg.gauge('ptpu_serve_kv_page_utilization', help='pool')
    rule = AlertRule('kv_pool_pressure',
                     metric='ptpu_serve_kv_page_utilization',
                     op='>=', value=0.97, clear_value=0.8, for_s=2.0,
                     clear_for_s=1.0, severity='critical')
    am = AlertManager(hist, rules=[rule], clock=lambda: t[0],
                      registry=reg, source='selftest')
    events = []
    # ramp to saturation, hold (sustain), then release (clear)
    for i, util in enumerate([0.3, 0.6, 0.99, 0.99, 0.99, 0.99,
                              0.5, 0.5, 0.5]):
        t[0] = float(i)
        g.set(util)
        events += hist.tick() or []
    kinds = [e['event'] for e in am.snapshot()['events']]
    assert kinds == ['fired', 'resolved'], kinds
    st = am.snapshot()['rules'][0]
    assert st['state'] == 'ok' and st['fired'] == 1, st
    assert reg.get('ptpu_alert_fired_total').value(
        rule='kv_pool_pressure', severity='critical') == 1
    assert reg.get('ptpu_alert_active').value(
        rule='kv_pool_pressure', severity='critical') == 0
    # render mid-fire state too: re-fire and leave it active
    t[0] = 20.0
    g.set(1.0)
    hist.tick()
    t[0] = 23.0
    g.set(1.0)
    hist.tick()
    assert am.active(), am.snapshot()
    # stale-section flag: a family that stopped publishing
    reg.gauge('ptpu_dead_engine_signal', help='quiet').set(1.0)
    t[0] = 200.0
    try:
        text = render_alerts(am.snapshot(),
                             series=hist.export(max_points=24),
                             registry_snap=reg.snapshot())
    finally:
        mon.set_time_fn(prev_clock)
    assert 'kv_pool_pressure' in text and 'firing' in text, text
    assert 'fired' in text and 'resolved' in text, text
    assert 'history (downsampled)' in text, text
    assert 'ptpu_dead_*' in text, text
    print(text)
    print('health_dump alerts selftest: OK')
    return 0


def alerts_main(argv):
    ap = argparse.ArgumentParser(
        prog='health_dump.py alerts',
        description='render alert rule states, fire/resolve '
                    'transitions, history sparklines and stale '
                    'metric sections from an alert_report artifact, '
                    'bench record or telemetry snapshot '
                    '(docs/observability.md#time-series--alerts)')
    ap.add_argument('artifact', nargs='?',
                    help='alert_report / bench record / snapshot JSON')
    ap.add_argument('--json', action='store_true')
    ap.add_argument('--selftest', action='store_true',
                    help='walk fire -> sustain -> hysteretic clear on '
                         'an injected clock')
    args = ap.parse_args(argv)
    if args.selftest:
        return _alerts_selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    with open(args.artifact) as f:
        doc = json.load(f)
    alerts = _find_alerts(doc)
    if alerts is None:
        raise ValueError(
            'no alert block in this artifact (expected an '
            'alert_report.*.json, an AlertManager.snapshot(), or a '
            "bench record with a leg-level 'alerts' summary — "
            'docs/observability.md#time-series--alerts)')
    if args.json:
        print(json.dumps(alerts, indent=2))
    else:
        print(render_alerts(alerts, series=_find_series_block(doc)))
    return 0


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    if argv and argv[0] == 'alerts':
        return alerts_main(argv[1:])
    if argv and argv[0] == 'ledger':
        return ledger_main(argv[1:])
    if argv and argv[0] == 'pp':
        return pp_main(argv[1:])
    if argv and argv[0] == 'host':
        return host_main(argv[1:])
    if argv and argv[0] == 'mem':
        return mem_main(argv[1:])
    if argv and argv[0] == 'numerics':
        return numerics_main(argv[1:])
    if argv and argv[0] == 'comm':
        return comm_main(argv[1:])
    if argv and argv[0] == 'serve':
        return serve_main(argv[1:])
    if argv and argv[0] == 'tenants':
        return tenants_main(argv[1:])
    if argv and argv[0] == 'cluster':
        return cluster_main(argv[1:])
    if argv and argv[0] == 'pallas':
        return pallas_main(argv[1:])
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument('artifact', nargs='?',
                    help='hang/OOM report JSON or workerlog .jsonl')
    ap.add_argument('--json', action='store_true',
                    help='echo the parsed artifact as JSON')
    ap.add_argument('--level', default=None,
                    help='level filter for .jsonl logs (e.g. ERROR)')
    ap.add_argument('--selftest', action='store_true',
                    help='exercise recorder/accountant/logs end to end')
    args = ap.parse_args(argv)
    if args.selftest:
        return _selftest()
    if not args.artifact:
        ap.error('artifact path required (or --selftest)')
    if args.artifact.endswith('.jsonl'):
        print(render_log(args.artifact, level=args.level))
        return 0
    with open(args.artifact) as f:
        doc = json.load(f)
    print(json.dumps(doc, indent=2) if args.json else render(doc))
    return 0


if __name__ == '__main__':
    sys.exit(main())
