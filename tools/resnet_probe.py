"""ResNet-50 roofline probe (VERDICT r5 #2): what can this chip do on
ResNet-50-shaped work, independent of the framework? A minimal pure-JAX
ResNet-50 (train step: conv+BN(batch-stats)+ReLU, SGD-momentum fused) in
both layouts and batch sizes, vs the framework bench. The gap between
the best probe number and paddle_tpu's bench is framework overhead; the
gap between the probe and the chip's asymptotic 3x3-conv rate (~20-23%
MFU, PARITY.md) is ResNet's own shape mix (7x7 stem, 1x1 projections,
small late spatials)."""
import sys
import time
import functools

import numpy as np
import jax
import jax.numpy as jnp

V5E_PEAK = 197.0


def conv(x, w, stride=1, layout='NHWC'):
    dn = ('NHWC', 'HWIO', 'NHWC') if layout == 'NHWC' \
        else ('NCHW', 'OIHW', 'NCHW')
    kh = w.shape[0] if layout == 'NHWC' else w.shape[2]
    pad = [(kh // 2, kh // 2)] * 2 if kh > 1 else [(0, 0)] * 2
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), pad, dimension_numbers=dn)


def bn_relu(x, scale, bias, axis):
    m = x.mean(axis, keepdims=True)
    v = x.var(axis, keepdims=True)
    y = (x - m) * jax.lax.rsqrt(v + 1e-5) * scale + bias
    return jax.nn.relu(y)


def make_resnet50(layout='NHWC', dtype=jnp.bfloat16):
    """Returns (params, apply_fn). Weights in HWIO/OIHW by layout."""
    rng = np.random.RandomState(0)
    cfg = [(3, 64, 256, 1), (4, 128, 512, 2), (6, 256, 1024, 2),
           (3, 512, 2048, 2)]
    params = []

    def W(kh, kw, cin, cout):
        w = rng.randn(kh, kw, cin, cout).astype('float32') \
            * np.sqrt(2.0 / (kh * kw * cin))
        if layout == 'NCHW':
            w = w.transpose(3, 2, 0, 1)
        return jnp.asarray(w, dtype)

    def S(c):
        return (jnp.ones((c,), dtype), jnp.zeros((c,), dtype))

    stem = (W(7, 7, 3, 64), *S(64))
    params.append(stem)
    strides = []
    cin = 64
    for nblk, mid, cout, stride in cfg:
        for i in range(nblk):
            s = stride if i == 0 else 1
            blk = {
                'c1': (W(1, 1, cin, mid), *S(mid)),
                'c2': (W(3, 3, mid, mid), *S(mid)),
                'c3': (W(1, 1, mid, cout), *S(cout)),
            }
            if i == 0:
                blk['proj'] = (W(1, 1, cin, cout), *S(cout))
            params.append(blk)
            strides.append(s)
            cin = cout
    head = jnp.asarray(rng.randn(2048, 1000).astype('float32') * 0.01,
                       dtype)
    params.append(head)
    caxis = (0, 1, 2) if layout == 'NHWC' else (0, 2, 3)

    def brelu(x, sc, bi):
        shape = (1, 1, 1, -1) if layout == 'NHWC' else (1, -1, 1, 1)
        m = x.mean(caxis, keepdims=True)
        v = ((x - m) ** 2).mean(caxis, keepdims=True)
        return jax.nn.relu((x - m) * jax.lax.rsqrt(v + 1e-5)
                           * sc.reshape(shape) + bi.reshape(shape))

    def apply(params, x, labels):
        (w, sc, bi) = params[0]
        x = conv(x, w, 2, layout)
        x = brelu(x, sc, bi)
        wd = (1, 2) if layout == 'NHWC' else (2, 3)
        x = jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max,
            (1, 3, 3, 1) if layout == 'NHWC' else (1, 1, 3, 3),
            (1, 2, 2, 1) if layout == 'NHWC' else (1, 1, 2, 2),
            'SAME')
        sh = (1, 1, 1, -1) if layout == 'NHWC' else (1, -1, 1, 1)

        def bn(t, sc, bi):
            mm = t.mean(caxis, keepdims=True)
            vv = ((t - mm) ** 2).mean(caxis, keepdims=True)
            return (t - mm) * jax.lax.rsqrt(vv + 1e-5) \
                * sc.reshape(sh) + bi.reshape(sh)

        for blk, stride in zip(params[1:-1], strides):
            w1, s1, b1 = blk['c1']
            w2, s2, b2 = blk['c2']
            w3, s3, b3 = blk['c3']
            h = brelu(conv(x, w1, 1, layout), s1, b1)
            h = brelu(conv(h, w2, stride, layout), s2, b2)
            h = bn(conv(h, w3, 1, layout), s3, b3)
            if 'proj' in blk:
                wp, sp, bp = blk['proj']
                x = bn(conv(x, wp, stride, layout), sp, bp)
            x = jax.nn.relu(x + h)
        x = x.mean(wd)
        logits = (x @ params[-1]).astype(jnp.float32)
        lse = jax.nn.logsumexp(logits, -1)
        return (lse - jnp.take_along_axis(
            logits, labels[:, None], 1)[:, 0]).mean()

    return params, apply


def bench(layout, B, dtype=jnp.bfloat16, steps=10, trials=3):
    params, apply = make_resnet50(layout, dtype)
    rng = np.random.RandomState(0)
    shape = (B, 224, 224, 3) if layout == 'NHWC' else (B, 3, 224, 224)
    x = jnp.asarray(rng.rand(*shape), dtype)
    y = jnp.asarray(rng.randint(0, 1000, (B,)), jnp.int32)

    vel = jax.tree_util.tree_map(jnp.zeros_like, params)

    @jax.jit
    def step(params, vel, x, y):
        loss, g = jax.value_and_grad(apply)(params, x, y)
        new_vel = jax.tree_util.tree_map(
            lambda v, gg: 0.9 * v + gg.astype(v.dtype), vel, g)
        new_p = jax.tree_util.tree_map(
            lambda p, v: p - jnp.asarray(0.1, p.dtype) * v,
            params, new_vel)
        return loss, new_p, new_vel

    loss, params, vel = step(params, vel, x, y)
    float(loss)
    dt = float('inf')
    for _ in range(trials):
        t0 = time.time()
        for _ in range(steps):
            loss, params, vel = step(params, vel, x, y)
        float(loss)
        dt = min(dt, (time.time() - t0) / steps)
    flops = 3 * 4.1e9 * B
    return {'layout': layout, 'B': B,
            'img_s': round(B / dt, 1), 'ms': round(dt * 1000, 2),
            'mfu': round(flops / dt / 1e12 / V5E_PEAK, 4)}


if __name__ == '__main__':
    for layout in ('NHWC', 'NCHW'):
        for B in (128, 256):
            print(bench(layout, B, steps=8, trials=2), flush=True)
