"""Op micro-benchmark harness.

Reference parity: paddle/fluid/operators/benchmark/op_tester.cc +
tools/test_op_benchmark.sh (the op-benchmark CI gate). Times the hot ops
from the BASELINE list on the current device and emits JSON for regression
comparison: python tools/op_bench.py [--repeat N] [--out FILE].
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def bench_one(make, repeat):
    """Chain `repeat` executions inside one jit via lax.scan and fetch a
    scalar — on tunneled devices block_until_ready alone is not a reliable
    sync, and independent dispatches can overlap or dedupe. Numbers are
    conservative upper bounds (the chain serializes iterations and adds a
    full-output reduction per step)."""
    import jax
    import jax.numpy as jnp
    from jax import lax
    fn, args = make()

    def many(*a):
        def body(carry, i):
            a0 = a[0] + (carry * 1e-30).astype(a[0].dtype)
            out = fn(a0, *a[1:])
            leaf = jax.tree_util.tree_leaves(out)[0]
            # full-output reduction: keeps XLA from dead-code-eliminating
            # any of the op's work
            return carry + jnp.sum(leaf.astype(jnp.float32)), None
        c, _ = lax.scan(body, jnp.asarray(0.0, jnp.float32),
                        jnp.arange(repeat))
        return c

    jfn = jax.jit(many)
    float(jfn(*args))  # compile + warm
    t0 = time.time()
    float(jfn(*args))
    return (time.time() - t0) / repeat * 1e3


def main():
    p = argparse.ArgumentParser()
    p.add_argument('--repeat', type=int, default=20)
    p.add_argument('--out', default=None)
    args = p.parse_args()

    import jax
    import jax.numpy as jnp
    rng = np.random.RandomState(0)

    def t(*shape, dtype=jnp.bfloat16):
        return jnp.asarray(rng.randn(*shape).astype('float32')).astype(dtype)

    def flash():
        from paddle_tpu.ops.pallas.flash_attention import flash_attention_bhld
        return flash_attention_bhld, (t(8, 2048, 128), t(8, 2048, 128),
                                      t(8, 2048, 128))

    def conv():
        f = lambda x, w: jax.lax.conv_general_dilated(
            x, w, (1, 1), 'SAME', dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
        return f, (t(32, 256, 56, 56), t(256, 256, 3, 3))

    def swce():
        def f(lg, lb):
            return -jnp.take_along_axis(jax.nn.log_softmax(lg, axis=-1),
                                        lb[:, None], axis=-1).mean()
        return f, (t(512, 50304, dtype=jnp.float32),
                   jnp.asarray(rng.randint(0, 50304, 512)))

    def adamw():
        def f(p_, g, m1, m2):
            m1n = 0.9 * m1 + 0.1 * g
            m2n = 0.999 * m2 + 0.001 * g * g
            return p_ - 1e-4 * m1n / (jnp.sqrt(m2n) + 1e-8), m1n, m2n
        shape = (125_000_000 // 8, 8)
        return f, tuple(t(*shape, dtype=jnp.float32) for _ in range(4))

    cases = {
        'matmul_4kx4k_bf16':
            lambda: (lambda a, b: a @ b, (t(4096, 4096), t(4096, 4096))),
        'conv2d_256x56x56_3x3': conv,
        'layer_norm_8x2048x4096':
            lambda: (lambda x: jax.nn.standardize(x, axis=-1),
                     (t(8, 2048, 4096),)),
        'softmax_ce_512x50k': swce,
        'flash_attention_8x2048x128': flash,
        'adamw_update_125m': adamw,
    }
    results = {}
    for name, make in cases.items():
        try:
            results[name] = round(bench_one(make, args.repeat), 3)
        except Exception as e:
            results[name] = f"ERROR: {type(e).__name__}"
    payload = {'unit': 'ms', 'results': results,
               'eager_dispatch': eager_dispatch_latency()}
    out = json.dumps(payload, indent=1)
    print(out)
    if args.out:
        with open(args.out, 'w') as f:
            f.write(out)




def eager_dispatch_latency():
    """Eager per-op dispatch overhead vs the jit path (SURVEY 'hard part
    (b)' / VERDICT r2 weak #8 evidence): time a tiny add through the
    eager tape (run_op: python dispatch + tape node + device RTT) vs the
    same op chained inside one jit (the TrainStep-style amortization).
    The delta is what paddle's eager mode pays per op and why the
    performance path compiles whole steps."""
    import jax
    import jax.numpy as jnp
    import paddle_tpu as paddle
    from paddle_tpu.core.tensor import Tensor

    x = Tensor(jnp.ones((8,), jnp.float32))
    y = Tensor(jnp.ones((8,), jnp.float32))
    paddle.add(x, y)                     # warm caches
    n = 200
    t0 = time.time()
    out = x
    for _ in range(n):
        out = paddle.add(out, y)
    float(out.sum())                     # sync the chain
    eager_us = (time.time() - t0) / n * 1e6

    from jax import lax

    @jax.jit
    def chained(a, b):
        def body(c, _):
            return c + b, ()
        c, _ = lax.scan(body, a, None, length=n)
        return c.sum()
    float(chained(x.data, y.data))       # compile
    t0 = time.time()
    for _ in range(5):
        r = chained(x.data, y.data)
    float(r)
    jit_us = (time.time() - t0) / 5 / n * 1e6
    return {'eager_us_per_op': round(eager_us, 1),
            'jit_us_per_op': round(jit_us, 2),
            'overhead_ratio': round(eager_us / max(jit_us, 1e-9), 1)}


if __name__ == '__main__':
    main()
